"""YugabyteDB CI sweep runner.

Counterpart of yugabyte/run-jepsen.py (the reference's python2 CI
orchestrator): sweep workload x nemesis x api combinations, each test
in its own subprocess with a hard wall-clock timeout (a wedged cluster
must not wedge the sweep), keep going on failures, and print a summary
whose exit code is the worst outcome seen.

    python -m jepsen_tpu.suites.yugabyte_runner \
        --workloads bank,set --nemeses none,partition \
        --apis ysql --time-limit 60 --test-timeout 1200
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time


def run_one(workload: str, nemesis: str, api: str, args) -> dict:
    """One test in a subprocess; returns {combo, outcome, secs}."""
    cmd = [sys.executable, "-m", "jepsen_tpu.suites.yugabyte", "test",
           "--workload", workload, "--api", api,
           "--time-limit", str(args.time_limit),
           "--nemesis", nemesis]
    for n in args.nodes.split(","):
        cmd += ["-n", n]
    if args.extra:
        cmd += args.extra.split()
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, timeout=args.test_timeout)
        outcome = {0: "valid", 1: "invalid"}.get(proc.returncode,
                                                 "error")
    except subprocess.TimeoutExpired:
        outcome = "timeout"
    return {"workload": workload, "nemesis": nemesis, "api": api,
            "outcome": outcome, "secs": round(time.time() - t0, 1)}


def main(argv=None) -> int:
    from . import yugabyte

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workloads", default=None,
                   help="comma list (default: the per-API matrix)")
    p.add_argument("--nemeses", default="none,partition",
                   help=f"comma list from {sorted(yugabyte.NEMESES)}")
    p.add_argument("--apis", default="ysql,ycql")
    p.add_argument("--nodes", default="n1,n2,n3,n4,n5")
    p.add_argument("--time-limit", type=int, default=60)
    p.add_argument("--test-timeout", type=int, default=1200,
                   help="hard per-test wall clock (run-jepsen.py's "
                        "TEST_TIMEOUT)")
    p.add_argument("--extra", default=None,
                   help="extra args passed through to each test")
    args = p.parse_args(argv)

    results = []
    for api in args.apis.split(","):
        workloads = (args.workloads.split(",") if args.workloads
                     else sorted(yugabyte.workloads(api=api)))
        for w in workloads:
            for nem in args.nemeses.split(","):
                print(f"=== {api} {w} nemesis={nem}", flush=True)
                results.append(run_one(w, nem, api, args))
                print(f"--- {results[-1]}", flush=True)

    print("\n== sweep summary ==")
    worst = 0
    for r in results:
        print(f"  {r['api']:5s} {r['workload']:12s} "
              f"{r['nemesis']:16s} {r['outcome']:8s} {r['secs']}s")
        worst = max(worst, {"valid": 0, "invalid": 1,
                            "timeout": 2, "error": 2}[r["outcome"]])
    return worst


if __name__ == "__main__":
    sys.exit(main())
