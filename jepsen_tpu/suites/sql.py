"""Generic SQL client: maps workload ops onto SQL transactions.

This is the counterpart of the per-DB client namespaces in the
reference's SQL suites (cockroachdb/src/jepsen/cockroach/client.clj:1-60
conn management + retries; tidb/src/tidb/sql.clj; yugabyte YSQL client),
built on the in-tree wire drivers (drivers.pgwire / drivers.mysql_wire)
instead of jdbc.

One `SQLClient` serves every workload in the shared registry. The op
vocabulary it understands (values may be independent-lifted `[k, v]`):

    read/write/cas          register ops           -> registers table
    txn [[f k v] ...]       elle append / wr mops  -> lists / registers
    read/transfer           bank                   -> accounts
    add/read                set                    -> sets
    read/inc                monotonic              -> counter
    write/read (lifted)     causal-reverse         -> cr
    insert (lifted [a,b])   adya g2                -> g2a / g2b

Error mapping follows drivers.__init__: DBError => the statement/txn was
definitely rejected => type "fail"; DriverError (conn loss/timeout) =>
indeterminate => "info" (reads may safely "fail").
"""

from __future__ import annotations

import random

from .. import client as jclient
from .. import independent
from ..drivers import DBError, DriverError
from ..workloads.comments import TABLE_COUNT

#: Error codes whose outcome is UNKNOWN: the txn may have committed.
#: pg 40003 = statement_completion_unknown (cockroach's "result is
#: ambiguous" commit errors); mysql 2013/2006-style losses arrive as
#: DriverError already.
AMBIGUOUS_SQL = {"40003"}


def resolve(node: str, default_port: int, test: dict) -> tuple[str, int]:
    """Node name -> (host, port). Tests (and NATed clusters) may remap
    via test["db-hosts"] = {node: "host" | ("host", port)}."""
    remap = (test or {}).get("db-hosts", {}).get(node, node)
    if isinstance(remap, (tuple, list)):
        return remap[0], int(remap[1])
    return remap, default_port


class Dialect:
    """SQL syntax + session knobs that differ across engines."""

    name = "generic"
    port = 5432

    def connect(self, node: str, test: dict):
        raise NotImplementedError

    def begin(self) -> str:
        return "BEGIN"

    def commit(self) -> str:
        return "COMMIT"

    def rollback(self) -> str:
        return "ROLLBACK"

    def begin_serializable(self) -> list[str]:
        """Statements opening a SERIALIZABLE txn (the isolation the
        dirty-reads workload runs under, dirty_reads.clj:51-52)."""
        return [self.begin()]

    def upsert(self, table: str, key: int, col: str, val: str) -> str:
        raise NotImplementedError

    def upsert_concat(self, table: str, key: int, val: int) -> str:
        """Append `val` to a comma-joined list column."""
        raise NotImplementedError

    def setup_stmts(self) -> list[str]:
        return [
            "CREATE TABLE IF NOT EXISTS registers"
            " (id BIGINT PRIMARY KEY, val BIGINT)",
            "CREATE TABLE IF NOT EXISTS lists"
            " (id BIGINT PRIMARY KEY, val TEXT)",
            "CREATE TABLE IF NOT EXISTS accounts"
            " (id BIGINT PRIMARY KEY, balance BIGINT)",
            "CREATE TABLE IF NOT EXISTS sets (val BIGINT PRIMARY KEY)",
            "CREATE TABLE IF NOT EXISTS counter"
            " (id BIGINT PRIMARY KEY, val BIGINT)",
            "CREATE TABLE IF NOT EXISTS cr"
            " (k BIGINT, v BIGINT, PRIMARY KEY (k, v))",
            "CREATE TABLE IF NOT EXISTS g2a"
            " (id BIGINT PRIMARY KEY, k BIGINT)",
            "CREATE TABLE IF NOT EXISTS g2b"
            " (id BIGINT PRIMARY KEY, k BIGINT)",
            "CREATE TABLE IF NOT EXISTS dirty"
            " (id BIGINT PRIMARY KEY, x BIGINT NOT NULL)",
        ]


class PGDialect(Dialect):
    """CockroachDB (--insecure trust auth) and YugabyteDB YSQL."""

    name = "pg"

    def __init__(self, port: int = 26257, user: str = "root",
                 database: str = "defaultdb", password: str | None = None,
                 timeout: float = 10.0):
        self.port, self.user, self.database = port, user, database
        self.password, self.timeout = password, timeout

    def connect(self, node: str, test: dict):
        from ..drivers import pgwire
        host, port = resolve(node, self.port, test)
        return pgwire.connect(host, port, user=self.user,
                              database=self.database,
                              password=self.password,
                              timeout=self.timeout)

    def begin_serializable(self):
        return ["BEGIN ISOLATION LEVEL SERIALIZABLE"]

    def upsert(self, table, key, col, val):
        return (f"INSERT INTO {table} (id, {col}) VALUES ({key}, {val}) "
                f"ON CONFLICT (id) DO UPDATE SET {col} = excluded.{col}")

    def upsert_concat(self, table, key, val):
        return (f"INSERT INTO {table} (id, val) VALUES ({key}, '{val}') "
                f"ON CONFLICT (id) DO UPDATE SET val = "
                f"{table}.val || ',' || excluded.val")


class MySQLDialect(Dialect):
    """TiDB (mysql protocol, root/no password by default).
    `session_stmts` run once per connection — the hook tidb's
    option sweeps use for `SET @@tidb_...` knobs (tidb/sql.clj)."""

    name = "mysql"

    def __init__(self, port: int = 4000, user: str = "root",
                 database: str = "test", password: str = "",
                 timeout: float = 10.0,
                 session_stmts: list[str] | None = None):
        self.port, self.user, self.database = port, user, database
        self.password, self.timeout = password, timeout
        self.session_stmts = list(session_stmts or [])

    def connect(self, node: str, test: dict):
        from ..drivers import mysql_wire
        host, port = resolve(node, self.port, test)
        return mysql_wire.connect(host, port, user=self.user,
                                  database=self.database,
                                  password=self.password,
                                  timeout=self.timeout)

    def begin_serializable(self):
        return ["SET TRANSACTION ISOLATION LEVEL SERIALIZABLE",
                self.begin()]

    def upsert(self, table, key, col, val):
        return (f"INSERT INTO {table} (id, {col}) VALUES ({key}, {val}) "
                f"ON DUPLICATE KEY UPDATE {col} = VALUES({col})")

    def upsert_concat(self, table, key, val):
        return (f"INSERT INTO {table} (id, val) VALUES ({key}, '{val}') "
                f"ON DUPLICATE KEY UPDATE val = "
                f"CONCAT(val, ',', VALUES(val))")


def _rows(res) -> list:
    """Normalize driver Result(s) to a row list (pg query returns a
    list of Results, mysql a single Result)."""
    if isinstance(res, list):
        return res[-1].rows if res else []
    return res.rows


class SQLClient(jclient.Client):
    """One connection per worker; lazy connect so a down DB surfaces as
    op-level "info"/"fail", not a setup crash (client.clj's open!/close!
    contract)."""

    def __init__(self, dialect: Dialect, mode: str = "register",
                 accounts: list | None = None, total: int = 100,
                 node: str | None = None,
                 sql_opts: dict | None = None):
        self.dialect = dialect
        self.mode = mode
        self.accounts = accounts if accounts is not None else list(range(8))
        self.total = total
        self.node = node
        # Workload-option knobs (tidb/core.clj:47-79 sweeps these):
        #   read_lock:        None | "FOR UPDATE" (suffix on txn reads)
        #   update_in_place:  bank transfers use server-side arithmetic
        self.sql_opts = dict(sql_opts or {})
        self.conn = None
        self._setup_done = False

    # -- lifecycle -----------------------------------------------------

    def open(self, test, node):
        return SQLClient(self.dialect, self.mode, self.accounts,
                         self.total, node, self.sql_opts)

    def _lock(self) -> str:
        rl = self.sql_opts.get("read_lock")
        return f" {rl}" if rl else ""

    def setup(self, test):
        pass  # schema created lazily on first invoke (first conn wins)

    def _ensure_conn(self, test):
        if self.conn is None:
            self.conn = self.dialect.connect(self.node, test or {})
            for stmt in getattr(self.dialect, "session_stmts", ()):
                self.conn.query(stmt)
        if not self._setup_done:
            for stmt in self.dialect.setup_stmts():
                self.conn.query(stmt)
            if self.mode == "dirty-reads":
                # Seed every row to -1 exactly once, insert-if-absent
                # (dirty_reads.clj:37-43's dotimes insert loop).
                d = self.dialect
                noop = ("ON CONFLICT (id) DO NOTHING" if d.name == "pg"
                        else "ON DUPLICATE KEY UPDATE x = x")
                for i in range(self._dirty_rows()):
                    self.conn.query(
                        f"INSERT INTO dirty (id, x) VALUES ({i}, -1) "
                        f"{noop}")
            if self.mode == "comments":
                # ids shard across several tables so rows land in
                # different ranges (comments.clj:30-40)
                for i in range(TABLE_COUNT):
                    self.conn.query(
                        f"CREATE TABLE IF NOT EXISTS comment_{i}"
                        " (id BIGINT PRIMARY KEY, k BIGINT)")
            if self.mode == "bank":
                # Atomic insert-if-absent seeding: account 0 holds the
                # full total, the rest 0. Concurrent seeders can't reset
                # balances mid-run (the upsert clause never fires a
                # write), so the sum is `total` from the first seed on.
                d = self.dialect
                noop = ("ON CONFLICT (id) DO NOTHING" if d.name == "pg"
                        else "ON DUPLICATE KEY UPDATE balance = balance")
                for a, bal in [(0, self.total)] + [
                        (a, 0) for a in self.accounts if a != 0]:
                    self.conn.query(
                        f"INSERT INTO accounts (id, balance) "
                        f"VALUES ({int(a)}, {bal}) {noop}")
            self._setup_done = True

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def teardown(self, test):
        pass

    # -- op dispatch ---------------------------------------------------

    def invoke(self, test, op):
        f = op.get("f")
        # Reads never wrote anything: indeterminate errors are safe to
        # report as definite failures (client.clj / etcd.clj:118).
        read_only = f in ("read",) and self.mode != "monotonic"
        try:
            self._ensure_conn(test)
            return self._dispatch(op)
        except DBError as e:
            # Most backend errors are definite rejections -> fail; the
            # ambiguous-commit SQLSTATEs mean the txn may have applied
            # -> info for writes (cockroach/client.clj's retry loop
            # makes the same distinction).
            ambiguous = str(e.code) in AMBIGUOUS_SQL and not read_only
            return {**op, "type": "info" if ambiguous else "fail",
                    "error": f"{self.dialect.name}-"
                    f"{e.code}: {e.message[:120]}"}
        except DriverError as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}
        except OSError as e:
            self.close(test)
            return {**op, "type": "fail" if read_only else "info",
                    "error": str(e)[:160]}

    def _dispatch(self, op):
        f = op.get("f")
        mode = self.mode
        # append/wr modes carry [f k v] micro-op lists whatever the op's
        # f is (long-fork uses f="read"/"write" with mop values).
        if f == "txn" or mode in ("append", "wr"):
            return self._txn(op)
        if mode == "bank":
            return self._bank(op)
        if mode == "set":
            return self._set(op)
        if mode == "dirty-reads":
            return self._dirty_reads(op)
        if mode == "table":
            return self._table(op)
        if mode == "comments":
            return self._comments(op)
        if mode == "monotonic":
            return self._monotonic(op)
        if mode in ("sequential", "causal-reverse"):
            return self._causal_reverse(op)
        if f == "insert":
            return self._g2(op)
        return self._register(op)

    # -- register (read/write/cas) -------------------------------------

    def _register(self, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        c, d = self.conn, self.dialect
        if op["f"] == "read":
            rows = _rows(c.query(
                f"SELECT val FROM registers WHERE id = {int(k)}"))
            out = int(rows[0][0]) if rows and rows[0][0] is not None \
                else None
            return {**op, "type": "ok", "value": lift(out)}
        if op["f"] == "write":
            c.query(d.upsert("registers", int(k), "val", str(int(val))))
            return {**op, "type": "ok"}
        if op["f"] == "cas":
            old, new = val
            c.query(d.begin())
            try:
                rows = _rows(c.query(
                    f"SELECT val FROM registers WHERE id = {int(k)}"
                    f"{self._lock()}"))
                cur = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else None
                if cur != old:
                    c.query(d.rollback())
                    return {**op, "type": "fail", "error": "precondition"}
                c.query(f"UPDATE registers SET val = {int(new)} "
                        f"WHERE id = {int(k)}")
                c.query(d.commit())
                return {**op, "type": "ok"}
            except DBError:
                self._try_rollback()
                raise
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    # -- elle txns ([f k v] micro-ops) ---------------------------------

    def _txn(self, op):
        mops = op["value"]
        v = mops
        k0 = None
        if independent.is_tuple(mops):
            k0, mops = mops.key, mops.value
        c, d = self.conn, self.dialect
        c.query(d.begin())
        out = []
        try:
            for mop in mops:
                mf, mk, mv = mop[0], mop[1], mop[2]
                if mf == "append":
                    c.query(d.upsert_concat("lists", int(mk), int(mv)))
                    out.append([mf, mk, mv])
                elif mf == "w":
                    c.query(d.upsert("registers", int(mk), "val",
                                     str(int(mv))))
                    out.append([mf, mk, mv])
                elif mf == "r" and self.mode == "append":
                    rows = _rows(c.query(
                        f"SELECT val FROM lists WHERE id = {int(mk)}"
                        f"{self._lock()}"))
                    txt = rows[0][0] if rows else None
                    vals = [int(x) for x in txt.split(",")] if txt else []
                    out.append([mf, mk, vals])
                elif mf == "r":
                    rows = _rows(c.query(
                        f"SELECT val FROM registers WHERE id = {int(mk)}"
                        f"{self._lock()}"))
                    rv = int(rows[0][0]) if rows and rows[0][0] is not None \
                        else None
                    out.append([mf, mk, rv])
                else:
                    raise DBError("XXMOP", f"unknown micro-op {mf!r}")
            c.query(d.commit())
        except DBError:
            self._try_rollback()
            raise
        new_v = independent.tuple_(k0, out) if k0 is not None else out
        return {**op, "type": "ok", "value": new_v}

    # -- bank ----------------------------------------------------------

    def _bank(self, op):
        c, d = self.conn, self.dialect
        if op["f"] == "read":
            c.query(d.begin())
            try:
                rows = _rows(c.query(
                    "SELECT id, balance FROM accounts"))
                c.query(d.commit())
            except DBError:
                self._try_rollback()
                raise
            return {**op, "type": "ok",
                    "value": {int(r[0]): int(r[1]) for r in rows}}
        if op["f"] == "transfer":
            t = op["value"]
            frm, to, amt = int(t["from"]), int(t["to"]), int(t["amount"])
            c.query(d.begin())
            try:
                rows = _rows(c.query(
                    f"SELECT balance FROM accounts WHERE id = {frm}"
                    f"{self._lock()}"))
                bal = int(rows[0][0]) if rows else 0
                if bal < amt:
                    c.query(d.rollback())
                    return {**op, "type": "fail", "error": "insufficient"}
                if self.sql_opts.get("update_in_place", True):
                    # server-side arithmetic (tidb's update-in-place)
                    c.query(f"UPDATE accounts SET balance = "
                            f"balance - {amt} WHERE id = {frm}")
                    c.query(f"UPDATE accounts SET balance = "
                            f"balance + {amt} WHERE id = {to}")
                else:
                    # client-computed writes: read both, write both —
                    # the lost-update-prone shape the sweep contrasts
                    rows2 = _rows(c.query(
                        f"SELECT balance FROM accounts WHERE id = {to}"
                        f"{self._lock()}"))
                    bal2 = int(rows2[0][0]) if rows2 else 0
                    c.query(f"UPDATE accounts SET balance = {bal - amt} "
                            f"WHERE id = {frm}")
                    c.query(f"UPDATE accounts SET balance = "
                            f"{bal2 + amt} WHERE id = {to}")
                c.query(d.commit())
            except DBError:
                self._try_rollback()
                raise
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    # -- set -----------------------------------------------------------

    def _set(self, op):
        c = self.conn
        if op["f"] == "add":
            c.query(f"INSERT INTO sets (val) VALUES ({int(op['value'])})")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            rows = _rows(c.query("SELECT val FROM sets"))
            return {**op, "type": "ok",
                    "value": sorted(int(r[0]) for r in rows)}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    # -- dirty-reads ---------------------------------------------------

    def _dirty_rows(self) -> int:
        return int(self.sql_opts.get("dirty_rows", 8))

    def _dirty_reads(self, op):
        """galera/percona dirty_reads.clj:48-66: read = full-table scan
        in one serializable txn; write = read every row then set every
        row to the op's unique value, in shuffled order, so competing
        writers deadlock/cert-fail often. `abort_prob` adds deliberate
        rollbacks so a healthy cluster still produces the failed-txn
        values the checker hunts for."""
        c, d = self.conn, self.dialect
        for stmt in d.begin_serializable():
            c.query(stmt)
        try:
            if op["f"] == "read":
                rows = _rows(c.query("SELECT x FROM dirty"))
                c.query(d.commit())
                return {**op, "type": "ok",
                        "value": [int(r[0]) for r in rows]}
            if op["f"] == "write":
                x = int(op["value"])
                order = random.sample(range(self._dirty_rows()),
                                      self._dirty_rows())
                for i in order:
                    c.query(f"SELECT x FROM dirty WHERE id = {i}")
                for i in order:
                    c.query(f"UPDATE dirty SET x = {x} WHERE id = {i}")
                if (random.random()
                        < float(self.sql_opts.get("abort_prob", 0.0))):
                    c.query(d.rollback())
                    return {**op, "type": "fail",
                            "error": "deliberate-abort"}
                c.query(d.commit())
                return {**op, "type": "ok"}
            c.query(d.rollback())
            return {**op, "type": "fail",
                    "error": f"unknown f {op['f']!r}"}
        except DBError:
            self._try_rollback()
            raise

    # -- table (DDL visibility) ----------------------------------------

    #: "relation/table does not exist": mysql 1146 (SQLSTATE 42S02),
    #: pg 42P01 — the anomaly signal for the table workload.
    NO_TABLE_SQL = {"1146", "42S02", "42P01"}
    #: duplicate primary key: mysql 1062 (23000), pg 23505 — expected
    #: noise (every insert targets id 0), not an anomaly.
    DUP_KEY_SQL = {"1062", "23000", "23505"}

    def _table(self, op):
        """tidb/table.clj:23-47: create-table then insert; an insert
        bounced with 'table doesn't exist' AFTER the create was acked
        is the DDL-visibility anomaly the checker hunts."""
        c = self.conn
        if op["f"] == "create-table":
            t = int(op["value"])
            c.query(f"CREATE TABLE IF NOT EXISTS t{t}"
                    " (id BIGINT PRIMARY KEY, val BIGINT)")
            return {**op, "type": "ok"}
        if op["f"] == "insert":
            t, k = op["value"]
            try:
                c.query(f"INSERT INTO t{int(t)} (id) VALUES ({int(k)})")
            except DBError as e:
                code = str(e.code)
                if code in self.NO_TABLE_SQL:
                    return {**op, "type": "fail", "error": "doesnt-exist"}
                if code in self.DUP_KEY_SQL:
                    return {**op, "type": "fail", "error": "duplicate-key"}
                raise
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    # -- comments (strict-serializability visibility) ------------------

    def _comments(self, op):
        """comments.clj:60-81: write = blind insert of a unique id
        into the table its id hashes to; read = one txn scanning every
        table for the key, returning the sorted visible ids."""
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        lift = (lambda x: independent.tuple_(k, x)) \
            if independent.is_tuple(v) else (lambda x: x)
        c, d = self.conn, self.dialect
        if op["f"] == "write":
            id_ = int(val)
            c.query(f"INSERT INTO comment_{id_ % TABLE_COUNT} "
                    f"(id, k) VALUES ({id_}, {int(k)})")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            c.query(d.begin())
            try:
                ids = []
                for i in range(TABLE_COUNT):
                    rows = _rows(c.query(
                        f"SELECT id FROM comment_{i} "
                        f"WHERE k = {int(k)}"))
                    ids += [int(r[0]) for r in rows]
                c.query(d.commit())
            except DBError:
                self._try_rollback()
                raise
            return {**op, "type": "ok", "value": lift(sorted(ids))}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    # -- monotonic -----------------------------------------------------

    def _monotonic(self, op):
        c, d = self.conn, self.dialect
        if op["f"] == "read":
            rows = _rows(c.query("SELECT val FROM counter WHERE id = 0"))
            v = int(rows[0][0]) if rows and rows[0][0] is not None else None
            return {**op, "type": "ok", "value": v}
        if op["f"] == "inc":
            c.query(d.begin())
            try:
                rows = _rows(c.query(
                    "SELECT val FROM counter WHERE id = 0"))
                cur = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else 0
                c.query(d.upsert("counter", 0, "val", str(cur + 1)))
                c.query(d.commit())
            except DBError:
                self._try_rollback()
                raise
            return {**op, "type": "ok", "value": cur + 1}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    # -- causal-reverse / sequential ----------------------------------

    def _causal_reverse(self, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        c = self.conn
        if op["f"] == "write":
            c.query(f"INSERT INTO cr (k, v) VALUES ({int(k)}, {int(val)})")
            return {**op, "type": "ok"}
        if op["f"] == "read":
            rows = _rows(c.query(f"SELECT v FROM cr WHERE k = {int(k)}"))
            out = sorted(int(r[0]) for r in rows)
            return {**op, "type": "ok", "value": independent.tuple_(k, out)
                    if independent.is_tuple(v) else out}
        return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}

    # -- adya g2 -------------------------------------------------------

    def _g2(self, op):
        v = op["value"]
        k, pair = (v.key, v.value) if independent.is_tuple(v) else (0, v)
        a_id, b_id = pair
        c, d = self.conn, self.dialect
        c.query(d.begin())
        try:
            ra = _rows(c.query(f"SELECT id FROM g2a WHERE k = {int(k)}"))
            rb = _rows(c.query(f"SELECT id FROM g2b WHERE k = {int(k)}"))
            if ra or rb:
                c.query(d.rollback())
                return {**op, "type": "fail", "error": "already-present"}
            if a_id is not None:
                c.query(f"INSERT INTO g2a (id, k) "
                        f"VALUES ({int(a_id)}, {int(k)})")
            else:
                c.query(f"INSERT INTO g2b (id, k) "
                        f"VALUES ({int(b_id)}, {int(k)})")
            c.query(d.commit())
        except DBError:
            self._try_rollback()
            raise
        return {**op, "type": "ok"}

    def _try_rollback(self):
        try:
            if self.conn is not None:
                self.conn.query(self.dialect.rollback())
        except (DBError, DriverError, OSError):
            self.close(None)


#: workload name -> SQLClient mode
MODES = {
    "register": "register", "append": "append", "wr": "wr",
    "bank": "bank", "set": "set", "monotonic": "monotonic",
    "sequential": "sequential", "long-fork": "wr", "g2": "g2",
    "dirty-reads": "dirty-reads", "table": "table",
    "comments": "comments",
}


def client_for(dialect: Dialect, workload: str, opts: dict | None = None
               ) -> SQLClient:
    opts = opts or {}
    return SQLClient(dialect, MODES.get(workload, "register"),
                     accounts=opts.get("accounts"),
                     total=opts.get("total-amount", 100),
                     sql_opts=opts.get("sql-opts"))
