"""The etcd suite — the canonical small per-DB suite, and config #1 of
the north-star benchmark (BASELINE.json).

Counterpart of etcd/src/jepsen/etcd.clj: installs etcd from the release
tarball on each node (db, etcd.clj:51-86), drives a compare-and-set
register per key over etcd's HTTP API (client, etcd.clj:93-143), lifts
it over independent keys with 10 threads/key, 300 ops/key, stagger 1/30s
(etcd-test, etcd.clj:154-180), partitions random halves every 10s, and
checks per-key linearizability plus timelines and perf plots.
"""

from __future__ import annotations

import json
import random
import urllib.error
import urllib.parse
import urllib.request

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import independent, nemesis as jnemesis, os_setup
from ..checker import models
from ..control import util as cutil
from . import base_opts, nemesis_cycle

VERSION = "v3.1.5"
DIR = "/opt/etcd"
BINARY = f"{DIR}/etcd"
LOGFILE = f"{DIR}/etcd.log"
PIDFILE = f"{DIR}/etcd.pid"


def node_url(node: str, port: int) -> str:
    return f"http://{node}:{port}"


def peer_url(node: str) -> str:
    return node_url(node, 2380)


def client_url(node: str) -> str:
    return node_url(node, 2379)


def initial_cluster(test: dict) -> str:
    """\"n1=http://n1:2380,n2=...\" (etcd.clj:42-49)."""
    return ",".join(f"{n}={peer_url(n)}" for n in test.get("nodes", []))


class EtcdDB(jdb.DB, jdb.SignalProcess, jdb.LogFiles):
    """Tarball install + daemonized etcd (db, etcd.clj:51-86);
    kill/pause fault protocols (db.clj:22-35) via SignalProcess."""

    process_pattern = "etcd"

    def __init__(self, version: str = VERSION):
        self.version = version

    def _start(self, sess, test, node):
        cutil.start_daemon(
            sess, BINARY,
            "--name", node,
            "--listen-peer-urls", peer_url(node),
            "--listen-client-urls", client_url(node),
            "--advertise-client-urls", client_url(node),
            "--initial-cluster-state", "new",
            "--initial-advertise-peer-urls", peer_url(node),
            "--initial-cluster", initial_cluster(test),
            logfile=LOGFILE, pidfile=PIDFILE, chdir=DIR)

    def setup(self, test, node):
        sess = control.current_session()
        url = (f"https://storage.googleapis.com/etcd/{self.version}/"
               f"etcd-{self.version}-linux-amd64.tar.gz")
        cutil.install_archive(sess.su(), url, DIR)
        self._start(sess.su(), test, node)
        import time
        time.sleep(5)

    def teardown(self, test, node):
        sess = control.current_session().su()
        cutil.stop_daemon(sess, PIDFILE)
        sess.exec("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [LOGFILE]


class EtcdClient(jclient.Client):
    """CAS register over etcd's v2 HTTP API (client, etcd.clj:93-143).
    Ops take independent-lifted values [k, v]."""

    def __init__(self, node: str | None = None, timeout: float = 5.0,
                 quorum: bool = False):
        # quorum=False matches the reference client (etcd.clj:108) — the
        # non-quorum reads are exactly what lets the linearizability
        # checker expose etcd's stale reads. Pass quorum=True for a
        # configuration the checker should find valid.
        self.node = node
        self.timeout = timeout
        self.quorum = quorum

    def open(self, test, node):
        return EtcdClient(node, self.timeout, self.quorum)

    def _url(self, k) -> str:
        return f"{client_url(self.node)}/v2/keys/r{k}"

    def _request(self, url: str, data: dict | None = None,
                 method: str = "GET"):
        body = urllib.parse.urlencode(data).encode() if data else None
        req = urllib.request.Request(url, data=body, method=method)
        if body:
            req.add_header("Content-Type",
                           "application/x-www-form-urlencoded")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def invoke(self, test, op):
        v = op["value"]
        k, val = (v.key, v.value) if independent.is_tuple(v) else (v, None)
        crash = "fail" if op["f"] == "read" else "info"
        try:
            if op["f"] == "read":
                q = "true" if self.quorum else "false"
                out = self._request(self._url(k) + f"?quorum={q}")
                read = out.get("node", {}).get("value")
                read = int(read) if read is not None else None
                return {**op, "type": "ok",
                        "value": independent.tuple_(k, read)}
            if op["f"] == "write":
                self._request(self._url(k), {"value": val}, "PUT")
                return {**op, "type": "ok"}
            if op["f"] == "cas":
                old, new = val
                try:
                    self._request(
                        self._url(k) + f"?prevValue={old}&prevExist=true",
                        {"value": new}, "PUT")
                    return {**op, "type": "ok"}
                except urllib.error.HTTPError as e:
                    if e.code in (404, 412):  # not found / compare failed
                        return {**op, "type": "fail",
                                "error": "precondition"}
                    raise
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return {**op, "type": "fail", "error": "not-found"}
            return {**op, "type": crash, "error": f"http-{e.code}"}
        except OSError as e:  # timeouts, refused connections, DNS
            return {**op, "type": crash, "error": str(e)}


def r(test=None, ctx=None):
    return {"type": "invoke", "f": "read", "value": None}


def w(test=None, ctx=None):
    return {"type": "invoke", "f": "write", "value": random.randint(0, 4)}


def cas(test=None, ctx=None):
    return {"type": "invoke", "f": "cas",
            "value": [random.randint(0, 4), random.randint(0, 4)]}


def workloads(opts: dict | None = None) -> dict:
    """Registry-uniform view: etcd is the single canonical CAS-register
    suite (etcd.clj:149-180)."""
    opts = opts or {}

    def register():
        t = etcd_test(opts)
        return {"generator": t["generator"], "checker": t["checker"]}

    return {"register": register}


def etcd_test(opts: dict | None = None) -> dict:
    """Full test map (etcd-test, etcd.clj:150-180)."""
    opts = base_opts(**(opts or {}))
    ops_per_key = opts.get("ops-per-key", 300)
    threads_per_key = opts.get("threads-per-key", 10)
    db = EtcdDB(opts.get("version", VERSION))
    interval = opts.get("nemesis-interval", 10)
    nemesis = jnemesis.partition_random_halves()
    nemesis_gen = nemesis_cycle(interval)
    if opts.get("faults"):
        from ..nemesis import combined as ncombined
        pkg = ncombined.nemesis_package(db, interval,
                                        faults=opts["faults"])
        nemesis = pkg["nemesis"]
        if pkg.get("generator") is not None:
            nemesis_gen = pkg["generator"]
    test = {
        "name": "etcd",
        "os": os_setup.debian(),
        "db": db,
        "client": EtcdClient(quorum=bool(opts.get("quorum", False))),
        "nemesis": nemesis,
        "checker": jchecker.compose({
            "perf": jchecker.perf_checker(),
            "indep": independent.checker(jchecker.compose({
                "timeline": jchecker.timeline_checker(),
                "linear": jchecker.linearizable(models.cas_register()),
            })),
        }),
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(
                independent.concurrent_generator(
                    threads_per_key, range(100000),
                    lambda k: gen.limit(
                        ops_per_key,
                        gen.stagger(1 / 30, gen.mix([r, w, cas])))),
                nemesis_gen)),
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    """CLI entry: test / analyze / serve (etcd.clj:182-191)."""
    return jcli.run_cli(lambda tmap, args: etcd_test(tmap), argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
