package jepsen.tpu.hazelcast;

import com.hazelcast.core.EntryView;
import com.hazelcast.map.merge.MapMergePolicy;
import com.hazelcast.nio.ObjectDataInput;
import com.hazelcast.nio.ObjectDataOutput;
import com.hazelcast.nio.serialization.DataSerializable;

import java.io.IOException;
import java.util.SortedSet;
import java.util.TreeSet;

/**
 * Split-brain merge policy for the hazelcast suite's CRDT-style set
 * workload: when partitions heal, reconcile the two replicas of a
 * long[]-encoded set by taking their union, so no acknowledged add is
 * dropped by the merge (the anomaly the default policies exhibit and
 * the set checker exists to catch). Installed on the server classpath
 * by the suite's DB setup; counterpart of the server extension the
 * reference ships with its hazelcast suite.
 */
public class SetUnionMergePolicy implements MapMergePolicy, DataSerializable {

  private static long[] values(EntryView view) {
    Object v = view == null ? null : view.getValue();
    return v == null ? new long[0] : (long[]) v;
  }

  @Override
  public Object merge(String mapName, EntryView merging, EntryView existing) {
    SortedSet<Long> union = new TreeSet<Long>();
    for (long x : values(merging)) {
      union.add(x);
    }
    for (long x : values(existing)) {
      union.add(x);
    }
    long[] out = new long[union.size()];
    int i = 0;
    for (long x : union) {
      out[i++] = x;
    }
    return out;
  }

  @Override
  public void writeData(ObjectDataOutput out) throws IOException {
    // stateless: nothing to serialize
  }

  @Override
  public void readData(ObjectDataInput in) throws IOException {
    // stateless: nothing to deserialize
  }
}
