"""Postgres-RDS suite.

Counterpart of postgres-rds/src/jepsen/postgres_rds.clj: the database
is an EXTERNAL managed endpoint (nothing to install — RDS provisioning
happens out-of-band), so the DB protocol is a noop and every client
connects to the configured endpoint. Workloads are the SQL matrix over
the in-tree pg-wire driver.

    python -m jepsen_tpu.suites.postgres_rds test \
        --endpoint mydb.abc123.rds.amazonaws.com --user jepsen ...
"""

from __future__ import annotations

from .. import cli as jcli
from .. import db as jdb
from .. import nemesis as jnemesis
from . import base_opts, sql, standard_workloads, suite_test


class ExternalDB(jdb.DB):
    """No setup/teardown: the endpoint outlives the test
    (postgres-rds's db is likewise a stub)."""

    def setup(self, test, node):
        pass

    def teardown(self, test, node):
        pass


def workloads(opts: dict | None = None) -> dict:
    std = standard_workloads(opts)
    return {k: std[k] for k in
            ("register", "bank", "set", "append", "wr", "g2")}


def default_client(workload: str, opts: dict):
    opts = opts or {}
    dialect = sql.PGDialect(
        port=int(opts.get("port", 5432)),
        user=opts.get("user", "postgres"),
        database=opts.get("database", "postgres"),
        password=opts.get("password"))
    return sql.client_for(dialect, workload, opts)


def postgres_rds_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    # All "nodes" are the single external endpoint when given.
    if opts.get("endpoint"):
        opts["nodes"] = [opts["endpoint"]]
    wname = opts.get("workload", "bank")
    return suite_test(
        "postgres-rds", wname, opts, workloads(opts),
        db=ExternalDB(),
        client=opts.get("client") or default_client(wname, opts),
        # no SSH access to RDS: the only faults available are
        # client-side (the reference suite likewise runs nemesis-free)
        nemesis=jnemesis.noop())


def main(argv=None) -> int:
    from . import resolve_workload

    def opt_fn(p):
        p.add_argument("--workload", default=None,
                       choices=sorted(workloads()))
        p.add_argument("--endpoint", default=None,
                       help="RDS endpoint hostname")
        p.add_argument("--user", default="postgres")
        # --password is taken by the standard SSH options
        p.add_argument("--db-password", dest="db_password",
                       default=None)
        p.add_argument("--database", default="postgres")

    def opts_from(tmap, args):
        out = dict(tmap)
        for k in ("endpoint", "user", "database"):
            v = getattr(args, k, None)
            if v is not None:
                out[k] = v
        if getattr(args, "db_password", None) is not None:
            out["password"] = args.db_password
        out["workload"] = resolve_workload(args, tmap, "bank")
        return out

    return jcli.run_cli(
        lambda tmap, args: postgres_rds_test(opts_from(tmap, args)),
        name="postgres-rds", opt_fn=opt_fn, argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
