"""MongoDB-on-RocksDB suite.

Counterpart of mongodb-rocks/src/jepsen/mongodb_rocks.clj (169 LoC):
the mongodb suite with the rocksdb storage engine selected — the
variant that exposed RocksDB-specific write-loss behavior.
"""

from __future__ import annotations

from .. import cli as jcli
from . import mongodb


def mongodb_rocks_test(opts: dict | None = None) -> dict:
    return mongodb.mongodb_test(opts, name="mongodb-rocks",
                                storage_engine="rocksdb")


def workloads(opts: dict | None = None) -> dict:
    return mongodb.workloads(opts)


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: mongodb_rocks_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "register")}),
        name="mongodb-rocks",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
