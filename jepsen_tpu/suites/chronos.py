"""Chronos suite.

Counterpart of chronos/src/jepsen/chronos/ (750 LoC): Chronos job
scheduling over Mesos + ZooKeeper — jobs are scheduled via Chronos's
HTTP API and the checker verifies every job ran on time by reading
run-marker files off the nodes. The HTTP scheduling client is real
(urllib); the mesos/zk stack installs are the DB layer.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis, os_setup
from . import base_opts, nemesis_cycle
from .sql import resolve


class ChronosDB(jdb.DB, jdb.LogFiles):
    """zookeeper + mesos master/agent + chronos via apt
    (chronos/src/jepsen/chronos.clj's setup)."""

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y",
                  "zookeeperd", "mesos", "chronos")
        nodes = test.get("nodes", [node])
        zk = ",".join(f"{n}:2181" for n in nodes)
        sess.exec("sh", "-c",
                  f"echo zk://{zk}/mesos > /etc/mesos/zk")
        sess.exec("service", "zookeeper", "restart")
        sess.exec("service", "mesos-master", "restart")
        sess.exec("service", "mesos-slave", "restart")
        sess.exec("service", "chronos", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        for svc in ("chronos", "mesos-slave", "mesos-master",
                    "zookeeper"):
            sess.exec_ok("service", svc, "stop")

    def log_files(self, test, node):
        return ["/var/log/chronos/chronos.log",
                "/var/log/mesos/mesos-master.INFO"]


class ChronosClient(jclient.Client):
    """Schedules run-once jobs over the HTTP API; each job touches a
    marker file the final read collects (chronos.clj's add-job! /
    read-runs shape)."""

    def __init__(self, port: int = 4400, node: str | None = None,
                 timeout: float = 10.0):
        self.port = port
        self.node = node
        self.timeout = timeout

    def open(self, test, node):
        return ChronosClient(self.port, node, self.timeout)

    def invoke(self, test, op):
        crash = "fail" if op["f"] == "read" else "info"
        host, port = resolve(self.node, self.port, test or {})
        try:
            if op["f"] == "add":
                j = op["value"]
                body = json.dumps({
                    "name": f"jepsen-{j}",
                    "command": f"touch /tmp/chronos-run-{j}",
                    "schedule": "R1//PT10S", "epsilon": "PT30S",
                    "owner": "jepsen@localhost",
                }).encode()
                req = urllib.request.Request(
                    f"http://{host}:{port}/scheduler/iso8601",
                    data=body, method="POST",
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=self.timeout).read()
                return {**op, "type": "ok"}
            if op["f"] == "read":
                # collect run markers from every node over SSH
                runs = set()
                for n in test.get("nodes", []):
                    sess = control.session(test, n)
                    try:
                        out = sess.exec_raw(
                            "ls /tmp/ | grep chronos-run- || true").out
                        for line in out.split():
                            runs.add(int(line.rsplit("-", 1)[-1]))
                    finally:
                        sess.disconnect()
                return {**op, "type": "ok", "value": sorted(runs)}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except urllib.error.HTTPError as e:
            return {**op, "type": "fail" if 400 <= e.code < 500
                    else crash, "error": f"http-{e.code}"}
        except OSError as e:
            return {**op, "type": crash, "error": str(e)[:160]}


def generator():
    import itertools
    counter = itertools.count()

    def add(test=None, ctx=None):
        return {"type": "invoke", "f": "add", "value": next(counter)}

    return gen.stagger(1.0, add)


def final_read():
    return gen.clients(gen.until_ok(gen.repeat_gen({"f": "read"})))


def workloads(opts: dict | None = None) -> dict:
    return {"jobs": lambda: {
        "generator": generator(),
        "checker": jchecker.set_checker()}}


def chronos_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    test = {
        "name": "chronos jobs",
        "os": os_setup.debian(),
        "db": ChronosDB(),
        "client": opts.get("client") or ChronosClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": jchecker.set_checker(),
        "generator": gen.phases(
            gen.time_limit(
                opts.get("time-limit", 60),
                gen.clients(generator(),
                            nemesis_cycle(
                                opts.get("nemesis-interval", 10)))),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            final_read()),
        "workload": "jobs",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: chronos_test(tmap),
                        name="chronos", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
