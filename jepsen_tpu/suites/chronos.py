"""Chronos suite.

Counterpart of chronos/src/jepsen/chronos/ (750 LoC): Chronos job
scheduling over Mesos + ZooKeeper — jobs are scheduled via Chronos's
HTTP API and the checker verifies every job ran on time by reading
run-marker files off the nodes. The HTTP scheduling client is real
(urllib); the mesos/zk stack installs are the DB layer.
"""

from __future__ import annotations

import json
import re as _re
import urllib.error
import urllib.request

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis, os_setup
from . import base_opts, nemesis_cycle
from . import chronos_checker
from .sql import resolve


class ChronosDB(jdb.DB, jdb.LogFiles):
    """zookeeper + mesos master/agent + chronos via apt
    (chronos/src/jepsen/chronos.clj's setup)."""

    def setup(self, test, node):
        sess = control.current_session().su()
        sess.exec("apt-get", "install", "-y",
                  "zookeeperd", "mesos", "chronos")
        # fresh run-log dir: stale files from a previous test on the
        # same node would read as this test's runs (job names restart
        # at 1), masking real misses; legacy markers likewise
        sess.exec("rm", "-rf", JOB_DIR)
        sess.exec("sh", "-c", "rm -f /tmp/chronos-run-*")
        sess.exec("mkdir", "-p", JOB_DIR)
        nodes = test.get("nodes", [node])
        zk = ",".join(f"{n}:2181" for n in nodes)
        sess.exec("sh", "-c",
                  f"echo zk://{zk}/mesos > /etc/mesos/zk")
        sess.exec("service", "zookeeper", "restart")
        sess.exec("service", "mesos-master", "restart")
        sess.exec("service", "mesos-slave", "restart")
        sess.exec("service", "chronos", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        for svc in ("chronos", "mesos-slave", "mesos-master",
                    "zookeeper"):
            sess.exec_ok("service", svc, "stop")

    def log_files(self, test, node):
        return ["/var/log/chronos/chronos.log",
                "/var/log/mesos/mesos-master.INFO"]


JOB_DIR = "/tmp/chronos-test"


def job_schedule_str(job: dict) -> str:
    """ISO8601 repeating interval (chronos.clj:101-106):
    R<count>/<start>/PT<interval>S."""
    from datetime import datetime, timezone
    start = chronos_checker.parse_time(job["start"])
    iso = datetime.fromtimestamp(start, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
    return f"R{job['count']}/{iso}/PT{job['interval']}S"


def job_command(job: dict) -> str:
    """Each run logs its job name, start and end times into a fresh
    tempfile the final read collects (chronos.clj:108-116)."""
    return (f"MEW=$(mktemp -p {JOB_DIR}); "
            f"echo \"{job['name']}\" >> $MEW; "
            "date -u -Ins >> $MEW; "
            f"sleep {job['duration']}; "
            "date -u -Ins >> $MEW;")


def parse_run_file(node: str, text: str) -> dict:
    """name / start / end lines -> a run map (chronos.clj:152-159);
    a file with no end line is an incomplete run."""
    lines = text.strip().split("\n")
    try:
        name = int(lines[0]) if lines and lines[0].strip() else None
    except ValueError:
        # Partial write / stray file: a run with name None can't match
        # any job, so the checker surfaces it under "unparseable"
        # instead of the until-ok final read raise-retrying forever.
        name = None
    return {"node": node,
            "name": name,
            "start": _ts(lines[1]) if len(lines) > 1 else None,
            "end": _ts(lines[2]) if len(lines) > 2 else None}


_TS_RE = _re.compile(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}")


def _ts(line: str) -> str | None:
    """A truncated `date -u -Ins` line (partial write) is no timestamp:
    return None so the run counts as incomplete, not a checker crash."""
    s = line.strip()
    return s if _TS_RE.match(s) else None


class ChronosClient(jclient.Client):
    """Schedules repeating jobs over the HTTP API; each run logs a
    marker file the final read collects and parses (chronos.clj's
    add-job! / read-runs)."""

    def __init__(self, port: int = 4400, node: str | None = None,
                 timeout: float = 10.0):
        self.port = port
        self.node = node
        self.timeout = timeout

    def open(self, test, node):
        return ChronosClient(self.port, node, self.timeout)

    def read_runs(self, test) -> list[dict]:
        """All runs from all nodes (chronos.clj:161-170)."""
        runs = []
        for n in test.get("nodes", []):
            sess = control.session(test, n)
            try:
                # \036 (ASCII RS): octal escapes are POSIX printf;
                # \x1e is a bashism dash would emit literally
                out = sess.exec_raw(
                    f"for f in {JOB_DIR}/*; do "
                    "[ -f \"$f\" ] || continue; "
                    "cat \"$f\"; printf '\\036'; done").out
                for rec in out.split("\x1e"):
                    if rec.strip():
                        runs.append(parse_run_file(n, rec))
            finally:
                sess.disconnect()
        return runs

    def invoke(self, test, op):
        crash = "fail" if op["f"] == "read" else "info"
        host, port = resolve(self.node, self.port, test or {})
        try:
            if op["f"] in ("add", "add-job"):
                if op["f"] == "add":   # legacy run-once set workload
                    j = op["value"]
                    body = {"name": f"jepsen-{j}",
                            "command": f"touch /tmp/chronos-run-{j}",
                            "schedule": "R1//PT10S", "epsilon": "PT30S"}
                else:
                    job = op["value"]
                    body = {"name": str(job["name"]),
                            "command": job_command(job),
                            "schedule": job_schedule_str(job),
                            "scheduleTimeZone": "UTC",
                            "epsilon": f"PT{job['epsilon']}S",
                            "mem": 1, "disk": 1, "cpus": 0.001,
                            "async": False}
                body["owner"] = "jepsen@localhost"
                req = urllib.request.Request(
                    f"http://{host}:{port}/scheduler/iso8601",
                    data=json.dumps(body).encode(), method="POST",
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=self.timeout).read()
                return {**op, "type": "ok"}
            if op["f"] == "read":
                if (op.get("value") or {}) == "markers" or \
                        test.get("workload") == "jobs":
                    # legacy set workload: marker filenames only
                    runs = set()
                    for n in test.get("nodes", []):
                        sess = control.session(test, n)
                        try:
                            out = sess.exec_raw(
                                "ls /tmp/ | grep chronos-run- "
                                "|| true").out
                            for line in out.split():
                                runs.add(int(line.rsplit("-", 1)[-1]))
                        finally:
                            sess.disconnect()
                    return {**op, "type": "ok", "value": sorted(runs)}
                return {**op, "type": "ok",
                        "value": self.read_runs(test)}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except urllib.error.HTTPError as e:
            return {**op, "type": "fail" if 400 <= e.code < 500
                    else crash, "error": f"http-{e.code}"}
        except OSError as e:
            return {**op, "type": crash, "error": str(e)[:160]}


def generator():
    import itertools
    counter = itertools.count()

    def add(test=None, ctx=None):
        return {"type": "invoke", "f": "add", "value": next(counter)}

    return gen.stagger(1.0, add)


def add_job_generator(head_start: float = 10.0):
    """Random repeating jobs (chronos.clj:194-219): interval always
    exceeds duration + epsilon + forgiveness so one job's runs never
    overlap — the premise of the disjoint target windows the checker
    matches against."""
    import itertools
    import random
    import time as _time

    counter = itertools.count(1)

    def add(test=None, ctx=None):
        duration = random.randint(0, 9)
        epsilon = 10 + random.randint(0, 19)
        interval = (1 + duration + epsilon
                    + int(chronos_checker.EPSILON_FORGIVENESS)
                    + random.randint(0, 29))
        return {"type": "invoke", "f": "add-job",
                "value": {"name": next(counter),
                          "start": _time.time() + head_start,
                          "count": 1 + random.randint(0, 98),
                          "duration": duration,
                          "epsilon": epsilon,
                          "interval": interval}}

    return gen.stagger(30.0, add)


def final_read():
    return gen.clients(gen.until_ok(gen.repeat_gen({"f": "read"})))


def workloads(opts: dict | None = None) -> dict:
    return {
        "jobs": lambda: {
            "generator": generator(),
            "checker": jchecker.set_checker()},
        "schedule": lambda: {
            "generator": add_job_generator(),
            "checker": chronos_checker.ChronosChecker()},
    }


def chronos_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wl = opts.get("workload", "schedule")
    spec = workloads(opts)[wl]()
    test = {
        "name": f"chronos {wl}",
        "os": os_setup.debian(),
        "db": ChronosDB(),
        "client": opts.get("client") or ChronosClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": spec["checker"],
        "generator": gen.phases(
            gen.time_limit(
                opts.get("time-limit", 60),
                gen.clients(spec["generator"],
                            nemesis_cycle(
                                opts.get("nemesis-interval", 10)))),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            final_read()),
        "workload": wl,
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: chronos_test(
            {**tmap,
             "workload": resolve_workload(args, tmap, "schedule")}),
        name="chronos",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
