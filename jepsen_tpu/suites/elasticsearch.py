"""Elasticsearch suite.

Counterpart of elasticsearch/src/jepsen/elasticsearch (862 LoC): a
deb-installed ES cluster and the set workload that exposed its
dirty-window data loss — documents indexed during partitions, a final
refresh + search that must see every acknowledged doc. Client is plain
HTTP (the reference goes through the native transport client).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis, os_setup
from ..workloads import set_workload
from . import base_opts, nemesis_cycle
from .sql import resolve

VERSION = "1.5.0"
LOGFILE = "/var/log/elasticsearch/elasticsearch.log"
INDEX = "jepsen"


class ElasticsearchDB(jdb.DB, jdb.LogFiles):
    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://download.elastic.co/elasticsearch/elasticsearch/"
               f"elasticsearch-{self.version}.deb")
        sess.exec("sh", "-c",
                  f"wget -q -O /tmp/es.deb {url} && "
                  f"dpkg -i --force-confnew /tmp/es.deb")
        nodes = test.get("nodes", [node])
        hosts = json.dumps([f"{n}:9300" for n in nodes])
        cfg = "\n".join([
            f"cluster.name: jepsen",
            f"node.name: {node}",
            f"network.host: {node}",
            f"discovery.zen.ping.unicast.hosts: {hosts}",
            f"discovery.zen.minimum_master_nodes: "
            f"{len(nodes) // 2 + 1}",
        ])
        sess.exec("sh", "-c",
                  f"cat > /etc/elasticsearch/elasticsearch.yml "
                  f"<< 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "elasticsearch", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "elasticsearch", "stop")
        sess.exec("rm", "-rf", "/var/lib/elasticsearch/jepsen")

    def log_files(self, test, node):
        return [LOGFILE]


class ESClient(jclient.Client):
    """Set ops over the document API: add = index doc with id=value
    (write concern: wait_for_active_shards), read = refresh + match_all
    search."""

    def __init__(self, port: int = 9200, node: str | None = None,
                 timeout: float = 5.0):
        self.port = port
        self.node = node
        self.timeout = timeout

    def open(self, test, node):
        return ESClient(self.port, node, self.timeout)

    def _url(self, test, path: str) -> str:
        host, port = resolve(self.node, self.port, test or {})
        return f"http://{host}:{port}{path}"

    def _request(self, test, path: str, body: dict | None = None,
                 method: str = "GET") -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._url(test, path), data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read() or b"{}")

    def invoke(self, test, op):
        crash = "fail" if op["f"] == "read" else "info"
        try:
            if op["f"] == "add":
                v = int(op["value"])
                self._request(test, f"/{INDEX}/doc/{v}?op_type=create",
                              {"value": v}, "PUT")
                return {**op, "type": "ok"}
            if op["f"] == "read":
                self._request(test, f"/{INDEX}/_refresh", None, "POST")
                out = self._request(
                    test, f"/{INDEX}/_search",
                    {"size": 100000,
                     "query": {"match_all": {}}}, "POST")
                hits = out.get("hits", {}).get("hits", [])
                return {**op, "type": "ok",
                        "value": sorted(int(h["_id"]) for h in hits)}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except urllib.error.HTTPError as e:
            if e.code == 409:   # op_type=create conflict: definite
                return {**op, "type": "fail", "error": "conflict"}
            if 400 <= e.code < 500:
                return {**op, "type": "fail", "error": f"http-{e.code}"}
            return {**op, "type": crash, "error": f"http-{e.code}"}
        except OSError as e:
            return {**op, "type": crash, "error": str(e)[:160]}


DIRTY_INDEX = "dirty_read"


class DirtyReadClient(ESClient):
    """Dirty-read ops (dirty_read.clj:32-104): write = index doc id=v,
    read = GET by id (found -> ok, absent -> fail), refresh = POST
    _refresh retried until every shard reports success, strong-read =
    refresh-backed match_all search returning the full id set."""

    def open(self, test, node):
        return DirtyReadClient(self.port, node, self.timeout)

    def invoke(self, test, op):
        crash = "fail" if op["f"] in ("read", "strong-read") else "info"
        try:
            if op["f"] == "write":
                v = int(op["value"])
                self._request(test, f"/{DIRTY_INDEX}/doc/{v}",
                              {"id": v}, "PUT")
                return {**op, "type": "ok"}
            if op["f"] == "read":
                try:
                    out = self._request(
                        test, f"/{DIRTY_INDEX}/doc/{int(op['value'])}")
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        return {**op, "type": "fail", "error": "absent"}
                    raise
                return {**op, "type": "ok" if out.get("found", True)
                        else "fail"}
            if op["f"] == "refresh":
                # all shards must acknowledge, else the strong read can
                # miss committed docs; paced retries span the post-
                # nemesis heal window (dirty_read.clj:60-82 retries
                # under a 120s op timeout)
                import time as _time
                for i in range(60):
                    out = self._request(test, f"/{DIRTY_INDEX}/_refresh",
                                        None, "POST")
                    sh = out.get("_shards") or {}
                    if sh.get("total", 0) == sh.get("successful", 0):
                        return {**op, "type": "ok"}
                    if i < 59:
                        _time.sleep(float(test.get(
                            "refresh-retry-interval", 2.0)))
                return {**op, "type": "info", "error": "refresh-partial"}
            if op["f"] == "strong-read":
                out = self._request(
                    test, f"/{DIRTY_INDEX}/_search",
                    {"size": 100000, "query": {"match_all": {}}}, "POST")
                hits = out.get("hits", {}).get("hits", [])
                return {**op, "type": "ok",
                        "value": sorted(int(h["_id"]) for h in hits)}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except urllib.error.HTTPError as e:
            if 400 <= e.code < 500:
                return {**op, "type": "fail", "error": f"http-{e.code}"}
            return {**op, "type": crash, "error": f"http-{e.code}"}
        except OSError as e:
            return {**op, "type": crash, "error": str(e)[:160]}


class RWGen(gen.Generator):
    """dirty_read.clj:160-189: the first `w` threads write an
    ever-incrementing value, recording the in-flight write per node;
    the rest read their node's most recent in-flight write — aiming to
    observe an uncommitted write in the instant before a crash. Pure:
    the counter and in-flight vector advance in `update` on each
    dispatched write invocation."""

    __slots__ = ("w", "next_write", "in_flight")

    def __init__(self, w: int, next_write: int = 0,
                 in_flight: tuple = ()):
        self.w = w
        self.next_write = next_write
        self.in_flight = in_flight

    def _nodes(self, test) -> int:
        return max(1, len(test.get("nodes") or ()))

    @staticmethod
    def _node_of(ctx, p, n_nodes: int) -> int:
        """Node index for a process: clients bind to nodes by THREAD
        (interpreter nodes[wid % len(nodes)]), and a crashed process
        retires to p + concurrency — so the thread, not the raw
        process id, decides which node an op lands on."""
        t = ctx.process_to_thread(p)
        return t % n_nodes if isinstance(t, int) else 0

    def op(self, test, ctx):
        p = ctx.some_free_process()
        if p is None:
            return (gen.PENDING, self)
        t = ctx.process_to_thread(p)
        n_nodes = self._nodes(test)
        if isinstance(t, int) and t < self.w:
            o = {"type": "invoke", "f": "write", "value": self.next_write,
                 "process": p, "time": ctx.time}
        else:
            inf = self.in_flight or (0,) * n_nodes
            o = {"type": "invoke", "f": "read",
                 "value": inf[self._node_of(ctx, p, n_nodes)],
                 "process": p, "time": ctx.time}
        return (o, self)

    def update(self, test, ctx, event):
        if event.get("type") == "invoke" and event.get("f") == "write":
            n_nodes = self._nodes(test)
            inf = list(self.in_flight or (0,) * n_nodes)
            n = self._node_of(ctx, event.get("process"), n_nodes)
            inf[n] = event["value"]
            return RWGen(self.w, self.next_write + 1, tuple(inf))
        return self


class DirtyReadChecker(jchecker.Checker):
    """dirty_read.clj:106-156: a read is dirty when its value appears
    in NO final strong read (it observed a write that never committed);
    an acknowledged write is lost when no strong read contains it; the
    per-node strong reads must also agree with each other."""

    def check(self, test, history, opts):
        ok = [o for o in history if o.get("type") == "ok"]
        writes = {o["value"] for o in ok if o.get("f") == "write"}
        reads = {o["value"] for o in ok if o.get("f") == "read"}
        strong = [set(o["value"] or ()) for o in ok
                  if o.get("f") == "strong-read"]
        if not strong:
            return {"valid?": "unknown", "error": "no strong reads"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        not_on_all = on_some - on_all
        dirty = reads - on_some
        lost = writes - on_some
        some_lost = writes - on_all
        agree = on_all == on_some
        return {
            "valid?": agree and not dirty and not lost,
            "nodes-agree?": agree,
            "strong-read-count": len(strong),
            "read-count": len(reads),
            "on-all-count": len(on_all),
            "on-some-count": len(on_some),
            "unchecked-count": len(on_some - reads),
            "not-on-all-count": len(not_on_all),
            "not-on-all": sorted(not_on_all),
            "dirty-count": len(dirty),
            "dirty": sorted(dirty),
            "lost-count": len(lost),
            "lost": sorted(lost),
            "some-lost-count": len(some_lost),
            "some-lost": sorted(some_lost),
        }


def dirty_read_gen(opts: dict) -> gen.Generator:
    """The reference's phase structure (dirty_read.clj:208-222):
    staggered writes/reads under the nemesis, stop, a per-client
    refresh, quiescence, then a per-client strong read."""
    conc = int(opts.get("concurrency", 6) or 6)
    writers = max(1, conc // 3)
    return gen.phases(
        gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(gen.stagger(0.1, RWGen(writers)),
                        nemesis_cycle(opts.get("nemesis-interval", 10)))),
        gen.nemesis(gen.once({"type": "info", "f": "stop"})),
        gen.clients(gen.each_thread(gen.once({"f": "refresh"}))),
        gen.log_gen("Waiting for quiescence"),
        gen.sleep(opts.get("quiesce", 10)),
        gen.clients(gen.each_thread(gen.once({"f": "strong-read"}))),
    )


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    return {
        "set": lambda: set_workload.test(n=opts.get("set-size", 500)),
        "dirty-read": lambda: {
            "client": DirtyReadClient(),
            "generator": dirty_read_gen(opts),
            "checker": DirtyReadChecker(),
            "full-generator": True,   # phases carry their own nemesis
        },
    }


def elasticsearch_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    name = opts.get("workload", "set")
    wl = workloads(opts)[name]()
    if wl.get("full-generator"):
        generator = wl["generator"]    # phases carry their own nemesis
    else:
        generator = gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(wl["generator"],
                        nemesis_cycle(opts.get("nemesis-interval", 10))))
    test = {
        "name": f"elasticsearch {name}",
        "os": os_setup.debian(),
        "db": ElasticsearchDB(opts.get("version", VERSION)),
        "client": opts.get("client") or wl.get("client") or ESClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": jchecker.compose({
            name: wl["checker"],
            "perf": jchecker.perf_checker(),
        }),
        "generator": generator,
        "workload": name,
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    from . import resolve_workload
    return jcli.run_cli(
        lambda tmap, args: elasticsearch_test(
            {**tmap, "workload": resolve_workload(args, tmap, "set")}),
        name="elasticsearch",
        opt_fn=lambda p: p.add_argument(
            "--workload", default=None, choices=sorted(workloads())),
        argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
