"""Elasticsearch suite.

Counterpart of elasticsearch/src/jepsen/elasticsearch (862 LoC): a
deb-installed ES cluster and the set workload that exposed its
dirty-window data loss — documents indexed during partitions, a final
refresh + search that must see every acknowledged doc. Client is plain
HTTP (the reference goes through the native transport client).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .. import checker as jchecker
from .. import cli as jcli
from .. import client as jclient
from .. import control
from .. import db as jdb
from .. import generator as gen
from .. import nemesis as jnemesis, os_setup
from ..workloads import set_workload
from . import base_opts, nemesis_cycle
from .sql import resolve

VERSION = "1.5.0"
LOGFILE = "/var/log/elasticsearch/elasticsearch.log"
INDEX = "jepsen"


class ElasticsearchDB(jdb.DB, jdb.LogFiles):
    def __init__(self, version: str = VERSION):
        self.version = version

    def setup(self, test, node):
        sess = control.current_session().su()
        url = (f"https://download.elastic.co/elasticsearch/elasticsearch/"
               f"elasticsearch-{self.version}.deb")
        sess.exec("sh", "-c",
                  f"wget -q -O /tmp/es.deb {url} && "
                  f"dpkg -i --force-confnew /tmp/es.deb")
        nodes = test.get("nodes", [node])
        hosts = json.dumps([f"{n}:9300" for n in nodes])
        cfg = "\n".join([
            f"cluster.name: jepsen",
            f"node.name: {node}",
            f"network.host: {node}",
            f"discovery.zen.ping.unicast.hosts: {hosts}",
            f"discovery.zen.minimum_master_nodes: "
            f"{len(nodes) // 2 + 1}",
        ])
        sess.exec("sh", "-c",
                  f"cat > /etc/elasticsearch/elasticsearch.yml "
                  f"<< 'EOF'\n{cfg}\nEOF")
        sess.exec("service", "elasticsearch", "restart")

    def teardown(self, test, node):
        sess = control.current_session().su()
        sess.exec_ok("service", "elasticsearch", "stop")
        sess.exec("rm", "-rf", "/var/lib/elasticsearch/jepsen")

    def log_files(self, test, node):
        return [LOGFILE]


class ESClient(jclient.Client):
    """Set ops over the document API: add = index doc with id=value
    (write concern: wait_for_active_shards), read = refresh + match_all
    search."""

    def __init__(self, port: int = 9200, node: str | None = None,
                 timeout: float = 5.0):
        self.port = port
        self.node = node
        self.timeout = timeout

    def open(self, test, node):
        return ESClient(self.port, node, self.timeout)

    def _url(self, test, path: str) -> str:
        host, port = resolve(self.node, self.port, test or {})
        return f"http://{host}:{port}{path}"

    def _request(self, test, path: str, body: dict | None = None,
                 method: str = "GET") -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._url(test, path), data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read() or b"{}")

    def invoke(self, test, op):
        crash = "fail" if op["f"] == "read" else "info"
        try:
            if op["f"] == "add":
                v = int(op["value"])
                self._request(test, f"/{INDEX}/doc/{v}?op_type=create",
                              {"value": v}, "PUT")
                return {**op, "type": "ok"}
            if op["f"] == "read":
                self._request(test, f"/{INDEX}/_refresh", None, "POST")
                out = self._request(
                    test, f"/{INDEX}/_search",
                    {"size": 100000,
                     "query": {"match_all": {}}}, "POST")
                hits = out.get("hits", {}).get("hits", [])
                return {**op, "type": "ok",
                        "value": sorted(int(h["_id"]) for h in hits)}
            return {**op, "type": "fail", "error": f"unknown f {op['f']!r}"}
        except urllib.error.HTTPError as e:
            if e.code == 409:   # op_type=create conflict: definite
                return {**op, "type": "fail", "error": "conflict"}
            if 400 <= e.code < 500:
                return {**op, "type": "fail", "error": f"http-{e.code}"}
            return {**op, "type": crash, "error": f"http-{e.code}"}
        except OSError as e:
            return {**op, "type": crash, "error": str(e)[:160]}


def workloads(opts: dict | None = None) -> dict:
    opts = opts or {}
    return {"set": lambda: set_workload.test(
        n=opts.get("set-size", 500))}


def elasticsearch_test(opts: dict | None = None) -> dict:
    opts = base_opts(**(opts or {}))
    wl = workloads(opts)["set"]()
    test = {
        "name": "elasticsearch set",
        "os": os_setup.debian(),
        "db": ElasticsearchDB(opts.get("version", VERSION)),
        "client": opts.get("client") or ESClient(),
        "nemesis": jnemesis.partition_random_halves(),
        "checker": jchecker.compose({
            "set": wl["checker"],
            "perf": jchecker.perf_checker(),
        }),
        "generator": gen.time_limit(
            opts.get("time-limit", 60),
            gen.clients(wl["generator"],
                        nemesis_cycle(opts.get("nemesis-interval", 10)))),
        "workload": "set",
    }
    for k, v in opts.items():
        test.setdefault(k, v)
    return test


def main(argv=None) -> int:
    return jcli.run_cli(lambda tmap, args: elasticsearch_test(tmap),
                        name="elasticsearch", argv=argv)


if __name__ == "__main__":
    import sys
    sys.exit(main())
