"""Per-database test suites.

Counterpart of the reference's per-DB subprojects (SURVEY.md §2.6): each
suite module exposes

    workloads        {name: fn(opts) -> {"generator", "checker", ...}}
    <db>_test(opts)  a full test map for one workload
    main()           CLI entry (test / analyze / serve subcommands)

following the etcd template (etcd/src/jepsen/etcd.clj:154-191). Suites
with workload matrices (tidb/core.clj:32-100, yugabyte/core.clj:74-110,
cockroachdb, dgraph) build their maps from the shared workload library;
`all_tests` expands the sweep the way the reference's test-all does.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable

from .. import generator as gen
from ..workloads import (adya, append, bank, causal_reverse, long_fork,
                         monotonic, register, set_workload, wr)

#: Every per-DB suite module (the reference's 27 sibling subprojects,
#: SURVEY.md §2.6; mongodb is the shared core behind the -rocks and
#: -smartos variants).
SUITES = (
    "aerospike", "charybdefs", "chronos", "cockroach", "consul",
    "crate", "dgraph", "disque", "elasticsearch", "etcd", "faunadb",
    "galera", "hazelcast", "ignite", "logcabin", "mongodb",
    "mongodb_rocks", "mongodb_smartos", "mysql_cluster", "percona",
    "postgres_rds", "rabbitmq", "raftis", "rethinkdb", "robustirc",
    "tidb", "yugabyte", "zookeeper",
)


def load_suite(name: str):
    """Import a suite module by name (lazy: suites pull in their
    drivers only when used)."""
    if name not in SUITES:
        raise ValueError(f"unknown suite {name!r}; have {SUITES}")
    return importlib.import_module(f".{name}", __package__)


def base_opts(**kw) -> dict:
    """Default CLI-ish options (cli.clj:18,78-99)."""
    opts = {
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "concurrency": 5,
        "time-limit": 60,
        "ssh": {},
    }
    opts.update(kw)
    return opts


def standard_workloads(opts: dict | None = None) -> dict[str, Callable]:
    """The workload registry shared by the matrix suites. Each entry
    returns a {"generator", "checker"} package."""
    opts = opts or {}
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    return {
        "register": lambda: _pkg(register.test()),
        "bank": lambda: _pkg(bank.test()),
        "set": lambda: _pkg(set_workload.test(n=opts.get("set-size", 100))),
        "append": lambda: _pkg(append.test()),
        "wr": lambda: _pkg(wr.test()),
        "long-fork": lambda: long_fork.workload(
            opts.get("long-fork-group", 2)),
        "monotonic": lambda: monotonic.workload(),
        "sequential": lambda: causal_reverse.workload(nodes),
        "g2": lambda: adya.workload(),
    }


def _pkg(test_map: dict) -> dict:
    return {"generator": test_map.get("generator"),
            "checker": test_map.get("checker")}


def resolve_workload(args, tmap: dict, default: str) -> str:
    """--workload wins when given explicitly; a stored run's workload
    wins over the suite default so `analyze` re-checks with the right
    model (cli.clj:381-411)."""
    return (getattr(args, "workload", None) or tmap.get("workload")
            or default)


def nemesis_cycle(interval: float = 10) -> Any:
    """The standard start/stop nemesis schedule
    (etcd.clj:174-178, combined.clj:26-28). gen.cycle — NOT repeat_gen,
    which re-yields the first sleep forever and never starts a fault."""
    return gen.cycle([gen.sleep(interval),
                      {"type": "info", "f": "start"},
                      gen.sleep(interval),
                      {"type": "info", "f": "stop"}])


def suite_test(name: str, workload_name: str, opts: dict,
               workloads: dict[str, Callable],
               db=None, client=None, nemesis=None,
               os_setup=None) -> dict:
    """Assemble a full test map from a workload registry entry, the way
    each suite's <db>-test does (etcd.clj:154-180)."""
    if workload_name not in workloads:
        raise ValueError(
            f"unknown workload {workload_name!r}; "
            f"have {sorted(workloads)}")
    wl = workloads[workload_name]()
    g = wl["generator"]
    interval = opts.get("nemesis-interval", 10)
    nemesis_gen = nemesis_cycle(interval)
    # Combined fault bundle for ANY suite (combined.clj:318-364): opts
    # {"faults": ["partition", "kill", "pause", "clock"]} swaps the
    # plain start/stop partition schedule for the composed package's
    # nemesis + generator (faults the DB can't support are dropped).
    heal_gen = gen.once({"type": "info", "f": "stop"})
    if opts.get("faults"):
        # An explicit fault request beats the suite's default nemesis —
        # every suite bakes one in, so "explicit argument wins" would
        # make the flag a no-op everywhere.
        from ..nemesis import combined as ncombined
        pkg = ncombined.nemesis_package(
            db, interval, faults=opts["faults"])
        nemesis = pkg["nemesis"]
        if pkg.get("generator") is not None:
            nemesis_gen = pkg["generator"]
        if pkg.get("final_generator") is not None:
            heal_gen = pkg["final_generator"]
    main_gen = gen.time_limit(
        opts.get("time-limit", 60),
        gen.clients(g, nemesis_gen))
    if wl.get("final_generator") is not None:
        # post-time-limit phase (queue drains, final reads): heal the
        # nemesis first so a live partition can't wedge an until-ok
        # final phase (the reference's std-gen shape)
        main_gen = gen.phases(
            main_gen,
            gen.nemesis(heal_gen),
            wl["final_generator"])
    test = {
        "name": f"{name} {workload_name}",
        "nodes": opts.get("nodes"),
        "concurrency": opts.get("concurrency", 5),
        "ssh": opts.get("ssh", {}),
        "generator": main_gen,
        "checker": wl["checker"],
        "workload": workload_name,
    }
    # Omit unset roles so core.run's defaults (noop db/os/...) apply.
    # A workload entry may carry its own default client (e.g. per-mode
    # wire clients); an explicit `client` argument wins.
    client = client if client is not None else wl.get("client")
    for key, val in (("db", db), ("client", client),
                     ("nemesis", nemesis), ("os", os_setup)):
        if val is not None:
            test[key] = val
    test.update(opts.get("extra", {}))
    # Carry every other opt through (store, start-time, ssh details...)
    # so `analyze` on a stored run writes back into the SAME run dir.
    for k, v in opts.items():
        if k != "extra":
            test.setdefault(k, v)
    if "start-time" in opts and opts.get("name"):
        test["name"] = opts["name"]
    return test


def all_tests(name: str, opts: dict, workloads: dict[str, Callable],
              **kw) -> list[dict]:
    """One test map per workload — the suite sweep (tidb/core.clj:32-100,
    cli.clj test-all)."""
    return [suite_test(name, w, opts, workloads, **kw)
            for w in sorted(workloads)]
