"""The Client protocol: how workloads talk to the system under test.

Mirrors the reference protocol (jepsen/src/jepsen/client.clj:9-28): a
client has a five-phase lifecycle. `open` clones a fresh client bound to a
node; `setup` installs schemas/initial data; `invoke` applies one op and
returns its completion; `teardown` cleans up; `close` releases the
connection. One client instance exists per logical process; crashed
processes get fresh clients (core.clj:360-377).
"""

from __future__ import annotations

from typing import Any


class Client:
    def open(self, test: dict, node: str) -> "Client":
        """Return a client bound to the given node. Called once per
        process; must be safe to call concurrently."""
        return self

    def setup(self, test: dict) -> None:
        pass

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply op to the system; return the completion op (type ok /
        fail / info)."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass

    def close(self, test: dict) -> None:
        pass


class NoopClient(Client):
    """Does nothing, successfully (client.clj:30-37)."""

    def invoke(self, test, op):
        return {**op, "type": "ok"}


def noop() -> Client:
    return NoopClient()


class ValidatingClient(Client):
    """Asserts protocol contracts around an inner client
    (client.clj:73-119)."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        opened = self.client.open(test, node)
        if opened is None:
            raise ValueError(f"open returned None on {self.client!r}")
        return ValidatingClient(opened)

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        res = self.client.invoke(test, op)
        if not isinstance(res, dict):
            raise ValueError(
                f"client invoke returned {res!r}, not a completion op")
        if res.get("type") not in ("ok", "fail", "info"):
            raise ValueError(f"bad completion type: {res!r}")
        if res.get("process") != op.get("process"):
            raise ValueError(
                f"completion process {res.get('process')!r} != invocation "
                f"process {op.get('process')!r}")
        if res.get("f") != op.get("f"):
            raise ValueError(
                f"completion f {res.get('f')!r} != invocation f "
                f"{op.get('f')!r}")
        return res

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)


def validate(client: Client) -> Client:
    return ValidatingClient(client)


def is_client(x: Any) -> bool:
    return isinstance(x, Client)
