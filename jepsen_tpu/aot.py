"""Persistent AOT executable cache: zero XLA compiles on warm sweeps.

A bucketed sweep's kernels are keyed by a tiny tuple — bucket shape,
batch size, flag set, closure formulation, backend — yet every fresh
`analyze-store` process used to re-trace and re-compile each of them
from scratch: tens of seconds of XLA time on the north-star shape
before the first verdict, paid again on every repeat sweep over the
same store. This module front-ends `jax.jit(...).lower(...).compile()`
with two layers:

  * an in-process map (compiled executables reused across buckets of
    the same geometry — what jit's own tracing cache did, minus the
    tracing), and
  * a disk cache of serialized executables
    (`jax.experimental.serialize_executable`), keyed by a digest of
    (jax/jaxlib version, backend platform + device count, input
    avals, kernel flags, formulation), so a REPEAT sweep in a fresh
    process deserializes instead of compiling.

Every lookup lands in exactly one of the `compile_cache_hits` /
`compile_cache_misses` counters — the warm-path bench drives the miss
count to zero and `make bench-warm` gates on it. Everything here is
best-effort: a corrupt/incompatible cache entry (jax upgrade, topology
change — both keyed, but belt and braces) degrades to a fresh compile,
never to a failed sweep. Gates: `JEPSEN_TPU_AOT_CACHE` (default on),
`JEPSEN_TPU_COMPILE_CACHE_DIR` (default `~/.cache/jepsen_tpu/
executables`).
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
from pathlib import Path

log = logging.getLogger(__name__)

#: In-memory executables, bounded: a sweep sees a handful of bucket
#: geometries, so 128 is generous; insertion order evicts oldest.
_MEM_CAP = 128

_mem: dict[str, object] = {}
_lock = threading.Lock()


def enabled() -> bool:
    """One home for the JEPSEN_TPU_AOT_CACHE gate (default on)."""
    from . import gates
    return gates.get("JEPSEN_TPU_AOT_CACHE")


def cache_dir() -> Path:
    """The on-disk executable cache directory
    (JEPSEN_TPU_COMPILE_CACHE_DIR overrides the default)."""
    from . import gates
    d = gates.get("JEPSEN_TPU_COMPILE_CACHE_DIR")
    if d:
        return Path(d)
    return Path.home() / ".cache" / "jepsen_tpu" / "executables"


def clear_memory() -> None:
    """Drop the in-process executable map (tests; a backend restart)."""
    with _lock:
        _mem.clear()


def resident_count() -> int:
    """How many compiled executables the in-process map holds — the
    `resident_executables` gauge the device cost observatory
    publishes (obs.device via residency.publish_residency_gauges)."""
    with _lock:
        return len(_mem)


def _fingerprint(args, key_parts: tuple) -> str:
    """Digest of everything that determines the compiled artifact:
    toolchain versions, backend topology, input avals, kernel flags."""
    import jax
    try:
        import jaxlib
        jaxlib_v = jaxlib.__version__
    except Exception:
        jaxlib_v = ""
    backend = jax.devices()[0].platform if jax.devices() else "none"
    parts = [jax.__version__, jaxlib_v,
             backend, str(jax.device_count()), repr(key_parts)]
    for a in args:
        parts.append(f"{tuple(a.shape)}:{a.dtype}")
    return hashlib.sha256("|".join(map(str, parts)).encode()).hexdigest()


def _disk_load(path: Path):
    """Deserialize one cached executable, or None (missing/corrupt/
    incompatible — the caller recompiles and overwrites)."""
    try:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = pickle.loads(path.read_bytes())
        return se.deserialize_and_load(payload, in_tree, out_tree)
    except FileNotFoundError:
        return None
    except Exception:
        log.debug("AOT cache load failed for %s; recompiling",
                  path, exc_info=True)
        return None


def _disk_store(path: Path, compiled) -> None:
    """Serialize one executable, atomically (temp + rename — a crash
    mid-write must never leave a torn entry for another process)."""
    try:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = se.serialize(compiled)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(f".{path.name}.{os.getpid()}.tmp")
        tmp.write_bytes(pickle.dumps((payload, in_tree, out_tree)))
        os.replace(tmp, path)
    except Exception:
        log.debug("AOT cache store failed for %s", path, exc_info=True)


def compiled_for(jitfn, args, key_parts: tuple):
    """The compiled executable for `jitfn` over `args`' avals: memory,
    then disk, then `lower().compile()` (+ persist). Exactly one of
    compile_cache_hits/compile_cache_misses increments per call. Any
    failure in the AOT machinery returns the plain jitted fn — the
    sweep must never be hostage to its own compile cache."""
    from . import trace
    try:
        key = _fingerprint(args, key_parts)
        with _lock:
            hit = _mem.get(key)
        if hit is not None:
            trace.counter("compile_cache_hits").inc()
            # re-observe on memory hits too: the observatory resets
            # per sweep, and a later sweep's costdb must still carry
            # the resident executables it dispatched (dict probe once
            # captured; nothing with JEPSEN_TPU_COSTDB off)
            from .obs import device as device_obs
            device_obs.observe(key_parts, args, hit, source="compiled")
            return hit
        path = cache_dir() / f"{key}.jtx"
        compiled = _disk_load(path)
        if compiled is not None:
            trace.counter("compile_cache_hits").inc()
        else:
            trace.counter("compile_cache_misses").inc()
            compiled = jitfn.lower(*args).compile()
            _disk_store(path, compiled)
        with _lock:
            if len(_mem) >= _MEM_CAP:
                _mem.pop(next(iter(_mem)))
            _mem[key] = compiled
        # the device cost observatory's capture point: the compiled
        # executable's cost/memory analyses, once per (key_parts,
        # batch) — a dict probe on repeats, nothing at all with the
        # JEPSEN_TPU_COSTDB gate off
        from .obs import device as device_obs
        device_obs.observe(key_parts, args, compiled, source="compiled")
        return compiled
    except Exception:
        log.warning("AOT executable cache failed; dispatching via jit",
                    exc_info=True)
        return jitfn
