"""Sharded store -> tensor ingest (SURVEY.md §5.7).

The analysis phase is device-bound only if the host can feed it:
encoding one 10k-op list-append history costs ~50ms of dict parsing,
so a single-core loop would throttle a TPU slice checking hundreds of
histories per second. This module shards the ingest the way the batch
sweep shards the checking: run directories are encoded by a process
pool, each worker reading its own history file from disk (nothing but
compact arrays crosses the process boundary — no op-dict pickling),
and the parent batches the results straight onto the mesh.

The reference's analogues are the chunked parallel history writer
(jepsen/src/jepsen/util.clj:203-225) and bounded-pmap over independent
keys (independent.clj:472-492); here the unit is a whole stored run.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import time
from pathlib import Path
from typing import Sequence

log = logging.getLogger(__name__)


def load_history_dir(run_dir: str | os.PathLike) -> list[dict]:
    """History ops from a run dir (delegates to the store's loader —
    one format rule, shared with Store.load_history)."""
    from .store import load_history_dir as _load
    return _load(run_dir)


def native_ingest_enabled() -> bool:
    """One home for the JEPSEN_TPU_NATIVE_INGEST gate (default on) so
    the sweep and the bench's reporting can't drift apart."""
    from . import gates
    return gates.get("JEPSEN_TPU_NATIVE_INGEST")


def encode_run_dir(run_dir: str | os.PathLike, checker: str = "append",
                   lean: bool = True, info: dict | None = None):
    """Load + encode one run dir. With lean=True the per-row completion
    ops are dropped so only arrays cross process boundaries (witness
    rendering then reports txn row numbers instead of full ops — the
    batch sweep's flags don't carry witnesses anyway).

    `info`, when given, gets info["cache"] set to "hit"/"miss" (None
    when the encoded sidecar cache didn't apply) so pooled callers can
    aggregate cache counters in the PARENT tracer — pool workers'
    COUNTERS are process-local and never exported (their spans spool
    to the trace fabric, but counters relay only via this dict)."""
    from . import supervisor, trace
    # self-nemesis (JEPSEN_TPU_FAULT_INJECT): deterministic encode
    # faults / worker kills land here, ahead of the cache, so every
    # retry of a selected run dir fails identically in every process
    supervisor.maybe_inject_encode_fault(run_dir)
    cacheable = lean and checker in ("append", "wr")
    if info is not None:
        info["cache"] = None
    if cacheable:
        from . import store as _store
        if _store.encode_cache_enabled():
            with trace.span("cache_probe"):
                enc = _store.load_encoded(run_dir, checker)
            if enc is not None:
                trace.counter("cache_hits").inc()
                if info is not None:
                    info["cache"] = "hit"
                    if getattr(enc, "upgraded", False):
                        info["upgraded"] = True
                return enc
            trace.counter("cache_misses").inc()
            if info is not None:
                info["cache"] = "miss"
    if cacheable and native_ingest_enabled():
        # C++ fast path: history.jsonl -> tensors/edges with no Python
        # dicts (native/hist_encode.cc). None -> fall through to the
        # Python encoder; the native side only accepts inputs it can
        # encode byte-identically. Lean only: this path's witnesses are
        # the lean int shape, which the Python branches below
        # canonicalize to as well (encode.lean_anomalies /
        # wr.lean_wr_anomalies) so persisted artifacts don't depend on
        # which encoder ran. The native encoder also writes the
        # encoded.v1 sidecar straight from its own buffers (no Python
        # round-trip) when cache writes are on.
        jl = Path(run_dir) / "history.jsonl"
        if jl.is_file():
            from . import store as _store
            from .checker.elle import native_encode as ne
            sidecar = None
            if _store.encode_cache_enabled() \
                    and _store.encode_cache_write_enabled():
                sidecar = _store.encoded_cache_path(run_dir, checker)
            with trace.span("encode_native"):
                enc = (ne.encode_history_file(jl, sidecar_path=sidecar)
                       if checker == "append"
                       else ne.encode_wr_history_file(
                           jl, sidecar_path=sidecar))
            if enc is not None:
                return enc
    with trace.span("load_history"):
        hist = load_history_dir(run_dir)
    with trace.span("encode_py"):
        if checker == "append":
            from .checker.elle.encode import (encode_history,
                                              lean_anomalies)
            enc = encode_history(hist)
            if lean:
                enc.anomalies = lean_anomalies(enc)
        elif checker == "wr":
            from .checker.elle.wr import (encode_wr_history,
                                          lean_wr_anomalies)
            enc = encode_wr_history(hist)
            if lean:
                enc.anomalies = lean_wr_anomalies(enc)
        else:
            raise ValueError(f"unknown checker {checker!r}")
    if lean:
        enc.txn_ops = []
        if cacheable:
            from . import store as _store
            with trace.span("sidecar_write"):
                _store.save_encoded(run_dir, checker, enc)
    return enc


def _worker(args):
    run_dir, checker = args
    try:
        return encode_run_dir(run_dir, checker)
    except Exception as e:
        return e


def overlap_seconds(spans_a: list, spans_b: list) -> float:
    """Total seconds where some span in `a` intersects some span in
    `b` (both lists of (start, end) wall-clock pairs). Used to report
    honest pipeline overlap: worker parse spans x caller device
    spans. Delegates to the one shared interval implementation in
    `trace` (the attribution report walks the same arithmetic)."""
    from . import trace
    return trace.overlap_seconds(spans_a, spans_b)


def _stream_worker(args):
    """Pool worker for the streaming pipeline: encode one run dir and
    move the arrays through shared memory when a segment name was
    assigned (jepsen_tpu.shm), or fall back to pickling the encoding.
    Returns (idx, payload, encode-info, t0, t1); payload is a shm
    descriptor, the encoding itself, or the per-run Exception. The
    (t0, t1) parse span uses time.monotonic: CLOCK_MONOTONIC is
    system-wide on Linux, so spans compare across processes (the
    measured-overlap contract) and an NTP step can't corrupt them.

    With worker tracing on (`tctx` non-None — parent tracing enabled,
    JEPSEN_TPU_WORKER_TRACE on, a spool dir registered), the worker
    records its own spans into a process-local Tracer, spools them to
    `<store>/trace-<pid>.jsonl` per task (torn-tail-safe), and ships
    a compact digest back in einfo["tdigest"] — the parent folds the
    digest into its metrics and merge_traces folds the spool into
    the sweep's trace.json as this worker's own pid track."""
    idx, run_dir, checker, seg_name, tctx = args
    from . import trace
    trace.ensure_worker_tracer(tctx)
    t0 = time.monotonic()
    einfo: dict = {}
    try:
        with trace.span("encode",
                        run=os.path.basename(str(run_dir).rstrip("/"))):
            enc = encode_run_dir(run_dir, checker, info=einfo)
        from . import shm
        from . import store as _store
        if _store.sidecar_version(checker) == 2 \
                and _store.encode_cache_enabled() \
                and (einfo.get("cache") == "hit"
                     or _store.encode_cache_write_enabled()) \
                and _store.encoded_cache_path(run_dir, checker,
                                              2).is_file():
            # a dispatch-shaped sidecar answers for this run (warm
            # hit, or this encode just wrote it — with cache writes
            # DISABLED a merely-existing file may be stale, so only a
            # validated hit qualifies): send a tiny reference and let
            # the PARENT mmap it — copying the padded tensors through
            # a shm segment would re-introduce the host copy the v2
            # format exists to remove, and the parent's views must be
            # its own mapping for the pack stage to stay copy-free
            payload = shm.sidecar_ref(run_dir, checker)
        elif seg_name is not None:
            with trace.span("shm_export"):
                payload = shm.export(enc, seg_name, checker)
        else:
            payload = enc
    except Exception as e:
        payload = e
    t1 = time.monotonic()
    digest = trace.flush_worker_spool()
    if digest:
        einfo["tdigest"] = digest
    return idx, payload, einfo, t0, t1


def _load_worker(run_dir):
    try:
        return load_history_dir(run_dir)
    except Exception as e:
        return e


def _spawn_safe() -> bool:
    """Can a spawn-context worker actually boot? spawn re-imports
    __main__; when __main__ has no importable file (stdin scripts, a
    REPL, embedded interpreters) every worker dies during bootstrap
    and the pool respawns replacements forever — the parent then hangs
    in imap instead of falling back. Detect that case up front."""
    import sys
    m = sys.modules.get("__main__")
    f = getattr(m, "__file__", None)
    if f is None:
        # `python -m pkg.mod` has a spec instead of a file: fine
        return getattr(m, "__spec__", None) is not None
    return os.path.exists(f)


def _pool_map(worker, items: list, processes: int | None) -> list:
    """Shared process-pool recipe: spawned workers (the parent usually
    holds live device runtimes), per-item exceptions returned not
    raised, serial fallback on pool failure. The pool is a
    ProcessPoolExecutor rather than multiprocessing.Pool because a
    SIGKILLed worker (OOM killer, the kill nemesis) must surface as
    BrokenProcessPool — which routes to the serial fallback — instead
    of hanging the parent forever on a result that will never come."""
    if processes is None:
        processes = min(len(items), os.cpu_count() or 1)
    if processes <= 1 or len(items) <= 1 or not _spawn_safe():
        return [worker(it) for it in items]
    from concurrent.futures import ProcessPoolExecutor
    ctx = mp.get_context("spawn")
    try:
        with ProcessPoolExecutor(max_workers=processes,
                                 mp_context=ctx) as ex:
            return list(ex.map(worker, items,
                               chunksize=max(1, len(items)
                                             // (4 * processes))))
    except Exception:
        log.warning("process-pool map failed; falling back to serial",
                    exc_info=True)
        return [worker(it) for it in items]


def parallel_load(run_dirs: Sequence[str | os.PathLike],
                  processes: int | None = None) -> list:
    """Load many run-dir histories via a process pool (for sweeps that
    need raw ops rather than txn encodings — e.g. the per-key register
    sweep). Returns histories or per-run Exception objects, aligned
    with run_dirs."""
    return _pool_map(_load_worker, list(run_dirs), processes)


def parallel_encode(run_dirs: Sequence[str | os.PathLike],
                    checker: str = "append",
                    processes: int | None = None) -> list:
    """Encode many run dirs via a process pool. Returns a list aligned
    with run_dirs: EncodedHistory / WrEncoded on success, the raised
    Exception object on per-run failure (callers route those to their
    fallback checker).

    processes=0 forces the serial path."""
    return _pool_map(_worker, [(d, checker) for d in run_dirs],
                     processes)


def iter_encode_chunks(run_dirs: Sequence[str | os.PathLike],
                       checker: str = "append", chunk: int = 64,
                       processes: int | None = None,
                       info: dict | None = None):
    """Yield (run_dir, encoding) pairs in chunks, IN ORDER, while later
    run dirs keep encoding in background workers — so a caller that
    dispatches each chunk to the accelerator overlaps device compute
    with host parsing (the analyze-store sweep's ingest/check
    pipeline). Encodings are EncodedHistory/WrEncoded or the per-run
    Exception, exactly as parallel_encode.

    On a single-core host a pool is still worth one worker when a REAL
    accelerator runs the checks (the worker parses while the parent
    blocks on the device); without one, pooling 1 core is pure
    serialization overhead, so the serial path is used unless
    JEPSEN_TPU_PIPELINE=1 forces it.

    `info`, when given, gets info["pooled"] set to whether background
    workers actually ran, and info["parse_spans"] filled with each
    worker parse's (start, end) wall-clock pair — intersect those with
    the caller's own device-dispatch spans (`overlap_seconds`) for a
    measured, not inferred, pipeline-overlap number. Spans are
    appended when their items are YIELDED (not when the pool delivers
    them), so a mid-stream pool failure can never leave spans for
    items the caller never saw — the measured overlap only ever counts
    parses whose results reached the device loop. Callers reporting
    overlap must not claim pipelining for the strictly serial path.

    Transport: pool results ride shared memory (jepsen_tpu.shm) —
    workers send only (name, offset, shape, dtype) descriptors and the
    parent wraps zero-copy views over the same pages — unless
    JEPSEN_TPU_SHM_INGEST=0 or /dev/shm is unusable, in which case the
    arrays are pickled per item exactly as before. Either way results
    arrive via imap_unordered and a reorder buffer restores run-dir
    order per chunk, so one slow run dir delays only its own chunk
    instead of head-of-line-blocking every later worker's delivery
    (`reorder_depth` gauge = the deepest the buffer got)."""
    dirs = list(run_dirs)
    if info is not None:
        info["pooled"] = False
        info["parse_spans"] = []
    if not dirs:
        return
    if checker in ("append", "wr") and native_ingest_enabled():
        # Probe the native encoder in THIS process: pooled workers'
        # fallback counters live in worker-local tracers that are never
        # exported, so a missing .so would otherwise degrade the whole
        # sweep's ingest with no signal in the sweep's metrics.json.
        # _cached_lib counts + warns on a miss as a side effect.
        from . import native_lib
        native_lib.hist_lib()
    if processes is None:
        from . import gates
        ncpu = os.cpu_count() or 1
        force = gates.get("JEPSEN_TPU_PIPELINE")
        processes = min(len(dirs), ncpu) if ncpu > 1 or force else 0
    else:
        # never spawn more workers than there are run dirs to parse
        processes = min(int(processes), len(dirs))
    done = 0   # dirs fully yielded: a mid-stream pool failure resumes
    #            serially from here instead of double-yielding
    if processes and processes > 0 and len(dirs) > 1 and _spawn_safe():
        from . import shm, trace
        from . import store as _store
        use_shm = shm.enabled() and shm.available()
        names = [shm.gen_name() if use_shm else None for _ in dirs]
        consumed = [name is None for name in names]
        from concurrent.futures import ProcessPoolExecutor, as_completed
        ctx = mp.get_context("spawn")
        ex = None
        try:
            # ProcessPoolExecutor, not multiprocessing.Pool: a worker
            # that dies without delivering (SIGKILL from the kill
            # nemesis, the OOM killer) raises BrokenProcessPool here
            # instead of hanging imap on a result that will never
            # arrive — the except below then resumes SERIALLY from
            # `done`, so a crashed worker costs re-encodes, never the
            # sweep. as_completed registers ONE waiter per future
            # (repeated wait(FIRST_COMPLETED) over the outstanding set
            # would re-register every not-done future per wake-up —
            # O(N²) churn on a big store's feed loop).
            ex = ProcessPoolExecutor(max_workers=processes,
                                     mp_context=ctx)
            if info is not None:
                info["pooled"] = True
            tr = trace.get_current()
            # worker trace fabric: one context per sweep (trace id +
            # spool dir + monotonic send stamp); None when tracing or
            # worker tracing is off — the worker then skips the whole
            # fabric for free
            tctx = trace.worker_ctx()
            futs = [ex.submit(_stream_worker,
                              (i, d, checker, names[i], tctx))
                    for i, d in enumerate(dirs)]
            pending: dict = {}   # idx -> ((dir, enc), span)
            frontier = 0         # next idx to yield
            buf, span_buf = [], []
            for fut in as_completed(futs):
                idx, payload, einfo, t0, t1 = fut.result()
                if shm.is_sidecar_ref(payload):
                    # warm v2 hit: mmap the sidecar HERE, in the
                    # consuming process — zero bytes crossed the pipe
                    payload = shm.materialize_sidecar(payload)
                elif shm.is_descriptor(payload):
                    tr.counter("shm_bytes").inc(payload["nbytes"])
                    payload = shm.materialize(payload)
                consumed[idx] = True
                if einfo.get("cache") == "hit":
                    tr.counter("cache_hits").inc()
                elif einfo.get("cache") == "miss":
                    tr.counter("cache_misses").inc()
                td = einfo.get("tdigest")
                if td:
                    # the worker's span digest, relayed through the
                    # einfo path like the cache counters: span count
                    # plus per-stage seconds per task (full spans live
                    # in the worker's spool for merge_traces)
                    tr.counter("worker_spans").inc(
                        int(td.get("spans", 0)))
                    for k, secs in (td.get("stage_secs")
                                    or {}).items():
                        tr.histogram(f"worker.{k}").observe(secs)
                if einfo.get("upgraded"):
                    # the worker's v1->v2 upgrade telemetry relayed
                    # into THIS process (worker counters/events are
                    # process-local and never exported; only spans
                    # ride the spool)
                    tr.counter("sidecar_upgrades").inc()
                    from .obs import events as obs_events
                    obs_events.emit(
                        "cache_rebuild",
                        path=str(_store.encoded_cache_path(
                            dirs[idx], checker, 2)),
                        cause="v1->v2 upgrade")
                # the worker's parse window lands on its own trace
                # track (monotonic spans; the tracer converts), so
                # trace.json shows parse/device overlap directly
                tr.add_span("parse", t0, t1, track="ingest-pool",
                            clock="monotonic")
                pending[idx] = ((dirs[idx], payload), (t0, t1))
                if len(pending) > 1:
                    g = tr.gauge("reorder_depth")
                    g.set(max(getattr(g, "value", 0) or 0,
                              len(pending)))
                while frontier in pending:
                    item, span = pending.pop(frontier)
                    buf.append(item)
                    span_buf.append(span)
                    frontier += 1
                    if len(buf) >= chunk:
                        if info is not None:
                            info["parse_spans"].extend(span_buf)
                        yield buf
                        done += len(buf)
                        buf, span_buf = [], []
            if buf:
                if info is not None:
                    info["parse_spans"].extend(span_buf)
                yield buf
                done += len(buf)
            return
        except Exception:
            log.warning("pipelined encode pool failed; falling back "
                        "to serial", exc_info=True)
        finally:
            if ex is not None:
                # cancel queued work and give running tasks a bounded
                # grace to finish: workers should not still be creating
                # segments when the stale-sweep below runs, but a
                # WEDGED worker (a hang in a huge/corrupt parse — the
                # class the supervisor exists for) must not hold
                # teardown hostage the way shutdown(wait=True) would,
                # so stragglers are killed. Their segments fall to the
                # stale-sweep below, or to shm.reclaim_stale at the
                # next sweep's start, keyed on the dead pid.
                procs = list((getattr(ex, "_processes", None)
                              or {}).values())
                ex.shutdown(wait=False, cancel_futures=True)
                deadline = time.monotonic() + 5.0
                for p in procs:
                    p.join(max(0.0, deadline - time.monotonic()))
                for p in procs:
                    if p.is_alive():
                        log.warning("killing wedged encode worker "
                                    "pid=%s", p.pid)
                        p.kill()
            # Exception-path sweep: any segment a worker created but
            # the parent never mapped must not outlive the pool. The
            # happy path unlinks at materialize time, so this only
            # fires for crashed/abandoned items.
            for name, ok in zip(names, consumed):
                if not ok:
                    shm.unlink_stale(name)
    for i in range(done, len(dirs), chunk):
        yield [(d, _worker((d, checker)))
               for d in dirs[i:i + chunk]]
