"""Sharded store -> tensor ingest (SURVEY.md §5.7).

The analysis phase is device-bound only if the host can feed it:
encoding one 10k-op list-append history costs ~50ms of dict parsing,
so a single-core loop would throttle a TPU slice checking hundreds of
histories per second. This module shards the ingest the way the batch
sweep shards the checking: run directories are encoded by a process
pool, each worker reading its own history file from disk (nothing but
compact arrays crosses the process boundary — no op-dict pickling),
and the parent batches the results straight onto the mesh.

The reference's analogues are the chunked parallel history writer
(jepsen/src/jepsen/util.clj:203-225) and bounded-pmap over independent
keys (independent.clj:472-492); here the unit is a whole stored run.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
from pathlib import Path
from typing import Sequence

log = logging.getLogger(__name__)


def load_history_dir(run_dir: str | os.PathLike) -> list[dict]:
    """History ops from a run dir (delegates to the store's loader —
    one format rule, shared with Store.load_history)."""
    from .store import load_history_dir as _load
    return _load(run_dir)


def encode_run_dir(run_dir: str | os.PathLike, checker: str = "append",
                   lean: bool = True):
    """Load + encode one run dir. With lean=True the per-row completion
    ops are dropped so only arrays cross process boundaries (witness
    rendering then reports txn row numbers instead of full ops — the
    batch sweep's flags don't carry witnesses anyway)."""
    hist = load_history_dir(run_dir)
    if checker == "append":
        from .checker.elle.encode import encode_history
        enc = encode_history(hist)
    elif checker == "wr":
        from .checker.elle.wr import encode_wr_history
        enc = encode_wr_history(hist)
    else:
        raise ValueError(f"unknown checker {checker!r}")
    if lean:
        enc.txn_ops = []
    return enc


def _worker(args):
    run_dir, checker = args
    try:
        return encode_run_dir(run_dir, checker)
    except Exception as e:
        return e


def _load_worker(run_dir):
    try:
        return load_history_dir(run_dir)
    except Exception as e:
        return e


def _pool_map(worker, items: list, processes: int | None) -> list:
    """Shared process-pool recipe: spawned workers (the parent usually
    holds live device runtimes), per-item exceptions returned not
    raised, serial fallback on pool failure."""
    if processes is None:
        processes = min(len(items), os.cpu_count() or 1)
    if processes <= 1 or len(items) <= 1:
        return [worker(it) for it in items]
    ctx = mp.get_context("spawn")
    try:
        with ctx.Pool(processes=processes) as pool:
            return pool.map(worker, items,
                            chunksize=max(1, len(items) // (4 * processes)))
    except Exception:
        log.warning("process-pool map failed; falling back to serial",
                    exc_info=True)
        return [worker(it) for it in items]


def parallel_load(run_dirs: Sequence[str | os.PathLike],
                  processes: int | None = None) -> list:
    """Load many run-dir histories via a process pool (for sweeps that
    need raw ops rather than txn encodings — e.g. the per-key register
    sweep). Returns histories or per-run Exception objects, aligned
    with run_dirs."""
    return _pool_map(_load_worker, list(run_dirs), processes)


def parallel_encode(run_dirs: Sequence[str | os.PathLike],
                    checker: str = "append",
                    processes: int | None = None) -> list:
    """Encode many run dirs via a process pool. Returns a list aligned
    with run_dirs: EncodedHistory / WrEncoded on success, the raised
    Exception object on per-run failure (callers route those to their
    fallback checker).

    processes=0 forces the serial path."""
    return _pool_map(_worker, [(d, checker) for d in run_dirs],
                     processes)
