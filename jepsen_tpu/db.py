"""L1: the DB lifecycle protocol.

Counterpart of jepsen.db (jepsen/src/jepsen/db.clj): a DB knows how to
install/start itself on a node and tear itself down; optional mixins add
process kill/start (Process), pause/resume (Pause), primary discovery
(Primary), and log collection (LogFiles) — protocols db.clj:10-40.
`cycle` tears down then sets up with retries (db.clj:89-130).
"""

from __future__ import annotations

import logging
from typing import Iterable

from . import control
from .control import Session
from .util import real_pmap

log = logging.getLogger(__name__)


class DB:
    def setup(self, test: dict, node: str) -> None:
        """Install and start the DB on this node."""
        pass

    def teardown(self, test: dict, node: str) -> None:
        """Stop the DB and wipe its state."""
        pass


class Process:
    """DBs supporting crash/restart fault injection (db.clj:22-29)."""

    def start(self, test: dict, node: str) -> None:
        raise NotImplementedError

    def kill(self, test: dict, node: str) -> None:
        raise NotImplementedError


class Pause:
    """DBs supporting pause/resume (SIGSTOP/SIGCONT; db.clj:31-35)."""

    def pause(self, test: dict, node: str) -> None:
        raise NotImplementedError

    def resume(self, test: dict, node: str) -> None:
        raise NotImplementedError


class SignalProcess(Process, Pause):
    """Mixin implementing the kill/pause/resume fault protocols for DBs
    whose server is a plain daemonized process: signals matched on
    `process_pattern` (the reference's grepkill!/hammer-time route,
    control/util.clj:238, nemesis.clj:380), restart via the DB's own
    `_start(sess, test, node)` launcher. Subclasses set
    `process_pattern` and factor their setup-time daemon launch into
    `_start` so the combined kill package can restart them."""

    process_pattern: str = ""

    def _start(self, sess, test: dict, node: str) -> None:
        raise NotImplementedError

    def _signal(self, sig: str) -> None:
        from .control import util as cutil
        assert self.process_pattern, type(self).__name__
        cutil.grepkill(control.current_session().su(),
                       self.process_pattern, signal=sig)

    def start(self, test, node):
        self._start(control.current_session().su(), test, node)

    def kill(self, test, node):
        self._signal("KILL")

    def pause(self, test, node):
        self._signal("STOP")

    def resume(self, test, node):
        self._signal("CONT")


class Primary:
    """DBs with a distinguished primary (db.clj:15-20)."""

    def setup_primary(self, test: dict, node: str) -> None:
        pass

    def primaries(self, test: dict) -> list[str]:
        return []


class LogFiles:
    def log_files(self, test: dict, node: str) -> list[str]:
        return []


class NoopDB(DB):
    pass


def noop() -> DB:
    return NoopDB()


class SetupFailed(Exception):
    pass


def cycle(db: DB, test: dict, retries: int = 3) -> None:
    """Teardown then setup on every node, retrying setup failures
    (db.clj:89-130). Runs primary setup on the first node afterwards."""
    nodes = test.get("nodes", [])
    for attempt in range(retries):
        try:
            control.on_nodes(test, db.teardown, nodes)
            control.on_nodes(test, db.setup, nodes)
            break
        except SetupFailed:
            if attempt == retries - 1:
                raise
            log.warning("DB setup failed; retrying (%d/%d)",
                        attempt + 1, retries)
    if isinstance(db, Primary) and nodes:
        db.setup_primary(test, nodes[0])


def teardown_all(db: DB, test: dict) -> None:
    control.on_nodes(test, db.teardown, test.get("nodes", []))


class TcpdumpDB(DB, LogFiles):
    """Wraps a DB, capturing packets for the whole test (db.clj:48-87)."""

    def __init__(self, db: DB, ports: Iterable[int],
                 pcap_path: str = "/tmp/jepsen/trace.pcap"):
        self.db = db
        self.ports = list(ports)
        self.pcap_path = pcap_path

    def setup(self, test, node):
        sess = control.current_session().su()
        filt = " or ".join(f"port {p}" for p in self.ports)
        sess.exec("mkdir", "-p", "/tmp/jepsen")
        from .control import util as cu
        cu.start_daemon(sess, "tcpdump", "-w", self.pcap_path, filt,
                        pidfile="/tmp/jepsen/tcpdump.pid",
                        logfile="/tmp/jepsen/tcpdump.log")
        self.db.setup(test, node)

    def teardown(self, test, node):
        self.db.teardown(test, node)
        sess = control.current_session().su()
        from .control import util as cu
        cu.stop_daemon(sess, "/tmp/jepsen/tcpdump.pid")

    def log_files(self, test, node):
        files = [self.pcap_path]
        if isinstance(self.db, LogFiles):
            files += self.db.log_files(test, node)
        return files
