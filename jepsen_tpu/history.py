"""The op model and history functions.

An operation is a plain dict — the universal currency of the framework
(reference: jepsen/src/jepsen/core.clj:220-254, generator/pure.clj:327-336):

    {"type":    "invoke" | "ok" | "fail" | "info",
     "process": int | "nemesis",
     "f":       str,                  # e.g. "read", "write", "cas", "txn"
     "value":   anything,
     "time":    int,                  # nanoseconds, relative to test start
     "index":   int,                  # position in the history
     "error":   optional}

A history is a list of op dicts ordered by real time: each client invocation
(:invoke) is later completed by an :ok (definitely happened), :fail
(definitely did not happen), or :info (indeterminate) op from the same
process. Nemesis ops are always :info and never complete.

This module provides the history functions the reference pulls from
knossos.history (index, pairs, complete, processes) plus tensor-encoding
hooks used by the TPU checkers.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

from .edn import dumps, loads_all

Op = dict  # documentation alias

INVOKE, OK, FAIL, INFO = "invoke", "ok", "fail", "info"
NEMESIS = "nemesis"

TYPES = (INVOKE, OK, FAIL, INFO)


def op(type: str, process: Any, f: Any, value: Any = None, **kw: Any) -> Op:
    """Construct an op map."""
    o = {"type": type, "process": process, "f": f, "value": value}
    o.update(kw)
    return o


def invoke_op(process: Any, f: Any, value: Any = None, **kw: Any) -> Op:
    return op(INVOKE, process, f, value, **kw)


def is_invoke(o: Op) -> bool:
    return o.get("type") == INVOKE


def is_ok(o: Op) -> bool:
    return o.get("type") == OK


def is_fail(o: Op) -> bool:
    return o.get("type") == FAIL


def is_info(o: Op) -> bool:
    return o.get("type") == INFO


def is_client_op(o: Op) -> bool:
    """Client ops have integer processes; the nemesis and other internal
    actors use named processes (reference: jepsen/src/jepsen/util.clj)."""
    return isinstance(o.get("process"), int)


def index(history: list[Op]) -> list[Op]:
    """Return a history whose ops all carry an :index equal to their
    position. Ops that already have the right index are reused."""
    out = []
    for i, o in enumerate(history):
        if o.get("index") != i:
            o = {**o, "index": i}
        out.append(o)
    return out


def processes(history: Iterable[Op]) -> set:
    return {o["process"] for o in history if "process" in o}


def pairs(history: Iterable[Op]) -> Iterator[tuple[Op, Op | None]]:
    """Yield (invocation, completion|None) pairs, in invocation order.

    A completion is the next op by the same process after its invocation.
    Invocations with no completion (still pending at history end) yield
    (invoke, None). Non-invoke ops without a prior invocation (e.g. nemesis
    :info ops) yield (op, None) as well.
    """
    pending: dict[Any, Op] = {}
    order: list[Op] = []
    completion: dict[int, Op] = {}
    for i, o in enumerate(history):
        p = o.get("process")
        if is_invoke(o):
            pending[p] = o
            order.append(o)
        elif p in pending:
            completion[id(pending.pop(p))] = o
        else:
            order.append(o)
    for o in order:
        yield o, completion.get(id(o))


def complete(history: list[Op]) -> list[Op]:
    """Rewrite a history so (a) every invocation completed by an :ok op
    carries the completion's :value (reads know what they returned), and
    (b) every :info completion with a nil value inherits its invocation's
    value (an indeterminate write still says *what* it may have written) —
    matching knossos.history/complete semantics used before
    linearizability checking."""
    out: list[Op] = [dict(o) for o in history]
    pending: dict[Any, Op] = {}  # process -> invocation (from out)
    for o in out:
        p = o.get("process")
        if is_invoke(o):
            pending[p] = o
        elif p in pending:
            inv = pending.pop(p)
            if is_ok(o):
                inv["value"] = o.get("value")
            elif is_info(o) and o.get("value") is None:
                o["value"] = inv.get("value")
    return out


def invocations(history: Iterable[Op]) -> list[Op]:
    return [o for o in history if is_invoke(o)]


def completions(history: Iterable[Op]) -> list[Op]:
    return [o for o in history if not is_invoke(o) and is_client_op(o)]


def oks(history: Iterable[Op]) -> list[Op]:
    return [o for o in history if is_ok(o)]


def filter_f(f: Any, history: Iterable[Op]) -> list[Op]:
    return [o for o in history if o.get("f") == f]


def client_ops(history: Iterable[Op]) -> list[Op]:
    return [o for o in history if is_client_op(o)]


def remove_failures(history: list[Op]) -> list[Op]:
    """Drop invocations that definitely failed, plus their :fail completions.
    :info (indeterminate) ops are preserved — they may have happened."""
    failed: set[int] = set()
    for inv, comp in pairs(history):
        if comp is not None and is_fail(comp):
            failed.add(id(inv))
            failed.add(id(comp))
    return [o for o in history if id(o) not in failed and not is_fail(o)]


# ---------------------------------------------------------------------------
# EDN interop (store compatibility with the reference layout)
# ---------------------------------------------------------------------------

# Fields whose string content is free text, not keyword-ish data.
_TEXT_FIELDS = ("error",)


def op_to_edn(o: Op) -> str:
    """Render one op as an EDN map line compatible with the reference's
    history.edn: keyword keys, and keyword-safe strings (op types, :f names,
    txn micro-op kinds like :append, nemesis targets like :majority) emitted
    as keywords — except free-text fields such as :error."""
    parts = []
    for k, v in o.items():
        keywordize = k not in _TEXT_FIELDS
        parts.append(f":{k} {dumps(v, keywordize=keywordize)}")
    return "{" + ", ".join(parts) + "}"


def history_to_edn(history: Iterable[Op]) -> str:
    return "\n".join(op_to_edn(o) for o in history) + "\n"


def op_from_edn_map(m: dict) -> Op:
    """Convert a parsed EDN op map (Keyword keys) into a plain-string op."""
    o: Op = {}
    for k, v in m.items():
        o[str(k)] = v
    return o


def history_from_edn(text: str) -> list[Op]:
    """Parse a history.edn file (one op map per top-level form)."""
    return [op_from_edn_map(m) for m in loads_all(text)]


# ---------------------------------------------------------------------------
# Latency / interval analytics (reference: jepsen/src/jepsen/util.clj:619-700)
# ---------------------------------------------------------------------------

def history_latencies(history: list[Op]) -> list[Op]:
    """Canonical implementation lives in util.history_latencies
    (reference util.clj:619-653): invocations gain "latency" (ns) and
    "completion" (the completing op)."""
    from .util import history_latencies as _hl
    return _hl(history)


def nemesis_intervals(history: list[Op], start_fs: set | None = None,
                      stop_fs: set | None = None) -> list[tuple[Op, Op | None]]:
    """Canonical implementation lives in util.nemesis_intervals
    (reference util.clj:655-700)."""
    from .util import nemesis_intervals as _ni
    return _ni(history, {"start": start_fs, "stop": stop_fs})
