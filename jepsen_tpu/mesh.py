"""Multi-host sharded sweeps: `analyze-store --mesh` coordination.

One store, a whole slice (ROADMAP item 1). Checking cost grows
superlinearly with history size but is embarrassingly parallel across
independent histories (arxiv 1908.04509), and the per-history
dependency-graph analysis partitions cleanly by run dir — so the
cross-HOST axis of a mesh sweep is a deterministic shard split of the
store's run dirs, not a global dispatch mesh. Each shard (host) runs
the existing warm path (sidecar mmap → views → donated buffers →
AOT-cached dispatch) over its own shard on its own local devices,
journals to its own `verdicts-<shard>.jsonl`, and exports its own
merged trace; the coordinator (shard 0) folds journals, traces and
metrics into the store-level artifacts once every shard's done marker
lands (or its bounded wait expires — a dead host's shard is LOST and
re-assignable, never a dead sweep).

Shard identity resolves in this order:

  1. `JEPSEN_TPU_MESH_SHARDS` (+ optional `JEPSEN_TPU_MESH_SHARD`) —
     the coordinator-free mode: set the count on every host, the
     index per host. Also how an operator RE-ASSIGNS a dead host's
     shard (`JEPSEN_TPU_MESH_SHARD=<k> ... --resume`).
  2. a jax.distributed job (`JAX_COORDINATOR_ADDRESS` et al.):
     `jax.process_index()` / `jax.process_count()` after
     `parallel.init_distributed()`.
  3. neither → one shard (a mesh sweep of one host is an ordinary
     sweep with a per-shard journal).

The shard assignment itself (`store.shard_of`) hashes the
store-relative run key, so every host computes the same partition
from nothing but its own directory listing — resume, re-assignment
and the verdict journal all key on the same string.
"""

from __future__ import annotations

import json
import logging
import sys
from pathlib import Path

from . import gates

log = logging.getLogger(__name__)


def mesh_enabled() -> bool:
    """The JEPSEN_TPU_MESH gate (default off; `--mesh` exports it)."""
    return gates.get("JEPSEN_TPU_MESH")


def resolve_shard() -> tuple[int, int]:
    """(shard index, shard count) for this process — see the module
    doc for the resolution order. An out-of-range explicit index is
    an error (wrapping a mistyped re-assignment onto another LIVE
    shard would race its journal), and so is a failed distributed
    init when a coordinator address is set (degrading to a full-store
    single-shard sweep would have every host of the fleet sweep
    everything, racing each other's artifacts)."""
    override = gates.get("JEPSEN_TPU_MESH_SHARD")
    shards = gates.get("JEPSEN_TPU_MESH_SHARDS")

    def ranged(shard: int, count: int) -> tuple[int, int]:
        if not 0 <= shard < count:
            raise ValueError(
                f"JEPSEN_TPU_MESH_SHARD={shard} out of range for "
                f"a {count}-shard mesh (indices are 0..{count - 1})")
        return shard, count

    if shards is not None and shards > 0:
        return ranged(0 if override is None else override, shards)
    try:
        from . import parallel
        joined = parallel.init_distributed()
        if joined:
            import jax
            # the documented re-assignment override applies here too:
            # MESH_SHARD replaces process_index so a replacement host
            # inside a distributed job can take a dead host's shard
            return ranged(jax.process_index() if override is None
                          else override, jax.process_count())
    except Exception as e:
        if isinstance(e, ValueError):
            raise
        raise RuntimeError(
            "mesh shard identity unresolvable: a coordinator address "
            "is set but jax.distributed init failed — refusing to "
            "degrade to a full-store single-shard sweep (every host "
            "would sweep everything, racing the same journals). Set "
            "JEPSEN_TPU_MESH_SHARDS/_SHARD for coordinator-free "
            "identity instead.") from e
    if override is not None:
        raise ValueError(
            f"JEPSEN_TPU_MESH_SHARD={override} set with no shard "
            "count: set JEPSEN_TPU_MESH_SHARDS too (or run inside a "
            "jax.distributed job) — a bare index cannot define a "
            "partition")
    return 0, 1


def shard_journal_path(store_base, shard: int) -> Path:
    """This shard's resumable verdict journal. Per-shard files keep
    resume strictly local: a killed fleet resumes each shard from its
    OWN journal with zero reads of (or writes racing) any other
    shard's, and a replacement host for a dead shard needs exactly one
    file."""
    return Path(store_base) / f"verdicts-{shard}.jsonl"


def merge_journals(store_base, n_shards: int, checker: str) -> dict:
    """{store-relative run dir: last journal entry} for `checker`
    across every per-shard journal — the coordinator's one verdict
    set. Shards partition the run dirs, so keys can't collide across
    journals; within one journal, last entry wins (the resume
    semantics)."""
    from .store import VerdictJournal
    out: dict[str, dict] = {}
    for k in range(n_shards):
        loaded = VerdictJournal.load(shard_journal_path(store_base, k))
        for (d, c), e in loaded.items():
            if c == checker:
                out[d] = e
    return out


def merge_shard_metrics(store_base, n_shards: int) -> dict:
    """Fleet-level metrics: counters summed across every present
    `metrics-shard<k>.json` (gauges/histograms stay per shard under
    `per_shard` — a max inflight_depth summed across hosts would mean
    nothing)."""
    counters: dict[str, int] = {}
    per_shard: dict[str, dict] = {}
    for k in range(n_shards):
        p = Path(store_base) / f"metrics-shard{k}.json"
        try:
            m = json.loads(p.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(m, dict):
            continue
        per_shard[str(k)] = m
        for name, v in (m.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[name] = counters.get(name, 0) + v
    return {"counters": counters, "per_shard": per_shard}


def coordinator_merge(store, checker: str, shard: int, n_shards: int,
                      own_rc: int | None, report: bool = False,
                      tracer=None, name: str | None = None) -> int:
    """The mesh sweep's merge step. Non-coordinator shards return
    their own exit code untouched; shard 0 waits (bounded) for the
    fleet's done markers, folds the per-shard journals into one
    verdict set (the merged exit code — an invalid verdict on ANY
    shard fails the fleet), merges the per-shard traces into one
    cross-host `trace.json` + `metrics.json`, and — with `report` —
    writes the merged attribution report with per-shard stage shares.

    Degradation, never silent success: the TRUTH about each shard is
    its journal's COVERAGE of its hash assignment (one store walk
    computes every shard's expected run set), not its done marker —
    markers are only the liveness hint the bounded wait polls, so a
    stale marker from a previous sweep, or a marker stamped by a
    shard that CRASHED mid-sweep (analyze_store's finally records
    exit "crashed"), can end the wait early but can never make a
    partial shard read as complete. A shard that is lost (no
    marker), crashed (non-validity exit code) or incomplete (journal
    missing assigned runs) floors the merged exit at 2, is named in
    the summary, and is re-assignable; only a fully-covered fleet
    lets the merge clean the worker spools."""
    own_rc = 255 if own_rc is None else own_rc
    if shard != 0 or n_shards <= 0:
        return own_rc
    import os

    from . import obs
    from . import supervisor as sv
    from .cli import validity_exit_code
    from .store import VerdictJournal, shard_of
    obs.install_events(store.base)
    try:
        others = [k for k in range(n_shards) if k != shard]
        done, lost = sv.wait_for_shards(
            store.base, others,
            timeout_s=gates.get("JEPSEN_TPU_MESH_WAIT_S"))
        # ONE walk derives every shard's expected assignment — the
        # same split every shard computed for itself
        expected: dict[int, set] = {k: set() for k in range(n_shards)}
        for d in store.iter_run_dirs(name=name):
            key = os.path.relpath(d, store.base)
            expected[shard_of(key, n_shards)].add(key)
        journaled: dict[int, dict] = {}
        for k in range(n_shards):
            journaled[k] = {
                d: e for (d, c), e in VerdictJournal.load(
                    shard_journal_path(store.base, k)).items()
                if c == checker}
        crashed, incomplete = [], []
        for k in sorted(done):
            ec = done[k].get("exit_code")
            obs.emit("shard_done", shard=k, exit_code=ec)
            if not isinstance(ec, int) or ec not in (0, 1, 2):
                crashed.append(k)
            elif not expected[k] <= set(journaled[k]):
                # a marker without the journal to back it: stale from
                # a previous sweep, or a partial re-sweep — the
                # journal is the evidence, the marker just a hint
                incomplete.append(k)
        for k in lost:
            obs.emit("shard_lost", shard=k, shards=n_shards)
        for k in lost + crashed + incomplete:
            log.warning(
                "shard %d/%d %s: its runs are unverdicted; re-assign "
                "it with JEPSEN_TPU_MESH_SHARD=%d "
                "JEPSEN_TPU_MESH_SHARDS=%d analyze-store --mesh "
                "--resume", k, n_shards,
                "missing at merge" if k in lost
                else "crashed" if k in crashed
                else "incompletely journaled",
                k, n_shards)
        merged: dict[str, dict] = {}
        for k in range(n_shards):
            merged.update(journaled[k])
        worst = own_rc
        counts = {0: 0, 1: 0, 2: 0}
        for e in merged.values():
            c = validity_exit_code(e)
            worst = max(worst, c)
            counts[c if c in counts else 2] += 1
        total = sum(len(v) for v in expected.values())
        unaccounted = max(0, total - len(merged))
        if lost or crashed or incomplete or unaccounted:
            worst = max(worst, 2)
        print(json.dumps({
            "mesh": True, "checker": checker, "shards": n_shards,
            "runs_total": total, "runs_verdicted": len(merged),
            "unaccounted": unaccounted, "valid": counts[0],
            "invalid": counts[1], "unknown": counts[2],
            "lost_shards": lost, "crashed_shards": crashed,
            "incomplete_shards": incomplete,
            "valid?": worst == 0}))
        cost_records: list = []
        search_records: list = []
        if Path(store.base).is_dir():
            # evidence-driven like the trace merge: shard costdbs
            # exist iff the shards ran with JEPSEN_TPU_COSTDB — merge
            # whatever landed into ONE deduplicated costdb.jsonl
            # (same executable on two shards → one record, windows
            # summed), independent of the trace gate
            try:
                cost_records = merge_costdbs(store.base, n_shards)
            except Exception:
                log.warning("mesh costdb merge failed", exc_info=True)
            # same evidence rule for the kernel-stats ledger: shard
            # analytics exist iff the shards ran with
            # JEPSEN_TPU_KERNEL_STATS
            try:
                search_records = merge_analytics(store.base, n_shards)
            except Exception:
                log.warning("mesh analytics merge failed",
                            exc_info=True)
            # one planner refit over the merged fleet tables (the
            # per-shard sweeps skipped theirs): plan.json then serves
            # every host's next warm sweep; no-op with the gate off
            try:
                from . import planner as planner_mod
                planner_mod.refresh(store.base, cost_records,
                                    search_records)
            except Exception:
                log.warning("mesh planner refresh failed",
                            exc_info=True)
        if tracer is not None and getattr(tracer, "enabled", False) \
                and Path(store.base).is_dir():
            try:
                _merge_trace_artifacts(
                    store.base, n_shards, report,
                    fleet_complete=not (lost or crashed or incomplete
                                        or unaccounted),
                    device_records=cost_records,
                    search_records=search_records)
            except Exception:
                log.warning("mesh trace merge failed", exc_info=True)
        return worst
    finally:
        obs.reset_events()


def merge_costdbs(store_base, n_shards: int) -> list[dict]:
    """Fold every present per-shard `costdb-shard<k>.jsonl` into one
    deduplicated `<store>/costdb.jsonl` (obs.device.merge_records:
    same (executable, geometry) on two shards → one record with the
    measured windows summed and the roofline re-derived). An absent
    shard file (that shard ran gate-off, or was lost) is an EMPTY
    typed table from load_costdb, not an error — merging a partial
    fleet is the norm, not the exception. Returns the merged records
    ([] when no shard captured any — gate off). The merged file is
    written atomically: it is a derived artifact, and a repeat merge
    must replace, not double, the fleet's records."""
    from . import trace as _trace
    from .obs import device as device_obs
    from .store import COSTDB_NAME, costdb_path, load_costdb
    lists = [load_costdb(costdb_path(store_base, k))
             for k in range(n_shards)]
    absent = sum(1 for t in lists if not t.exists)
    if absent:
        log.debug("costdb merge: %d/%d shard file(s) absent",
                  absent, n_shards)
    if not any(lists):
        return []
    merged = device_obs.merge_records(lists)
    _trace.atomic_write_text(
        Path(store_base) / COSTDB_NAME,
        "".join(json.dumps(r) + "\n" for r in merged))
    print(f"merged costdb: {len(merged)} record(s) across "
          f"{n_shards} shard(s)", file=sys.stderr)
    return merged


def merge_analytics(store_base, n_shards: int) -> list[dict]:
    """Fold every present per-shard `analytics-shard<k>.jsonl` into
    one `<store>/analytics.jsonl`. Shards partition the run dirs, so
    records can't collide across files; within one file, the last
    record per (dir, checker) wins (the resume semantics — a
    re-swept history's fresher stats replace its older line). The
    merged file is a derived artifact written atomically: a repeat
    merge replaces, never doubles. Returns the merged records ([]
    when no shard captured any — gate off)."""
    from . import trace as _trace
    from .store import ANALYTICS_NAME, analytics_path, load_analytics
    merged: dict[tuple, dict] = {}
    for k in range(n_shards):
        for rec in load_analytics(analytics_path(store_base, k)):
            merged[(rec.get("dir"), rec.get("checker"))] = rec
    if not merged:
        return []
    out = list(merged.values())
    _trace.atomic_write_text(
        Path(store_base) / ANALYTICS_NAME,
        "".join(json.dumps(r) + "\n" for r in out))
    print(f"merged analytics: {len(out)} record(s) across "
          f"{n_shards} shard(s)", file=sys.stderr)
    return out


def _merge_trace_artifacts(store_base, n_shards: int, report: bool,
                           fleet_complete: bool = True,
                           device_records: list | None = None,
                           search_records: list | None = None) -> None:
    """trace.json / metrics.json / report.{json,md} from the per-shard
    exports (a lost shard's missing files are skipped, not fatal).
    `device_records` is the ALREADY-merged costdb set the coordinator
    just wrote — handed through so the report can never read a stale
    pre-merge file."""
    from . import trace as _trace
    evs, per_shard = _trace.merge_shard_traces(store_base,
                                               range(n_shards))
    if not evs:
        return
    p = _trace.atomic_write_text(
        Path(store_base) / "trace.json",
        json.dumps({"traceEvents": evs, "displayTimeUnit": "ms"}))
    print(f"merged mesh trace written to {p}", file=sys.stderr)
    metrics = merge_shard_metrics(store_base, n_shards)
    _trace.atomic_write_text(Path(store_base) / "metrics.json",
                             json.dumps(metrics, indent=2))
    if report:
        from .obs import attribution
        rj, _md = attribution.write_report(
            store_base, evs, metrics, per_shard_events=per_shard,
            device_records=device_records or None,
            search_records=search_records or None)
        print(f"merged mesh report written to {rj}", file=sys.stderr)
    # every shard's spans now live in its trace-shard<k>.json export —
    # but ONLY when the whole fleet is accounted for: a lost/crashed/
    # incomplete shard may still be sweeping, and deleting its live
    # spool dir would strip the worker spans from the shard trace it
    # eventually exports. With stragglers outstanding the spool dirs
    # stay (each shard cleans its own at its next sweep start).
    if fleet_complete:
        for k in range(n_shards):
            sd = _trace.shard_spool_dir(store_base, k)
            _trace.clean_spools(sd)
            try:
                sd.rmdir()
            except OSError:
                pass
