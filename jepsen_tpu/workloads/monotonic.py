"""Monotonic workload: a counter that must never appear to go backwards.

Counterpart of the monotonic workloads in the cockroachdb and tidb
suites (cockroachdb/src/jepsen/cockroach/monotonic.clj,
tidb/src/tidb/monotonic.clj): clients increment a counter and read it;
reads paired with their real-time order must observe non-decreasing
values, and an `inc` must return a value strictly greater than any value
whose operation completed before the increment began.
"""

from __future__ import annotations

from .. import generator as gen
from ..checker import Checker


def r(test=None, ctx=None):
    return {"type": "invoke", "f": "read", "value": None}


def inc(test=None, ctx=None):
    return {"type": "invoke", "f": "inc", "value": None}


def generator():
    return gen.mix([r, inc])


class MonotonicChecker(Checker):
    """Replays completions in real-time order; any ok op whose observed
    value is smaller than a value already acknowledged before its invoke
    is a regression."""

    def check(self, test, history, opts):
        # prefix_max[j] = max value among the first j completions, in
        # completion order; floor for an op = prefix max over completions
        # whose index precedes the op's invoke (O(n log n) via bisect).
        import bisect
        comp_idx: list[int] = []
        prefix_max: list = []
        invoke_idx: dict = {}
        errors = []
        for i, op in enumerate(history):
            if op.get("type") == "invoke":
                invoke_idx[op.get("process")] = i
                continue
            if op.get("type") != "ok" or op.get("value") is None:
                continue
            inv = invoke_idx.get(op.get("process"), 0)
            j = bisect.bisect_left(comp_idx, inv)
            floor = prefix_max[j - 1] if j > 0 else None
            v = op["value"]
            if floor is not None:
                # An inc that began after `floor` was acknowledged must
                # return strictly more; a read may equal it.
                bad = v <= floor if op.get("f") == "inc" else v < floor
                if bad:
                    errors.append({"op": op, "expected-min": floor})
            comp_idx.append(i)
            prefix_max.append(v if not prefix_max
                              else max(prefix_max[-1], v))
        return {"valid?": not errors, "errors": errors[:16],
                "error-count": len(errors)}


def checker() -> Checker:
    return MonotonicChecker()


def workload(**opts) -> dict:
    return {"generator": generator(), "checker": checker()}
