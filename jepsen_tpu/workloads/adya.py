"""Adya G2 workload: predicate-based anti-dependency cycles.

Counterpart of jepsen.tests.adya (jepsen/src/jepsen/tests/adya.clj): per
key, two transactions each read both tables by predicate and, seeing
nothing, insert into different tables — under serializability at most one
can commit. Values are ``[a_id, b_id]`` pairs where exactly one side is
set, lifted over independent keys (g2-gen adya.clj:12-59); the checker
counts successful inserts per key (g2-checker adya.clj:61-88).
"""

from __future__ import annotations

import itertools

from .. import generator as gen, independent
from ..checker import Checker


def g2_gen() -> gen.Generator:
    """Pairs of :insert ops per key: one with a-id, one with b-id, ids
    globally unique (g2-gen adya.clj:12-59)."""
    ids = itertools.count(1)

    def key_gen(k):
        return [
            gen.once(lambda: {"type": "invoke", "f": "insert",
                              "value": [None, next(ids)]}),
            gen.once(lambda: {"type": "invoke", "f": "insert",
                              "value": [next(ids), None]}),
        ]

    return independent.concurrent_generator(2, range(10_000), key_gen)


class G2Checker(Checker):
    """At most one successful insert per key (g2-checker adya.clj:61-88).

    Expects ops whose value is lifted [key, [a_id, b_id]]."""

    def check(self, test, history, opts):
        keys: dict = {}
        for op in history:
            if op.get("f") != "insert":
                continue
            v = op.get("value")
            if independent.is_tuple(v):
                k = v.key
            elif isinstance(v, (list, tuple)) and len(v) == 2:
                k = v[0]
            else:
                continue
            if op.get("type") == "ok":
                keys[k] = keys.get(k, 0) + 1
            else:
                keys.setdefault(k, 0)
        insert_count = sum(1 for c in keys.values() if c > 0)
        illegal = {k: c for k, c in sorted(keys.items(), key=lambda kv:
                                           repr(kv[0])) if c > 1}
        return {"valid?": not illegal,
                "key-count": len(keys),
                "legal-count": insert_count - len(illegal),
                "illegal-count": len(illegal),
                "illegal": illegal}


def g2_checker() -> Checker:
    return G2Checker()


def workload() -> dict:
    return {"checker": g2_checker(), "generator": g2_gen()}
