"""L9: the workload library — partial test maps with generators+checkers.

Counterpart of jepsen.tests (jepsen/src/jepsen/tests.clj): `noop_test` is
the base test map (tests.clj:12-25), and the atom DB/client pair is the
in-process fake database used by integration tests (tests.clj:27-67) — a
compare-and-set register backed by a lock-protected cell with a 1 ms
sleep for real concurrency.
"""

from __future__ import annotations

import threading
import time

from .. import checker as jchecker
from .. import client as jclient
from .. import db as jdb


def noop_test() -> dict:
    """A valid no-op test skeleton (tests.clj:12-25)."""
    return {
        "name": "noop",
        "os": None,   # filled with noop by prepare_test
        "db": None,
        "client": None,
        "generator": None,
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "checker": jchecker.unbridled_optimism(),
        "ssh": {"dummy": True},
    }


class AtomRegister:
    """The shared in-process register (one per test run)."""

    def __init__(self, value=0):
        self.value = value
        self.lock = threading.Lock()

    def read(self):
        with self.lock:
            return self.value

    def write(self, v):
        with self.lock:
            self.value = v

    def cas(self, old, new) -> bool:
        with self.lock:
            if self.value == old:
                self.value = new
                return True
            return False


class AtomDB(jdb.DB):
    """Resets the shared register on setup (tests.clj:27-33)."""

    def __init__(self, register: AtomRegister):
        self.register = register

    def setup(self, test, node):
        self.register.write(0)

    def teardown(self, test, node):
        pass


class AtomClient(jclient.Client):
    """CAS register client against the in-process atom
    (tests.clj:34-67)."""

    def __init__(self, register: AtomRegister):
        self.register = register

    def open(self, test, node):
        return AtomClient(self.register)

    def invoke(self, test, op):
        time.sleep(0.001)  # real concurrency window
        f, v = op.get("f"), op.get("value")
        if f == "read":
            return {**op, "type": "ok", "value": self.register.read()}
        if f == "write":
            self.register.write(v)
            return {**op, "type": "ok"}
        if f == "cas":
            old, new = v
            ok = self.register.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        return {**op, "type": "fail", "error": f"unknown f {f!r}"}


def atom_fixtures():
    """(db, client) pair sharing one register."""
    reg = AtomRegister()
    return AtomDB(reg), AtomClient(reg)
