"""Bank workload: total balance must be conserved.

Counterpart of jepsen.tests.bank (jepsen/src/jepsen/tests/bank.clj):
clients transfer money between accounts and read all balances; under
snapshot isolation the total must stay constant and (by default) no
balance may go negative (bank.clj:93-130).

Ops:
    {"f": "read"}                                  -> {account: balance}
    {"f": "transfer", "value": {"from","to","amount"}}
"""

from __future__ import annotations

import random

from .. import generator as gen
from ..checker import Checker


DEFAULT_ACCOUNTS = list(range(8))
DEFAULT_TOTAL = 100
DEFAULT_MAX_TRANSFER = 5


class BankChecker(Checker):
    """Every read must total `total`; negative balances are errors unless
    allowed (bank.clj:93-130)."""

    def __init__(self, total: int = DEFAULT_TOTAL,
                 negative_balances: bool = False):
        self.total = total
        self.negative_balances = negative_balances

    def check(self, test, history, opts):
        total = test.get("total-amount", self.total)
        bad_reads = []
        read_count = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            read_count += 1
            balances = op.get("value") or {}
            s = sum(balances.values())
            errs = []
            if s != total:
                errs.append(f"total {s} != {total}")
            if not self.negative_balances:
                neg = {a: b for a, b in balances.items() if b < 0}
                if neg:
                    errs.append(f"negative balances {neg}")
            if errs:
                bad_reads.append({"op": op, "errors": errs})
        if read_count == 0:
            return {"valid?": "unknown", "error": "no reads"}
        return {"valid?": not bad_reads,
                "read-count": read_count,
                "bad-reads": bad_reads[:10],
                "bad-read-count": len(bad_reads)}


class BankPlot(Checker):
    """Renders bank.png: every account's balance over time from the ok
    reads, with nemesis shading — the reference's balance plot
    (bank.clj:160-186, drawn through perf/plot!). Always valid; the
    plot is the artifact."""

    def __init__(self, nemeses=None):
        self.nemeses = nemeses

    def check(self, test, history, opts):
        from ..checker import perf

        path = perf.store_path(test, opts, "bank.png")
        if path is None:
            return {"valid?": True}
        series: dict = {}
        times: dict = {}
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            t = perf.nanos_to_secs(op.get("time", 0))
            for acct, bal in (op.get("value") or {}).items():
                series.setdefault(acct, []).append(bal)
                times.setdefault(acct, []).append(t)
        if not series:
            return {"valid?": True, "plot": None}
        fig, ax = perf.fig_ax(test.get("name", "bank"), "balance",
                              logy=False)
        for acct in sorted(series, key=repr):
            ax.plot(times[acct], series[acct], lw=1,
                    label=f"account {acct}")
        nemeses = self.nemeses or (test.get("plot") or {}).get("nemeses")
        perf.draw_nemeses(ax, history, nemeses, perf.t_max(history))
        ax.grid(True, alpha=0.3)
        perf.finish(fig, ax, path)
        return {"valid?": True, "plot": str(path)}


def plot_checker(nemeses=None) -> Checker:
    return BankPlot(nemeses)


def checker(**kw) -> Checker:
    return BankChecker(**kw)


def generator(accounts=None, max_transfer=DEFAULT_MAX_TRANSFER):
    accounts = accounts or DEFAULT_ACCOUNTS

    def read(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def transfer(test=None, ctx=None):
        a, b = random.sample(accounts, 2)
        return {"type": "invoke", "f": "transfer",
                "value": {"from": a, "to": b,
                          "amount": random.randint(1, max_transfer)}}

    return gen.clients(gen.mix([read, transfer]))


def test(accounts=None, total=DEFAULT_TOTAL,
         max_transfer=DEFAULT_MAX_TRANSFER, plot: bool = True,
         nemeses=None, **kw) -> dict:
    """Partial test map; the checker composes the balance invariant
    with the balance-over-time plot (bank.clj:188-201)."""
    from ..checker import compose

    accounts = accounts or DEFAULT_ACCOUNTS
    balance = checker(total=total, **kw)
    return {"generator": generator(accounts, max_transfer),
            "checker": compose({"bank": balance,
                                "plot": plot_checker(nemeses)}) if plot
            else balance,
            "accounts": accounts,
            "total-amount": total,
            "max-transfer": max_transfer}
