"""Bank workload: total balance must be conserved.

Counterpart of jepsen.tests.bank (jepsen/src/jepsen/tests/bank.clj):
clients transfer money between accounts and read all balances; under
snapshot isolation the total must stay constant and (by default) no
balance may go negative (bank.clj:93-130).

Ops:
    {"f": "read"}                                  -> {account: balance}
    {"f": "transfer", "value": {"from","to","amount"}}
"""

from __future__ import annotations

import random

from .. import generator as gen
from ..checker import Checker


DEFAULT_ACCOUNTS = list(range(8))
DEFAULT_TOTAL = 100
DEFAULT_MAX_TRANSFER = 5


class BankChecker(Checker):
    """Every read must total `total`; negative balances are errors unless
    allowed (bank.clj:93-130)."""

    def __init__(self, total: int = DEFAULT_TOTAL,
                 negative_balances: bool = False):
        self.total = total
        self.negative_balances = negative_balances

    def check(self, test, history, opts):
        total = test.get("total-amount", self.total)
        bad_reads = []
        read_count = 0
        for op in history:
            if op.get("type") != "ok" or op.get("f") != "read":
                continue
            read_count += 1
            balances = op.get("value") or {}
            s = sum(balances.values())
            errs = []
            if s != total:
                errs.append(f"total {s} != {total}")
            if not self.negative_balances:
                neg = {a: b for a, b in balances.items() if b < 0}
                if neg:
                    errs.append(f"negative balances {neg}")
            if errs:
                bad_reads.append({"op": op, "errors": errs})
        if read_count == 0:
            return {"valid?": "unknown", "error": "no reads"}
        return {"valid?": not bad_reads,
                "read-count": read_count,
                "bad-reads": bad_reads[:10],
                "bad-read-count": len(bad_reads)}


def checker(**kw) -> Checker:
    return BankChecker(**kw)


def generator(accounts=None, max_transfer=DEFAULT_MAX_TRANSFER):
    accounts = accounts or DEFAULT_ACCOUNTS

    def read(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    def transfer(test=None, ctx=None):
        a, b = random.sample(accounts, 2)
        return {"type": "invoke", "f": "transfer",
                "value": {"from": a, "to": b,
                          "amount": random.randint(1, max_transfer)}}

    return gen.clients(gen.mix([read, transfer]))


def test(accounts=None, total=DEFAULT_TOTAL,
         max_transfer=DEFAULT_MAX_TRANSFER, **kw) -> dict:
    accounts = accounts or DEFAULT_ACCOUNTS
    return {"generator": generator(accounts, max_transfer),
            "checker": checker(total=total, **kw),
            "accounts": accounts,
            "total-amount": total,
            "max-transfer": max_transfer}
