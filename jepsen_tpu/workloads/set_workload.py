"""Set workload: add unique integers, read them all back at the end.

Pairs with checker.set_checker / checker.set_full (reference checkers
jepsen/src/jepsen/checker.clj:243-302,464-595). The generator adds
increasing integers from client threads, then a final read phase.
"""

from __future__ import annotations

import itertools

from .. import checker as jchecker
from .. import generator as gen


def generator(n: int | None = None):
    counter = itertools.count()

    def add(test=None, ctx=None):
        return {"type": "invoke", "f": "add", "value": next(counter)}

    adds = gen.clients(add if n is None else gen.limit(n, gen.repeat_gen(add)))
    final_read = gen.clients(gen.until_ok(gen.repeat_gen({"f": "read"})))
    return gen.phases(adds, final_read)


def test(n: int = 100, full: bool = False, **kw) -> dict:
    return {"generator": generator(n),
            "checker": jchecker.set_full(**kw) if full
            else jchecker.set_checker()}
