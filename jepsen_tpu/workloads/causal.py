"""Causal-consistency workload: a causal order of register ops that must
appear to execute in issue order, with position links.

Counterpart of jepsen.tests.causal (jepsen/src/jepsen/tests/causal.clj):
a CausalRegister model steps through ok ops carrying ``value``,
``position`` and ``link`` fields; each op must link to the previously
seen position (or "init"), writes must produce the next counter value,
and reads must return the current value (CausalRegister
causal.clj:35-84). The canonical causal order per key is
[read-init, write 1, read, write 2, read] (causal.clj:119-145).
"""

from __future__ import annotations

from typing import Any

from .. import generator as gen, independent
from ..checker import Checker


class Inconsistent:
    """Invalid model termination (causal.clj:17-32)."""

    def __init__(self, msg: str):
        self.msg = msg

    def step(self, op):
        return self


def is_inconsistent(m) -> bool:
    return isinstance(m, Inconsistent)


class CausalRegister:
    """value/counter/last-position state machine (causal.clj:35-84)."""

    def __init__(self, value: int = 0, counter: int = 0, last_pos=None):
        self.value = value
        self.counter = counter
        self.last_pos = last_pos

    def step(self, op: dict):
        c = self.counter + 1
        v = op.get("value")
        pos = op.get("position")
        link = op.get("link")
        if link not in ("init", self.last_pos):
            return Inconsistent(
                f"Cannot link {link} to last-seen position {self.last_pos}")
        f = op.get("f")
        if f == "write":
            if v == c:
                return CausalRegister(v, c, pos)
            return Inconsistent(
                f"expected value {c} attempting to write {v} instead")
        if f == "read-init":
            if self.counter == 0 and v not in (0, None):
                return Inconsistent(f"expected init value 0, read {v}")
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return Inconsistent(
                f"can't read {v} from register {self.value}")
        if f == "read":
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return Inconsistent(
                f"can't read {v} from register {self.value}")
        return Inconsistent(f"unknown f {f!r}")


def causal_register() -> CausalRegister:
    return CausalRegister(0, 0, None)


class CausalChecker(Checker):
    """Steps the model through every ok op (check causal.clj:89-111)."""

    def __init__(self, m=None):
        self.model = m or causal_register()

    def check(self, test, history, opts):
        s = self.model
        for op in history:
            if op.get("type") != "ok":
                continue
            s = s.step(op)
            if is_inconsistent(s):
                return {"valid?": False, "error": s.msg}
        return {"valid?": True, "model": s.value}


def check(m=None) -> Checker:
    return CausalChecker(m)


# Generator ops (causal.clj:114-117)
def r(test=None, ctx=None):
    return {"type": "invoke", "f": "read"}


def ri(test=None, ctx=None):
    return {"type": "invoke", "f": "read-init"}


def cw1(test=None, ctx=None):
    return {"type": "invoke", "f": "write", "value": 1}


def cw2(test=None, ctx=None):
    return {"type": "invoke", "f": "write", "value": 2}


def test(time_limit: float = 60, keys=None) -> dict:
    """Workload package: per-key causal order [ri cw1 r cw2 r] behind
    independent keys, nemesis on a 10s start/stop cycle
    (causal.clj:119-145)."""
    # Bounded stand-in for the reference's infinite (range): the
    # concurrent generator materializes its key list.
    ks = keys if keys is not None else range(10_000)
    return {
        "checker": independent.checker(check(causal_register())),
        "generator": gen.time_limit(
            time_limit,
            gen.clients(
                gen.stagger(1, independent.concurrent_generator(
                    1, ks, lambda k: [gen.once(g)
                                      for g in (ri, cw1, r, cw2, r)])),
                gen.cycle([gen.sleep(10), {"type": "info", "f": "start"},
                           gen.sleep(10), {"type": "info", "f": "stop"}]))),
    }
