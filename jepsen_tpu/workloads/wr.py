"""rw-register workload: write/read txns over a pool of registers.

Mirrors jepsen.tests.cycle.wr (jepsen/src/jepsen/tests/cycle/wr.clj:9-14,
generator backed by elle.rw-register/gen): each op is a transaction of
[f k v] micro-ops, f in {"r","w"}; writes carry unique values (per-key
monotone counters), so the checker can recover writer identity exactly.
"""

from __future__ import annotations

import random

from .. import generator as gen
from ..checker.elle.wr import rw_register_checker


class WrGen:
    """Stateful value factory for rw-register txns; wrapped in a fn
    generator via gen.clients (same pattern as append.AppendGen:
    speculative calls may skip write values, never repeat them)."""

    def __init__(self, key_count: int = 5, min_txn_length: int = 1,
                 max_txn_length: int = 4, max_writes_per_key: int = 256,
                 seed: int | None = None):
        self.key_count = key_count
        self.min_len = min_txn_length
        self.max_len = max_txn_length
        self.max_writes = max_writes_per_key
        self.rng = random.Random(seed)
        self.counters: dict = {}
        self.active: list = list(range(key_count))
        self.next_key = key_count

    def _key(self):
        return self.rng.choice(self.active)

    def __call__(self, test=None, ctx=None):
        txn = []
        for _ in range(self.rng.randint(self.min_len, self.max_len)):
            k = self._key()
            if self.rng.random() < 0.5:
                txn.append(["r", k, None])
            else:
                nxt = self.counters.get(k, 0) + 1
                if nxt > self.max_writes:
                    # retire the key, open a fresh one
                    self.active[self.active.index(k)] = self.next_key
                    k = self.next_key
                    self.next_key += 1
                    nxt = 1
                self.counters[k] = nxt
                txn.append(["w", k, nxt])
        return {"type": "invoke", "f": "txn", "value": txn}


def generator(**opts):
    return gen.clients(WrGen(**opts))


def checker(anomalies=("G2", "G1a", "G1b", "internal"), backend="auto",
            **kw):
    return rw_register_checker(anomalies, backend, **kw)


def test(**opts) -> dict:
    gen_opts = {k: opts.pop(k) for k in
                ("key_count", "min_txn_length", "max_txn_length",
                 "max_writes_per_key", "seed") if k in opts}
    return {"name": "rw-register",
            "generator": generator(**gen_opts),
            "checker": checker(**opts)}
