"""Causal-reverse workload: a strict-serializability anomaly where T1 < T2
in real time, but T2 is visible to a read without T1.

Counterpart of jepsen.tests.causal-reverse
(jepsen/src/jepsen/tests/causal_reverse.clj): blind single-value writes
run concurrently with whole-set reads. Replaying the history builds a
first-order write precedence graph — every write invocation records the
set of writes already acknowledged before it began (graph,
causal_reverse.clj:22-50). A read that contains w_i but misses some
acknowledged predecessor w_j < w_i is an error (errors,
causal_reverse.clj:52-75).
"""

from __future__ import annotations

from typing import Iterable

from .. import generator as gen, independent
from ..checker import Checker, compose


def precedence_graph(history: Iterable[dict]) -> dict:
    """{written-value: frozenset of values acknowledged before its invoke}
    (graph, causal_reverse.clj:22-50)."""
    completed: set = set()
    expected: dict = {}
    for op in history:
        if op.get("f") != "write":
            continue
        if op.get("type") == "invoke":
            expected[op.get("value")] = frozenset(completed)
        elif op.get("type") == "ok":
            completed.add(op.get("value"))
    return expected


def errors(history: Iterable[dict], expected: dict) -> list:
    """Reads whose visible writes imply missing predecessors
    (errors, causal_reverse.clj:52-75)."""
    errs = []
    for op in history:
        if op.get("type") != "ok" or op.get("f") != "read":
            continue
        seen = set(op.get("value") or ())
        our_expected: set = set()
        for v in seen:
            our_expected |= expected.get(v, frozenset())
        missing = our_expected - seen
        if missing:
            errs.append({**{k: v for k, v in op.items() if k != "value"},
                         "missing": sorted(missing, key=repr),
                         "expected-count": len(our_expected)})
    return errs


class CausalReverseChecker(Checker):
    def check(self, test, history, opts):
        expected = precedence_graph(history)
        errs = errors(history, expected)
        return {"valid?": not errs, "errors": errs}


def checker() -> Checker:
    return CausalReverseChecker()


def workload(nodes: list | None = None, per_key_limit: int = 500) -> dict:
    """Generator + checker package (workload, causal_reverse.clj:87-128):
    per key, a mix of whole-set reads and fresh-value writes, n workers
    per key."""
    n = len(nodes or ["n1", "n2", "n3", "n4", "n5"])

    def writes():
        i = 0
        while True:
            yield {"f": "write", "value": i}
            i += 1

    def key_gen(k):
        w = writes()
        return gen.limit(per_key_limit, gen.stagger(
            1 / 100, gen.mix([gen.repeat_gen({"f": "read"}),
                              lambda: next(w)])))

    from ..checker import perf_checker
    return {
        "checker": compose({
            "perf": perf_checker(),
            "sequential": independent.checker(checker()),
        }),
        "generator": independent.concurrent_generator(
            n, range(10_000), key_gen),
    }
