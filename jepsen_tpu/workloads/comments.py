"""Comments workload: strict-serializability write-visibility order.

Counterpart of cockroachdb/src/jepsen/cockroach/comments.clj:1-160 —
the signature check for the anomaly where T1 completes before T2
begins, yet a reader sees T2's insert without T1's (serializable but
not strictly serializable; the "comments appear out of order" story).
Writers blind-insert unique ids for a key across several tables (so
rows land in different shard ranges); readers scan all tables in one
transaction. Replaying the history, every write that COMPLETED before
a visible write was INVOKED must also be visible.
"""

from __future__ import annotations

import itertools

from . import causal_reverse
from .. import generator as gen
from .. import independent
from ..checker import Checker

#: tables the ids are sharded over (comments.clj:30-40's table-count)
TABLE_COUNT = 10


class CommentsChecker(Checker):
    """comments.clj:88-141: expected[w] = writes completed before w's
    invocation; an ok read seeing w but missing some of expected[w]
    is a strict-serializability violation. Same precedence algebra as
    causal-reverse (causal_reverse.clj shares it too), so the graph
    and error scan come from that module; only the truncated error
    rendering is comments-specific."""

    def check(self, test, history, opts):
        expected = causal_reverse.precedence_graph(history)
        errors = causal_reverse.errors(history, expected)
        for e in errors:
            # comments ids are ints; the shared helper repr-sorts to
            # tolerate mixed types, which misorders e.g. [10, 2]
            e["missing"] = sorted(e["missing"])
        return {"valid?": not errors, "errors": errors[:16],
                "error-count": len(errors)}


def checker() -> Checker:
    return CommentsChecker()


def workload(opts: dict | None = None) -> dict:
    """comments.clj:144-160: independent per-key concurrent generator,
    blind writes drawing globally-unique ids (the id picks the table)
    mixed with full-scan reads."""
    opts = opts or {}
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    counter = itertools.count()

    def w(test=None, ctx=None):
        return {"type": "invoke", "f": "write", "value": next(counter)}

    def r(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    from ..checker import compose
    return {
        "generator": independent.concurrent_generator(
            len(nodes), range(10_000),
            lambda k: gen.stagger(
                0.01, gen.limit(200, gen.mix([r, w])))),
        "checker": independent.checker(compose({
            "comments": CommentsChecker()})),
    }
