"""List-append workload: Elle's bread and butter.

Counterpart of jepsen.tests.cycle.append
(jepsen/src/jepsen/tests/cycle/append.clj) + elle.list-append's generator:
transactions of [f k v] micro-ops over named lists, checked for
transactional anomalies by checker.elle.

Generator options (append.clj:41-55):
    key_count            distinct keys active at a time
    min_txn_length       min micro-ops per txn
    max_txn_length       max micro-ops per txn
    max_writes_per_key   appends before a key retires
"""

from __future__ import annotations

import random

from .. import generator as gen
from ..checker import elle


class AppendGen:
    """Stateful value factory (wrapped in a fn generator, so state
    mutation happens only when ops are actually consumed is NOT
    guaranteed — but key rotation/value uniqueness tolerate speculative
    calls: values may skip, never repeat)."""

    def __init__(self, key_count=3, min_txn_length=1, max_txn_length=2,
                 max_writes_per_key=32):
        self.key_count = key_count
        self.min_txn_length = min_txn_length
        self.max_txn_length = max_txn_length
        self.max_writes_per_key = max_writes_per_key
        self.next_key = key_count
        self.active = list(range(key_count))
        self.writes = {k: 0 for k in self.active}
        self.next_val = 0

    def txn(self) -> list:
        mops = []
        n = random.randint(self.min_txn_length, self.max_txn_length)
        for _ in range(n):
            k = random.choice(self.active)
            if random.random() < 0.5:
                self.writes[k] = self.writes.get(k, 0) + 1
                if self.writes[k] > self.max_writes_per_key:
                    self.active.remove(k)
                    k = self.next_key
                    self.next_key += 1
                    self.active.append(k)
                    self.writes[k] = 1
                self.next_val += 1
                mops.append(["append", k, self.next_val])
            else:
                mops.append(["r", k, None])
        return mops

    def __call__(self, test=None, ctx=None):
        return {"type": "invoke", "f": "txn", "value": self.txn()}


def generator(**opts):
    return gen.clients(AppendGen(**opts))


def checker(anomalies=("G1", "G2"), backend="auto", **kw):
    return elle.append_checker(anomalies=anomalies, backend=backend, **kw)


def test(**opts) -> dict:
    """Partial test map (append.clj:31-57)."""
    checker_opts = {k: opts.pop(k) for k in
                    ("anomalies", "backend", "realtime", "process_order")
                    if k in opts}
    return {"generator": generator(**opts),
            "checker": checker(**checker_opts)}
