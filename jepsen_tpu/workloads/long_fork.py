"""Long-fork workload: a parallel-snapshot-isolation anomaly where
concurrent writes are observed in conflicting orders by different reads.

Counterpart of jepsen.tests.long-fork
(jepsen/src/jepsen/tests/long_fork.clj). Writes are single-key inserts
``[["w", k, 1]]``, each key written at most once; reads scan a whole
*group* of n consecutive keys. Reads over the same group must form a
total order under "dominates" comparison (nil -> value transitions only);
two mutually incomparable reads are a long fork (read-compare
long_fork.clj:210-246; find-forks 268-276).
"""

from __future__ import annotations

import random
from typing import Any, Sequence

from .. import generator as gen
from ..checker import Checker


def group_for(n: int, k: int) -> range:
    """The n keys of k's group: [k - k%n, k - k%n + n) (long_fork.clj:100-106)."""
    lo = k - (k % n)
    return range(lo, lo + n)


def read_txn_for(n: int, k: int, rng: random.Random | None = None) -> list:
    """A txn reading k's whole group in shuffled order (long_fork.clj:108-114)."""
    ks = list(group_for(n, k))
    (rng or random).shuffle(ks)
    return [["r", kk, None] for kk in ks]


class LongForkGen(gen.Generator):
    """Single inserts followed by group reads from the same worker, mixed
    with reads of other in-flight groups (Generator long_fork.clj:116-151).

    State: next_key counter + {worker: last-written-key} + a step seed.
    Randomness is derived afresh from the seed each op() so a re-invoked
    op() on the same state yields the same op (pure-generator contract);
    every successor state carries seed+1."""

    def __init__(self, n: int, next_key: int = 0,
                 workers: dict | None = None, seed: int = 0):
        self.n = n
        self.next_key = next_key
        self.workers = workers or {}
        self.seed = seed

    def op(self, test, ctx):
        worker = next((t for t in ctx.free_threads if t != gen.NEMESIS), None)
        if worker is None:
            return gen.PENDING, self
        rng = random.Random(f"long-fork:{self.seed}:{worker}")
        process = ctx.thread_to_process(worker)
        k = self.workers.get(worker)
        if k is not None:
            # We wrote a key: read its group and clear our slot.
            o = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": read_txn_for(self.n, k, rng)}, ctx)
            return o, LongForkGen(self.n, self.next_key,
                                  {**self.workers, worker: None},
                                  seed=self.seed + 1)
        active = [v for v in self.workers.values() if v is not None]
        if active and rng.random() < 0.5:
            k = rng.choice(active)
            o = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": read_txn_for(self.n, k, rng)}, ctx)
            return o, LongForkGen(self.n, self.next_key, self.workers,
                                  seed=self.seed + 1)
        o = gen.fill_in_op(
            {"process": process, "f": "write",
             "value": [["w", self.next_key, 1]]}, ctx)
        return o, LongForkGen(self.n, self.next_key + 1,
                              {**self.workers, worker: self.next_key},
                              seed=self.seed + 1)


def generator(n: int = 2) -> gen.Generator:
    return LongForkGen(n)


class IllegalHistory(Exception):
    def __init__(self, info: dict):
        self.info = info
        super().__init__(str(info))


def read_op_value_map(op: dict) -> dict:
    """read txn -> {key: value} (long_fork.clj:248-257)."""
    return {m[1]: m[2] for m in (op.get("value") or [])}


def read_compare(a: dict, b: dict) -> int | None:
    """-1 if a dominates, 0 equal, 1 if b dominates, None incomparable.
    Values change only nil -> written-once value (long_fork.clj:210-246)."""
    if len(a) != len(b):
        raise IllegalHistory({"type": "illegal-history", "reads": [a, b],
                              "msg": "reads query different keys"})
    res = 0
    for k, va in a.items():
        if k not in b:
            raise IllegalHistory({"type": "illegal-history", "reads": [a, b],
                                  "key": k,
                                  "msg": "reads query different keys"})
        vb = b[k]
        if va == vb:
            continue
        if vb is None:           # a saw a value b didn't: a dominates here
            if res > 0:
                return None
            res = -1
        elif va is None:         # b dominates here
            if res < 0:
                return None
            res = 1
        else:
            raise IllegalHistory(
                {"type": "illegal-history", "key": k, "reads": [a, b],
                 "msg": "distinct non-nil values for one key; "
                        "keys are written once"})
    return res


def is_read_txn(txn) -> bool:
    return all(m[0] == "r" for m in (txn or []))


def is_write_txn(txn) -> bool:
    return bool(txn) and len(txn) == 1 and txn[0][0] == "w"


def find_forks(ops: Sequence[dict]) -> list:
    """All mutually incomparable read pairs in one group
    (long_fork.clj:259-276)."""
    forks = []
    for i in range(len(ops)):
        ma = read_op_value_map(ops[i])
        for j in range(i + 1, len(ops)):
            if read_compare(ma, read_op_value_map(ops[j])) is None:
                forks.append([ops[i], ops[j]])
    return forks


# Past this many reads in one group the pairwise python comparator is
# replaced by the vectorized matmul formulation below.
VECTORIZE_THRESHOLD = 64


def find_forks_vectorized(ops: Sequence[dict]) -> list:
    """find_forks as one boolean matmul (SURVEY.md §5.7's blockwise
    long-fork search for 100k-op histories, BASELINE config #5).

    Keys are written once with value 1, so a read of a group is a 0/1
    vector V[i] over the group's keys (1 = observed). Read i strictly
    dominates j on some key iff (V @ (1-V)^T)[i, j] > 0; a long fork is
    a pair dominating each other: G & G^T. Value/shape validation stays
    host-side (read_op_value_map raises on distinct non-nil values the
    same way the pairwise route does)."""
    import numpy as np

    if len(ops) < 2:
        return []
    keys = sorted(read_op_value_map(ops[0]),
                  key=lambda k: (str(type(k)), str(k)))
    maps = [read_op_value_map(o) for o in ops]
    for m in maps[1:]:
        if set(m) != set(keys):
            raise IllegalHistory(
                {"type": "illegal-history", "reads": [maps[0], m],
                 "msg": "reads query different keys"})
    # exact parity with read_compare's error rule: a key may show ONE
    # non-nil value across all reads (keys are written once); two
    # distinct non-nil values is an illegal history
    for k in keys:
        distinct = {m[k] for m in maps if m[k] is not None}
        if len(distinct) > 1:
            raise IllegalHistory(
                {"type": "illegal-history", "key": k,
                 "reads": [m for m in maps if m[k] is not None][:2],
                 "msg": "distinct non-nil values for one key; "
                        "keys are written once"})
    V = np.asarray([[0 if m[k] is None else 1 for k in keys]
                    for m in maps], dtype=np.float32)
    W = 1.0 - V
    R = len(maps)
    block = 4096                       # memory stays O(block * R)
    forks = []
    for lo in range(0, R, block):
        hi = min(lo + block, R)
        A = (V[lo:hi] @ W.T) > 0       # i saw a key j missed
        B = (W[lo:hi] @ V.T) > 0       # j saw a key i missed
        F = A & B                      # mutual: a long fork
        for il, j in zip(*np.nonzero(F)):
            i = lo + int(il)
            if i < j:                  # each unordered pair once
                forks.append([ops[i], ops[int(j)]])
    return forks


def groups(n: int, read_ops: Sequence[dict]) -> list[list[dict]]:
    """Partition reads by their key set; each must cover exactly n keys
    (long_fork.clj:288-314)."""
    by_keys: dict[frozenset, list] = {}
    for op in read_ops:
        ks = frozenset(m[1] for m in (op.get("value") or []))
        by_keys.setdefault(ks, []).append(op)
    for ks, ops in by_keys.items():
        if len(ks) != n:
            raise IllegalHistory(
                {"type": "illegal-history", "op": ops[0],
                 "msg": f"every read should observe exactly {n} keys, "
                        f"got {len(ks)}"})
    return list(by_keys.values())


class LongForkChecker(Checker):
    """Verifies single-write keys, then searches every read group for
    incomparable pairs (checker long_fork.clj:363-378)."""

    def __init__(self, n: int):
        self.n = n

    def check(self, test, history, opts):
        reads = [o for o in history
                 if o.get("type") == "ok" and is_read_txn(o.get("value"))]
        early = [v for v in (o.get("value") for o in reads)
                 if not any(m[2] is not None for m in v)]
        late = [v for v in (o.get("value") for o in reads)
                if all(m[2] is not None for m in v)]
        base = {"reads-count": len(reads),
                "early-read-count": len(early),
                "late-read-count": len(late)}
        # multiple writes to one key -> unknown (long_fork.clj:327-342)
        seen: set = set()
        for o in history:
            if o.get("type") == "invoke" and is_write_txn(o.get("value")):
                k = o["value"][0][1]
                if k in seen:
                    return {**base, "valid?": "unknown",
                            "error": ["multiple-writes", k]}
                seen.add(k)
        try:
            forks = [f for g in groups(self.n, reads)
                     for f in (find_forks_vectorized(g)
                               if len(g) > VECTORIZE_THRESHOLD
                               else find_forks(g))]
        except IllegalHistory as e:
            return {**base, "valid?": "unknown", "error": e.info}
        if forks:
            return {**base, "valid?": False, "forks": forks}
        return {**base, "valid?": True}


def checker(n: int = 2) -> Checker:
    return LongForkChecker(n)


def workload(n: int = 2) -> dict:
    """{"checker", "generator"} package (long_fork.clj:380-385)."""
    return {"checker": checker(n), "generator": generator(n)}
