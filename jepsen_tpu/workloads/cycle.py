"""Generic cycle-detection workload: run any user-supplied dependency
analyzer over a history and fail on cycles.

Counterpart of jepsen.tests.cycle (jepsen/src/jepsen/tests/cycle.clj),
which wraps ``elle.core/check {:analyzer f}``. Here the analyzer is a
function ``history -> (edges, explain)`` where ``edges`` is an iterable
of (from-index, to-index, type) triples over indexed ops; cycles are
found by SCC over that graph (the same engine the Elle checkers use).
"""

from __future__ import annotations

from typing import Callable, Iterable

from .. import history as h
from ..checker import Checker
from ..checker.elle.graph import tarjan_scc


class CycleChecker(Checker):
    """Checker over a custom analyzer (cycle.clj:9-16)."""

    def __init__(self, analyzer: Callable):
        self.analyzer = analyzer

    def check(self, test, history, opts):
        history = h.index(list(history))
        out = self.analyzer(history)
        edges, explain = out if isinstance(out, tuple) else (out, None)
        n = len(history)
        adj: list[list[int]] = [[] for _ in range(n)]
        for e in edges:
            adj[e[0]].append(e[1])
        scc_ids = tarjan_scc(n, adj)
        comps: dict[int, list[int]] = {}
        for i, cid in enumerate(scc_ids):
            comps.setdefault(cid, []).append(i)
        sccs = [c for c in comps.values() if len(c) > 1]
        cycles = []
        for comp in sccs:
            comp = sorted(comp)
            cyc = {"ops": [history[i] for i in comp]}
            if explain is not None:
                cyc["explanation"] = explain(comp)
            cycles.append(cyc)
        return {"valid?": not cycles, "cycles": cycles,
                "scc-count": len(sccs)}


def checker(analyzer: Callable) -> Checker:
    return CycleChecker(analyzer)
