"""Queue workload: enqueue unique values, dequeue concurrently, drain
at the end; nothing may be lost (acknowledged enqueues) or invented.

Counterpart of the queue workloads in the rabbitmq/disque suites
(rabbitmq/src/jepsen/rabbitmq.clj, disque/src/jepsen/disque.clj) over
the total-queue checker (checker.clj:631-690). Ops:

    {"f": "enqueue", "value": v}
    {"f": "dequeue"}            -> ok value = v | fail "empty"
    {"f": "drain"}              -> ok value = [v, ...]
"""

from __future__ import annotations

import itertools

from .. import checker as jchecker
from .. import generator as gen


def generator(n: int | None = None):
    """The enqueue/dequeue mix ONLY — suites must run final_generator()
    AFTER their time limit, or an expiring clock cuts the drain and
    every in-flight element reads as lost (the reference puts the
    drain outside gen/time-limit for exactly this reason,
    disque.clj:275-296)."""
    counter = itertools.count()

    def enqueue(test=None, ctx=None):
        return {"type": "invoke", "f": "enqueue", "value": next(counter)}

    def dequeue(test=None, ctx=None):
        return {"type": "invoke", "f": "dequeue", "value": None}

    body = gen.mix([enqueue, dequeue])
    if n is not None:
        body = gen.limit(n, body)
    return gen.clients(body)


def final_generator():
    """Post-time-limit drain phase: every client drains until ok."""
    return gen.clients(gen.until_ok(gen.repeat_gen({"f": "drain"})))


def test(n: int | None = 500, **kw) -> dict:
    return {"generator": generator(n),
            "final_generator": final_generator(),
            "checker": jchecker.total_queue(),
            **kw}
