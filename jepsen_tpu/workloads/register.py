"""Linearizable register workload.

Counterpart of jepsen.tests.linearizable-register
(jepsen/src/jepsen/tests/linearizable_register.clj:23-60): independent
CAS registers per key, a read/write/cas op mix, and a per-key
linearizability check against the CAS-register model.

TPU-first twist: with checker backend="tpu" the per-key subhistories
batch into one padded event-tensor dispatch through
checker.knossos.kernels instead of a thread-pool of searches.
"""

from __future__ import annotations

import random

from .. import generator as gen
from .. import independent
from ..checker import linearizable, models


def r(test=None, ctx=None):
    return {"type": "invoke", "f": "read", "value": None}


def w(test=None, ctx=None):
    return {"type": "invoke", "f": "write", "value": random.randint(0, 4)}


def cas(test=None, ctx=None):
    return {"type": "invoke", "f": "cas",
            "value": [random.randint(0, 4), random.randint(0, 4)]}


def rand_op(test=None, ctx=None):
    return random.choice((r, w, cas))(test, ctx)


def generator(threads_per_key: int = 2, key_count: int = 10,
              ops_per_key: int = 100, ops=None):
    """Concurrent per-key generators over a rotating key space
    (linearizable_register.clj:34-50). `ops` restricts the op mix
    (e.g. ``[r, w]`` for stores without CAS)."""
    op_gen = rand_op if ops is None else gen.mix(list(ops))
    return independent.concurrent_generator(
        threads_per_key, range(key_count),
        lambda k: gen.limit(ops_per_key, op_gen))


def checker(backend: str = "auto", algorithm: str = "competition",
            model=None):
    return independent.checker(
        linearizable(model if model is not None else models.cas_register(),
                     algorithm=algorithm, backend=backend))


def test(threads_per_key: int = 2, key_count: int = 10,
         ops_per_key: int = 100, backend: str = "auto") -> dict:
    return {"generator": gen.clients(
                generator(threads_per_key, key_count, ops_per_key)),
            "checker": checker(backend=backend)}
