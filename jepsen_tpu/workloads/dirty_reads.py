"""Dirty-reads workload: failed writers must stay invisible.

Counterpart of galera/src/jepsen/galera/dirty_reads.clj:1-120 and the
byte-identical percona twin (percona/src/jepsen/percona/dirty_reads.clj)
— both reference suites exist essentially FOR this check. Writers
compete to set every row of a table to a unique per-transaction value;
readers concurrently scan the whole table. Any read that observes a
value written by a *failed* transaction is a dirty read (ANSI P1 /
Adya G1a); a read whose rows disagree with each other additionally
witnesses a non-atomic write (fractured read).

The generator mirrors the reference's `(gen/mix [reads writes])` with
writes drawing unique values from an infinite counter
(dirty_reads.clj:96-103); the checker mirrors its failed-writes /
inconsistent-reads / filthy-reads classification
(dirty_reads.clj:75-94).
"""

from __future__ import annotations

import itertools

from .. import generator as gen
from ..checker import Checker


def generator():
    counter = itertools.count()

    def write(test=None, ctx=None):
        return {"type": "invoke", "f": "write", "value": next(counter)}

    def read(test=None, ctx=None):
        return {"type": "invoke", "f": "read", "value": None}

    return gen.clients(gen.mix([read, write]))


class DirtyReadsChecker(Checker):
    """Flags ok reads containing any failed write's value
    (dirty_reads.clj:75-94). `info` writes are indeterminate — they may
    have committed — so only definite `fail` values count as dirty."""

    def check(self, test, history, opts):
        failed_writes = {op.get("value") for op in history
                         if op.get("type") == "fail"
                         and op.get("f") == "write"}
        reads = [op for op in history
                 if op.get("type") == "ok" and op.get("f") == "read"
                 and isinstance(op.get("value"), (list, tuple))]
        inconsistent = [op for op in reads
                        if len(set(op["value"])) > 1]
        dirty = [op for op in reads
                 if failed_writes.intersection(op["value"])]
        return {"valid?": not dirty,
                "failed-write-count": len(failed_writes),
                "read-count": len(reads),
                "inconsistent-reads": inconsistent[:16],
                "inconsistent-count": len(inconsistent),
                "dirty-reads": dirty[:16],
                "dirty-count": len(dirty)}


def checker() -> Checker:
    return DirtyReadsChecker()


def workload(**opts) -> dict:
    # compose {:perf :dirty-reads} like the reference's test-
    # (dirty_reads.clj:113-117)
    from ..checker import compose, perf_checker
    return {"generator": generator(),
            "checker": compose({"dirty-reads": checker(),
                                "perf": perf_checker()})}
