"""The analysis data plane: mesh construction and sharded batch checking.

The reference's only distribution mechanism is SSH fan-out on the control
plane (SURVEY.md §5.8) — analysis is single-JVM. This module is the
north-star addition: history batches are sharded over a TPU device mesh
with named axes

  dp  data parallel over histories (the primary axis, SURVEY.md §2.5)
  mp  model parallel within one history: the [T,T] adjacency/closure
      matrices are column-sharded, so each closure matmul runs as a
      distributed dense matmul with XLA inserting the collectives over
      ICI (the sequence-parallel analogue for long histories)

The batched formulation here (explicit [B,T,T] einsum instead of vmap)
exists so sharding constraints can be placed on the matrices themselves.
"""

from __future__ import annotations

import functools
import logging
import math
import os
import queue as _queue
import threading
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import planner as _planner
from .. import supervisor as sv
from .. import trace
from ..obs import device as obs_device
from ..obs import events as obs_events
from ..checker.elle import kernels as K
from ..devices import default_devices, ensure_platform_pin
from . import residency

ensure_platform_pin()
from ..util import pad_to_multiple

log = logging.getLogger(__name__)


def factor2(n: int) -> tuple[int, int]:
    """Split n into (a, b), a*b == n, as square as possible, a >= b."""
    b = int(math.isqrt(n))
    while n % b:
        b -= 1
    return n // b, b


def make_mesh(devices: Sequence | None = None,
              axes: tuple[str, str] = ("dp", "mp")) -> Mesh:
    """A 2-D device mesh: data parallel over histories × model parallel
    within a history's closure matmuls."""
    devices = list(devices if devices is not None else default_devices())
    dp, mp = factor2(len(devices))
    return Mesh(np.asarray(devices).reshape(dp, mp), axes)


def host_local_mesh() -> Mesh:
    """A dp×mp mesh over THIS process's local devices only — the
    per-shard dispatch mesh of `analyze-store --mesh`. On a
    distributed job `make_mesh()`'s default devices span every host,
    but a mesh-sweep shard checks ITS OWN run dirs on ITS OWN chips:
    the cross-host axis is the deterministic shard split of the store,
    never a global dispatch (host-local batches aren't addressable on
    a cross-process mesh without collective array assembly, and the
    shard split already extracts the parallelism)."""
    import jax
    return make_mesh(jax.local_devices())


def init_distributed() -> bool:
    """Join a multi-host analysis job (SURVEY.md §5.8's DCN plane):
    when JAX_COORDINATOR_ADDRESS (or COORDINATOR_ADDRESS) is set —
    optionally with JAX_NUM_PROCESSES/JAX_PROCESS_ID — initialize
    jax.distributed so `jax.devices()` spans every host's chips and
    the dp×mp meshes built here shard across ICI within a slice and
    DCN between them. Called by analyze-store and the bench before any
    device work. Returns True when distributed mode came up; a
    single-process run (no coordinator env) returns False and
    everything behaves as before. Idempotent."""
    import os

    if not (os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")):
        return False
    try:
        if jax._src.distributed.global_state.client is not None:
            return True  # already initialized
    except Exception:
        pass
    kw = {}
    addr = (os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS"))
    if addr:
        kw["coordinator_address"] = addr
    if os.environ.get("JAX_NUM_PROCESSES"):
        kw["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
    if os.environ.get("JAX_PROCESS_ID"):
        kw["process_id"] = int(os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(**kw)
    return True


def sharded_check_fn(mesh: Mesh | None, shape: K.BatchShape, *,
                     classify: bool = True, realtime: bool = False,
                     process_order: bool = False,
                     use_pallas: bool | None = None,
                     use_int8: bool | None = None,
                     fused: bool | None = None,
                     donate: bool = False,
                     with_stats: bool = False):
    """Build a jitted batched checker around kernels.check_batched_impl.
    With a mesh, inputs are expected sharded over 'dp' and the closure
    matrices are constrained to P('dp', None, 'mp'); without one, it's
    a plain single-device jit. The closure squaring defaults to the
    XLA matmul pipeline on every backend — the formulation the v5e
    hardware race picked (the fused Pallas kernel measured ~2.7×
    slower at the 5000-txn headline shape; `JEPSEN_TPU_CLOSURE=
    pallas[-int8]` re-enables it as an experiment, and benchmarks
    pass explicit bools to race the formulations). use_int8 switches
    the squaring dots to int8×int8→int32 — exact for the boolean
    closure — and composes with use_pallas (the VMEM fusion and the
    arithmetic are orthogonal levers). Mesh dispatches always stay
    XLA so the compiler can insert collectives. Explicit arguments
    win over the env. Memoized per (mesh, shape, flags) so repeated
    same-shape dispatches (bucketed sweeps, per-key loops) compile
    once."""
    if use_pallas and mesh is not None:
        # the Pallas squaring path bypasses the P('dp',None,'mp')
        # sharding constraint and would silently degrade sharded
        # layouts; sharded dispatch always uses the XLA formulation
        raise ValueError("use_pallas=True is single-device only: "
                         "sharded dispatch uses the XLA closure path")
    use_pallas, use_int8 = K.resolve_formulation(
        use_pallas, use_int8, single_device=mesh is None)
    if fused is None:
        fused = K.fused_classify_enabled()
    # fused only exists in classify mode; normalize so detect-mode
    # dispatches never compile twice over an irrelevant flag
    fused = bool(fused) and classify
    # donation is a dispatch-layer contract (the caller must treat its
    # input arrays as consumed) — normalize it away under a mesh so
    # the flag can't split the compile cache for sharded dispatches
    donate = bool(donate) and mesh is None
    return _sharded_check_fn_cached(mesh, shape, classify, realtime,
                                    process_order, use_pallas, use_int8,
                                    fused, donate, bool(with_stats))


# Executable residency + donated-slot ownership live in
# parallel.residency (the split ROADMAP items 1 and 2 share: the mesh
# sweep's per-shard dispatch loops and the future serve daemon both
# hold executables and donated buffers resident without re-owning this
# bookkeeping). The dispatcher below is pure scheduling; these two
# objects are its residency/ownership seams.
_residency = residency.ExecutableResidency()
_slots = residency.DeviceSlots()


@functools.lru_cache(maxsize=64)
def _sharded_check_fn_cached(mesh: Mesh | None, shape: K.BatchShape,
                             classify: bool, realtime: bool,
                             process_order: bool,
                             use_pallas: bool = False,
                             use_int8: bool = False,
                             fused: bool = False,
                             donate: bool = False,
                             with_stats: bool = False):
    if mesh is not None:
        spec = P("dp", None, "mp")

        def constrain(x):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec))
    else:
        def constrain(x):
            return x

    f = functools.partial(
        K.check_batched_impl, n_keys=shape.n_keys, max_pos=shape.max_pos,
        n_txns=shape.n_txns, steps=K.closure_steps(shape.n_txns),
        classify=classify, realtime=realtime, process_order=process_order,
        constrain=constrain, use_pallas=use_pallas, use_int8=use_int8,
        fused=fused, with_stats=with_stats)
    if mesh is None:
        if donate:
            # donated inputs: XLA reuses the six packed tensors' HBM
            # for the closure scratch instead of allocating fresh —
            # the caller's arrays are CONSUMED by the call.
            return jax.jit(f, donate_argnums=tuple(range(6)))
        return jax.jit(f)
    in_shard = NamedSharding(mesh, P("dp"))
    out_shard = NamedSharding(mesh, P("dp"))
    return jax.jit(f, in_shardings=(in_shard,) * 6, out_shardings=out_shard)


def shard_batch(mesh: Mesh | None, packed: dict) -> tuple:
    """Device-put packed batch arrays, sharded over dp when a mesh is
    given. Returns the 6 positional args for the check fn.

    A views-packed dict (kernels.pack_batch_views — the v2-sidecar
    warm path) carries per-history mmap views instead of stacked
    arrays: each view is device_put straight from the mapped pages,
    ragged views (a history padded to a smaller geometry than the
    bucket max) are padded ON DEVICE with the pack fill convention,
    and the batch axis is assembled in HBM (jnp.stack over device
    arrays) — the host copies zero bytes between the sidecar and the
    device. `h2d_bytes` counts what crossed to the device either
    way."""
    tr = trace.get_current()
    names = ("appends", "reads", "invoke_index", "complete_index",
             "process", "n_txns")
    if packed.get("views"):
        shape: K.BatchShape = packed["shape"]
        targets = {"appends": (shape.n_appends, 3),
                   "reads": (shape.n_reads, 3),
                   "invoke_index": (shape.n_txns,),
                   "complete_index": (shape.n_txns,),
                   "process": (shape.n_txns,)}
        fills = {"appends": -1, "reads": -1, "process": -1,
                 "invoke_index": 0, "complete_index": 0}
        args = []
        nbytes = 0
        for k in names[:-1]:
            tgt, fill = targets[k], fills[k]
            parts = []
            for v in packed[k]:
                nbytes += v.nbytes
                dv = jax.device_put(v)
                if v.shape != tgt:
                    dv = jnp.pad(dv,
                                 [(0, t - s)
                                  for s, t in zip(v.shape, tgt)],
                                 constant_values=fill)
                parts.append(dv)
            args.append(jnp.stack(parts))
        args.append(jnp.asarray(packed["n_txns"]))
        if tr.enabled:
            tr.counter("h2d_bytes").inc(nbytes)
        return tuple(args)
    args = [jnp.asarray(packed[k]) for k in names]
    if tr.enabled:
        tr.counter("h2d_bytes").inc(
            sum(packed[k].nbytes for k in names))
    if mesh is not None:
        s = NamedSharding(mesh, P("dp"))
        args = [jax.device_put(a, s) for a in args]
    return tuple(args)


# ---------------------------------------------------------------------------
# Long single histories: sequence-parallel checking (SURVEY.md §5.7).
# ---------------------------------------------------------------------------

def sp_mesh(devices: Sequence | None = None) -> Mesh:
    """A 1×N mesh dedicating the WHOLE slice to one history: dp is
    trivial, and the [T,T] adjacency/closure matrices are column-sharded
    over every device, so each closure matmul is a distributed dense
    matmul with XLA moving the halo over ICI — the context-parallel
    analogue for op-axis sharding."""
    devices = list(devices if devices is not None else default_devices())
    return Mesh(np.asarray(devices).reshape(1, len(devices)), ("dp", "mp"))


# Above this txn count the dense [T,T] closure no longer fits a slice's
# HBM; check_long_history switches to SCC condensation (elle.condense).
DENSE_TXN_LIMIT = 32_768


def check_long_history(enc, mesh: Mesh | None = None, *,
                       classify: bool = True, realtime: bool = False,
                       process_order: bool = False,
                       dense_limit: int = DENSE_TXN_LIMIT,
                       stats_out: list | None = None) -> dict:
    """Check ONE long encoded history; returns {anomaly: True} flags.

    Up to `dense_limit` txns: the dense closure with the op axis
    column-sharded across the mesh (the CP analogue). Beyond it: host
    SCC condensation (vectorized edge build + native Tarjan) feeding
    the device classification kernel per nontrivial SCC — the 100k-op
    path (BASELINE config #5), exact by SCC-locality of every anomaly
    query (elle/condense.py module doc).

    `stats_out` (a list) gains one stats dict for the history —
    device-computed on the dense path, host-derived (edge/SCC facts
    from the condensation's own Tarjan, no closure telemetry) past
    the dense limit."""
    if enc.n > dense_limit:
        from ..checker.elle import condense
        return condense.check_condensed(
            enc, classify=classify, realtime=realtime,
            process_order=process_order,
            devices=(list(mesh.devices.flat) if mesh is not None
                     else None), stats_out=stats_out)
    mesh = mesh if mesh is not None else sp_mesh()
    shape = K.BatchShape.plan([enc])
    packed = K.pack_batch([enc], shape)
    with_stats = stats_out is not None
    fn = sharded_check_fn(mesh, shape, classify=classify,
                          realtime=realtime, process_order=process_order,
                          with_stats=with_stats)
    args = shard_batch(mesh, packed)
    out = fn(*args)
    pending, dev_stats = out if with_stats else (out, None)
    # window opens AFTER the enqueue returns (first-call compile is
    # host time, not device time — same contract as the bucket path)
    t_disp = time.perf_counter()
    flags = np.asarray(_block_flags(pending, trace.get_current()))
    trace.get_current().device_complete("long-history", t_disp,
                                        txns=enc.n)
    if with_stats:
        stats_out.append(K.stats_row(np.asarray(dev_stats)[0],
                                     n_txns=enc.n,
                                     t_pad=shape.n_txns))
    return K.flags_to_names(int(flags[0]))


# ---------------------------------------------------------------------------
# Device-memory-aware batch scheduling (SURVEY.md §2.5): histories are
# bucketed by padded length so each dispatch's B·T² closure footprint
# stays under a budget, instead of padding everything to the longest.
# ---------------------------------------------------------------------------

def _size_of(e) -> int:
    """Txn count of an encoded history (attribute) or packed edge dict
    (key) — both bucket the same way."""
    return e["n"] if isinstance(e, dict) else e.n


def bucket_by_length(encs: Sequence, *, multiple: int = 128,
                     budget_cells: int = 1 << 27,
                     dp: int = 1) -> list[list[int]]:
    """Partition history indices into buckets of similar padded txn
    count. Each bucket satisfies B_pad * T_pad² <= budget_cells, where
    T_pad is the bucket max rounded up to `multiple` and B_pad is the
    bucket size rounded up to a multiple of `dp` (dispatchers pad
    ragged buckets to a dp multiple, so that headroom must be budgeted
    here, not discovered at dispatch). Returns buckets of indices into
    encs, longest histories first. Elements may be EncodedHistory-like
    (`.n`) or packed edge dicts (`["n"]`)."""
    order = sorted(range(len(encs)), key=lambda i: -_size_of(encs[i]))
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_tpad = 0
    for i in order:
        tpad = max(K.pad_to(max(_size_of(encs[i]), 1), multiple), 1)
        t = max(cur_tpad, tpad)
        b_pad = -(-(len(cur) + 1) // dp) * dp
        if cur and b_pad * t * t > budget_cells:
            buckets.append(cur)
            cur, cur_tpad = [], 0
            t = tpad
        cur.append(i)
        cur_tpad = t
    if cur:
        buckets.append(cur)
    return buckets


def _acc_phase(phases: dict | None, key: str, t0: float) -> None:
    """Accumulate a wall-clock span into a caller-supplied phase dict —
    the sweep-attribution hook (every host second of a bucketed sweep
    lands in exactly one named phase). Now a thin adapter over
    jepsen_tpu.trace spans: the duration is recorded ONCE (a completed
    phase span in the current tracer, feeding trace.json and
    `phase_totals`) and the same number lands in the legacy `phases`
    dict, so bench parity is exact by construction."""
    dt = trace.get_current().phase(key, t0)
    if phases is not None:
        phases[key] = phases.get(key, 0.0) + dt


class PendingVerdicts:
    """Verdicts still in flight: `check_bucketed_async` queues every
    bucket's device dispatch without a host sync, so the caller can
    overlap ingest/packing of the NEXT chunk with the device's work on
    this one. `.result()` blocks, pulls the flag words D2H and returns
    per-history {anomaly: True} dicts in input order (a history the
    supervisor abandoned yields its `supervisor.Quarantined` sentinel
    instead of a flags dict — callers render it as `valid? unknown`)."""

    def __init__(self, n: int, parts: list, finish=None):
        self._n = n
        # [(bucket indices, flags, dispatch-enqueue time|None,
        #   donated, smeta)] — flags is a live device array, or
        # (already resolved) a list of per-history flag words /
        # (word, stats-dict) pairs / Quarantined aligned with indices;
        # `donated` marks a dispatch holding a device-slot ledger
        # entry the finish closure must release; `smeta` is None or
        # (device stats matrix, BatchShape) for a kernel-stats
        # dispatch (JEPSEN_TPU_KERNEL_STATS)
        self._parts = parts
        # finish(idx, device_flags) -> resolved list: the dispatcher's
        # watchdog + OOM-backdown closure; None (bare construction)
        # blocks plainly with no recovery.
        self._finish = finish
        self._result: list | None = None
        self._stats: list = [None] * n

    def is_ready(self) -> bool:
        """True when every bucket's flags have materialized (no block):
        lets callers close an honest device-in-flight window — a chunk
        whose flags are already ready before the next host stall must
        not count that stall as pipeline overlap."""
        return all(getattr(f, "is_ready", lambda: True)()
                   for _, f, _, _, _ in self._parts)

    def stats(self) -> list:
        """Per-history `kernels.stats_row` dicts aligned with the
        verdict list (None for histories whose dispatch carried no
        stats: gate off, quarantined, or resolved through the OOM/
        watchdog backdown whose retries run stats-free). Only
        populated after `.result()`."""
        return self._stats

    def result(self, phases: dict | None = None) -> list[dict]:
        # Idempotent: callers can observe readiness and collect from
        # more than one code path (the bench's is_ready fast path plus
        # its end-of-loop drain); a second call returns the SAME
        # verdict list and accumulates NO extra "collect" time,
        # instead of returning all-Nones and double-counting.
        if self._result is not None:
            return self._result
        t0 = time.perf_counter()
        tr = trace.get_current()
        out: list[dict | None] = [None] * self._n
        for idx, flags, t_disp, donated, smeta in self._parts:
            if not isinstance(flags, list):
                if self._finish is not None:
                    # the finish closure owns the device window (logged
                    # on its success path only — a recovered bucket's
                    # device time is the backdown's own windows)
                    flags = self._finish(idx, flags, t_disp, donated,
                                         smeta)
                else:
                    arr = np.asarray(jax.block_until_ready(flags))
                    # padded replicas (flags beyond the bucket's own
                    # indices) are dropped here
                    flags = [int(w) for w in arr[:len(idx)]]
                    # dispatch->materialized delta on the device track
                    # (parts already resolved by the back-pressure
                    # loop carry None)
                    tr.device_complete("bucket", t_disp,
                                       histories=len(idx))
            for i, w in zip(idx, flags):
                if isinstance(w, tuple):
                    w, self._stats[i] = w
                out[i] = (w if isinstance(w, sv.Quarantined)
                          else K.flags_to_names(int(w)))
        self._parts = []
        tr.gauge("inflight_depth").set(0)   # fully drained
        _acc_phase(phases, "collect", t0)
        self._result = out
        return out  # type: ignore[return-value]


def pack_thread_enabled() -> bool:
    """One home for the JEPSEN_TPU_PACK_THREAD gate (default on):
    check_bucketed_async moves bucket packing + device_put onto a
    dedicated worker thread so the parent's critical path is only the
    async kernel enqueue and h2d overlaps device compute. 0 keeps
    everything inline on the calling thread."""
    from .. import gates
    return gates.get("JEPSEN_TPU_PACK_THREAD")


def _est_cells(encs: Sequence, bucket: list[int], dp: int) -> int:
    """The padded footprint bucket_by_length budgeted for this bucket
    (B rounded to a dp multiple x T_pad²) — computable before packing,
    so the dispatcher can spot buckets that exceed the per-slot budget
    (only possible for a single history too big to subdivide)."""
    tpad = max(K.pad_to(max(_size_of(encs[i]) for i in bucket), 128), 1)
    return -(-len(bucket) // dp) * dp * tpad * tpad


def _prep_bucket(encs: Sequence, bucket: list[int], mesh: Mesh | None,
                 dp: int, budget_cells: int, tr,
                 phases: dict | None) -> tuple:
    """Host-side packing of one bucket (pack phase): group selection,
    dp-replica padding, BatchShape planning and tensor packing. Runs on
    the packer thread when pack_thread_enabled(), inline otherwise —
    the tracer span lands on whichever thread did the work (its own
    track in trace.json).

    Single-device buckets try the copy-free views path first
    (kernels.pack_batch_views): when every history carries
    dispatch-shaped v2-sidecar views matching the planned shape, no
    host tensor is built at all. Otherwise pack_batch copies as
    before, and the bytes it copied for WARM (cache-loaded) histories
    are attributed to `warm_copy_bytes` — the number the warm
    north-star bench drives to zero."""
    t0 = time.perf_counter()
    group = [encs[i] for i in bucket]
    bucket_mesh = mesh
    if mesh is not None:
        # Pad ragged buckets to a dp multiple by replicating the
        # last history (results dropped at collect) so the dispatch
        # still shards across the mesh instead of falling to one
        # device — unless the padding itself would blow the budget
        # (a single history bigger than budget/dp), in which case
        # dispatch unsharded rather than 8x over budget.
        tpad = max(K.pad_to(max(e.n for e in group), 128), 1)
        padded = pad_to_multiple(group, dp)
        if len(padded) * tpad * tpad <= budget_cells:
            group = padded
        else:
            bucket_mesh = None
    shape = K.BatchShape.plan(group)
    packed = K.pack_batch_views(group, shape) \
        if bucket_mesh is None else None
    if packed is None:
        packed = K.pack_batch(group, shape)
        if tr.enabled:
            warm = sum(
                e.appends.nbytes + e.reads.nbytes
                + e.invoke_index.nbytes + e.complete_index.nbytes
                + e.process.nbytes
                for e in group if getattr(e, "warm", False))
            if warm:
                tr.counter("warm_copy_bytes").inc(warm)
    if tr.enabled:
        # padding waste this dispatch pays: B_pad·T_pad² minus the
        # ORIGINAL bucket's own cells, so dp-replica padding (group
        # may hold replicated histories) counts as waste too
        cells = len(group) * shape.n_txns * shape.n_txns
        tr.counter("pad_waste_cells").inc(
            cells - sum(max(_size_of(encs[i]), 1) ** 2 for i in bucket))
        # per-dispatch device-resident footprint, in closure cells —
        # the HBM-envelope invariant (max over dispatches x
        # max_inflight <= budget_cells) is asserted against this
        tr.histogram("bucket_cells").observe(cells)
    _acc_phase(phases, "pack", t0)
    return bucket, bucket_mesh, shape, packed


def _h2d_bucket(item: tuple, phases: dict | None) -> tuple:
    """device_put / sharding of one packed bucket (h2d phase)."""
    bucket, bucket_mesh, shape, packed = item
    t0 = time.perf_counter()
    args = shard_batch(bucket_mesh, packed)
    _acc_phase(phases, "h2d", t0)
    return bucket, bucket_mesh, shape, args


# ---------------------------------------------------------------------------
# Supervised dispatch: watchdog, OOM backdown, quarantine (ISSUE 4).
# The policy (gates, fault injection, the Quarantined sentinel) lives
# in jepsen_tpu.supervisor; this is the mechanism around jax calls.
# ---------------------------------------------------------------------------

def _block_flags(flags, tr):
    """`jax.block_until_ready` bounded by the dispatch watchdog
    (JEPSEN_TPU_DISPATCH_TIMEOUT_S; default off = plain block). On a
    timeout the wait is retried once — a transient host stall under a
    healthy device resolves here — then WatchdogTimeout raises and the
    caller quarantines the bucket. The device op itself cannot be
    cancelled; its waiter thread is abandoned daemonically
    (util.timeout_call), so a wedged runtime can't also wedge exit."""
    timeout = sv.dispatch_timeout_s()
    if timeout is None:
        return jax.block_until_ready(flags)
    from ..util import timeout_call
    _pending = object()
    for _attempt in range(2):
        got = timeout_call(timeout,
                           lambda: jax.block_until_ready(flags),
                           default=_pending)
        if got is not _pending:
            return got
        if _attempt == 0:
            # one wedged dispatch = one timeout, however many attempts
            # it burns — operators correlate this against `quarantined`
            tr.counter("watchdog_timeouts").inc()
        tr.instant("watchdog_timeout", track="device",
                   timeout_s=timeout, attempt=_attempt)
        obs_events.emit("watchdog_fire", timeout_s=timeout,
                        attempt=_attempt)
    raise sv.WatchdogTimeout(
        f"device dispatch exceeded {timeout}s twice")


def _quarantine_bucket(idx: list, stage: str, err, tr) -> list:
    """Per-history Quarantined sentinels for a bucket the supervisor
    abandoned, attributed as a quarantine span + counter."""
    with tr.span("quarantine", stage=stage, histories=len(idx)):
        tr.counter("quarantined").inc(len(idx))
        log.warning("quarantined %d histories (%s): %r",
                    len(idx), stage, err)
    e = repr(err)
    obs_events.emit("quarantine", stage=stage, histories=len(idx),
                    cause=e[:300])
    return [sv.Quarantined(stage, e) for _ in idx]


def _dispatch_fn(bucket_mesh, shape: K.BatchShape, kw: dict, args,
                 donate: bool):
    """The callable for one bucket dispatch: the jitted check fn, or —
    single-device with the AOT cache on — a persistent compiled
    executable (residency.ExecutableResidency over jepsen_tpu.aot)
    keyed by the input avals + kernel flags + formulation, so a
    repeat sweep pays zero XLA compiles."""
    fn = sharded_check_fn(bucket_mesh, shape, donate=donate, **kw)
    return _residency.dispatch_fn(fn, bucket_mesh, shape, kw, args,
                                  donate)


def _donate_active(bucket_mesh) -> bool:
    return _slots.donate_active(bucket_mesh)


def _note_donation(tr, args=None) -> None:
    _slots.note_donation(tr, args)


def _sync_check(encs, idx: list, mesh, budget_cells: int, kw: dict,
                tr, phases) -> np.ndarray:
    """One synchronous bucket check — the OOM-backdown retry path:
    pack, transfer, dispatch, block. Raises on OOM/watchdog; the
    caller owns the split/quarantine policy. Donation here is
    self-contained: the slot acquired for this retry releases in the
    finally, whatever the outcome — backdown recursion holds only its
    own halves' slots, never an ancestor's. Retries run stats-free
    (kernel-stats is observability; a re-planned bucket keeps its
    verdicts and drops its telemetry rather than re-keying the
    recovery executable)."""
    if kw.get("with_stats"):
        kw = {**kw, "with_stats": False}
    dp = mesh.devices.shape[0] if mesh is not None else 1
    bucket, bucket_mesh, shape, args = _h2d_bucket(
        _prep_bucket(encs, idx, mesh, dp, budget_cells, tr, phases),
        phases)
    donate = _donate_active(bucket_mesh)
    fn = _dispatch_fn(bucket_mesh, shape, kw, args, donate)
    sv.maybe_inject_oom()
    if donate:
        _note_donation(tr, args)
    try:
        t_disp = time.perf_counter()
        flags = fn(*args)
        obs_device.begin_dispatch(flags, kw, shape, bucket_mesh is None,
                                  donate, args, tr)
        try:
            arr = np.asarray(_block_flags(flags, tr))
        except BaseException:
            obs_device.discard_dispatch(flags, tr)
            raise
    finally:
        if donate:
            _slots.release()
    tr.device_complete("bucket", t_disp, histories=len(idx))
    obs_device.close_dispatch(flags, t_disp, len(idx), tr)
    return arr


def _oom_backdown(encs, idx: list, mesh, budget_cells: int, kw: dict,
                  tr, phases, err) -> list:
    """Recover from a RESOURCE_EXHAUSTED bucket: split it in half and
    retry each half synchronously at a HALVED per-slot cell budget
    (the padded footprint shrinks on both axes), recursing to
    singletons. A singleton that still OOMs is oversized for the
    device outright — it quarantines instead of crashing the sweep.
    In strict mode the original error re-raises untouched.

    Retries run WITHOUT draining the pipeline's other in-flight
    buckets first (draining from inside the threaded dispatcher would
    have to juggle its envelope semaphore — a deadlock risk not worth
    the memory it frees), so the halved budget is also what compensates
    for their residual pressure: each halving shrinks this retry's
    footprint until it fits the envelope slack or quarantines."""
    if sv.strict_enabled():
        raise err
    tr.counter("oom_retries").inc()
    if len(idx) == 1:
        return _quarantine_bucket(idx, "oom", err, tr)
    tr.counter("bucket_splits").inc()
    obs_events.emit("oom_split", histories=len(idx),
                    budget_cells=budget_cells)
    mid = (len(idx) + 1) // 2
    half_budget = max(1, budget_cells // 2)
    out: list = []
    for half in (idx[:mid], idx[mid:]):
        try:
            arr = _sync_check(encs, half, mesh, half_budget, kw, tr,
                              phases)
            out.extend(int(w) for w in arr[:len(half)])
        except BaseException as e:
            if isinstance(e, sv.WatchdogTimeout) \
                    and not sv.strict_enabled():
                out.extend(_quarantine_bucket(half, "watchdog", e, tr))
            elif sv.is_oom_error(e) and not sv.strict_enabled():
                out.extend(_oom_backdown(encs, half, mesh, half_budget,
                                         kw, tr, phases, e))
            else:
                raise
    return out


def _finish_part(encs, idx: list, flags, mesh, budget_cells: int,
                 kw: dict, tr, phases, t_disp=None,
                 donated: bool = False, smeta=None) -> list:
    """Resolve one dispatched bucket to per-history flag words (padded
    replicas dropped), recovering from OOM (backdown) and watchdog
    timeouts (quarantine) unless strict. The dispatch->materialized
    device window closes HERE, on the success path only — a recovered
    bucket's device time is the backdown's own per-half windows
    (_sync_check), never the original window stretched over the whole
    recovery (which would double-count the device track). A donated
    dispatch's ledger slot releases the moment its fate is decided —
    in particular BEFORE an OOM backdown re-plans, so a split bucket
    drops its original slot and the halves acquire their own.

    `smeta` ((device stats, BatchShape) — a kernel-stats dispatch)
    resolves to (word, stats-dict) pairs instead of bare words; the
    recovery paths resolve stats-free (a quarantined or re-planned
    history yields verdict evidence only)."""
    try:
        arr = np.asarray(_block_flags(flags, tr))
        if donated:
            _slots.release()
        tr.device_complete("bucket", t_disp, histories=len(idx))
        obs_device.close_dispatch(flags, t_disp, len(idx), tr)
        words = [int(w) for w in arr[:len(idx)]]
        if smeta is not None:
            rows = np.asarray(smeta[0])
            t_pad = smeta[1].n_txns
            return [(w, K.stats_row(rows[j], n_txns=_size_of(encs[i]),
                                    t_pad=t_pad))
                    for j, (i, w) in enumerate(zip(idx, words))]
        return words
    except BaseException as e:
        # the abandoned dispatch's cost window is discarded, never
        # recorded: a recovered bucket's device time is the backdown's
        # own windows, same as the device track
        obs_device.discard_dispatch(flags, tr)
        if donated:
            _slots.release()
        if isinstance(e, sv.WatchdogTimeout) and not sv.strict_enabled():
            return _quarantine_bucket(idx, "watchdog", e, tr)
        if sv.is_oom_error(e) and not sv.strict_enabled():
            return _oom_backdown(encs, idx, mesh, budget_cells, kw, tr,
                                 phases, e)
        raise


def check_bucketed_async(encs: Sequence, mesh: Mesh | None = None, *,
                         classify: bool = True, realtime: bool = False,
                         process_order: bool = False,
                         budget_cells: int = 1 << 27,
                         fused: bool | None = None,
                         max_inflight: int = 2,
                         phases: dict | None = None,
                         with_stats: bool = False) -> PendingVerdicts:
    """Dispatch a bucketed sweep WITHOUT blocking on the device: every
    bucket is packed, transferred and queued (JAX dispatch is async),
    and the returned PendingVerdicts resolves the flags later. This is
    the double-buffered pipeline's core — the caller dispatches chunk N,
    then collects chunk N-1 while N computes.

    `max_inflight` bounds how many buckets' packed tensors are resident
    at once: once more than that many dispatches are outstanding, the
    oldest is resolved to host flags before the next bucket transfers —
    host packing far outruns the O(T^3) closure, so an unbounded queue
    would accumulate every bucket's input tensors in device/host memory
    (exactly what budget_cells exists to prevent). Double-buffering
    only needs depth 2.

    HBM envelope: `budget_cells` bounds the TOTAL device-resident
    footprint, not one bucket's — the bucketer is therefore sized at
    budget_cells // max_inflight per bucket, so max_inflight resident
    buckets can never exceed the envelope the caller budgeted
    (ROADMAP's PR-1 open item, resolved on the halve-the-bucket side:
    the sync wrapper keeps its depth-2 pipelining and the footprint
    guarantee instead of giving up the overlap with max_inflight=1).
    A single history too long to fit the per-slot budget can't be
    subdivided; such singleton buckets are dispatched LAST and strictly
    alone (everything else resolved first, nothing pipelined next to
    them), so the envelope degrades to one such history's own
    unavoidable footprint, never that plus a pipeline's worth.

    With pack_thread_enabled() (default) a dedicated "pack-h2d" thread
    packs bucket N+1 and device_puts it while the calling thread
    dispatches/collects bucket N, so the parent's critical path is
    only the async kernel enqueue and the h2d copy overlaps device
    compute; a Semaphore caps packed-and-transferred-but-unresolved
    buckets at max_inflight so the thread can never outrun the
    envelope.

    `phases` (optional dict) accumulates per-phase host wall-clock:
    "pack" (bucket planning + host tensor packing), "h2d" (device_put /
    sharding), "dispatch" (async kernel enqueue); `.result(phases)`
    and the max_inflight back-pressure add "collect" (block + D2H +
    flag rendering)."""
    if mesh is not None and mesh.devices.size == 1:
        # a 1-device mesh (analyze-store's make_mesh() on a single-
        # device host) is single-device dispatch wearing a Mesh:
        # normalize it away so the warm path — views pack, donated
        # buffers, the AOT executable cache — applies to the REAL
        # sweep, not just bare-mesh callers. Sharding over one device
        # is an identity constraint; verdicts are unchanged.
        mesh = None
    parts: list = []
    inflight: list[int] = []    # indices into parts, oldest first
    depth = max(1, max_inflight)
    dp = mesh.devices.shape[0] if mesh is not None else 1
    tr = trace.get_current()
    kw = dict(classify=classify, realtime=realtime,
              process_order=process_order, fused=fused,
              with_stats=bool(with_stats))
    t0 = time.perf_counter()
    eff_budget = max(1, budget_cells // depth)
    pl = _planner.get()
    if pl is not None:
        # the cost-aware planner races candidate pad multiples on
        # predicted device seconds and keeps the winner's composition;
        # it answers bucket_by_length's exact output (multiple 128)
        # whenever it has no model — and composition only moves
        # histories between dispatches, never changes a verdict
        buckets = pl.plan_buckets(encs, budget_cells=eff_budget, dp=dp)
    else:
        buckets = bucket_by_length(encs, budget_cells=eff_budget, dp=dp)
    # Singleton buckets whose one history alone exceeds the per-slot
    # budget cannot honor depth-sharing: peel them off to dispatch
    # strictly alone after the pipelined buckets drain.
    oversized = [b for b in buckets
                 if _est_cells(encs, b, dp) > eff_budget]
    buckets = [b for b in buckets
               if _est_cells(encs, b, dp) <= eff_budget]
    _acc_phase(phases, "pack", t0)

    def finish(idx, flags, t_disp=None, donated=False, smeta=None):
        out = _finish_part(encs, idx, flags, mesh, eff_budget, kw,
                           tr, phases, t_disp, donated, smeta)
        # dispatched-vs-resolved parity for the live health snapshot:
        # exactly the buckets `buckets_dispatched` counted resolve
        # through here (sync-resolved OOM paths were never dispatched)
        tr.counter("buckets_resolved").inc()
        return out

    def resolve_oldest():
        j = inflight.pop(0)
        t0 = time.perf_counter()
        idx, flags, t_disp, donated, smeta = parts[j]
        parts[j] = (idx, finish(idx, flags, t_disp, donated, smeta),
                    None, False, None)
        tr.gauge("inflight_depth").set(len(inflight))
        _acc_phase(phases, "collect", t0)

    def dispatch(item) -> bool:
        """Enqueue one packed bucket async; returns False when the
        bucket was instead resolved synchronously (an OOM at enqueue
        went down the backdown path — nothing joined the pipeline)."""
        bucket, bucket_mesh, shape, args = item
        t0 = time.perf_counter()
        donate = _donate_active(bucket_mesh)
        fn = _dispatch_fn(bucket_mesh, shape, kw, args, donate)
        try:
            sv.maybe_inject_oom()
            out = fn(*args)
            # a kernel-stats dispatch returns (flags, stats); the
            # flags array stays the dispatch's identity (device
            # windows, cost observatory) and the stats ride as smeta
            flags, dev_stats = out if isinstance(out, tuple) \
                else (out, None)
            if donate:
                _note_donation(tr, args)
            parts.append((bucket, flags, time.perf_counter(), donate,
                          (dev_stats, shape) if dev_stats is not None
                          else None))
            obs_device.begin_dispatch(flags, kw, shape,
                                      bucket_mesh is None, donate,
                                      args, tr)
        except BaseException as e:
            if not sv.is_oom_error(e) or sv.strict_enabled():
                raise
            _acc_phase(phases, "dispatch", t0)
            parts.append((bucket, _oom_backdown(
                encs, bucket, mesh, eff_budget, kw, tr, phases, e),
                None, False, None))
            return False
        inflight.append(len(parts) - 1)
        tr.counter("buckets_dispatched").inc()
        tr.gauge("inflight_depth").set(len(inflight))
        _acc_phase(phases, "dispatch", t0)
        return True

    def handle_failed(bucket, e):
        """A bucket whose pack/h2d failed: strict re-raises (the old
        fail-fast contract); OOM goes down the backdown path; any
        other *Exception* quarantines JUST this bucket — independent
        sub-problems fail independently, the rest of the sweep
        proceeds. Non-Exception BaseExceptions (KeyboardInterrupt,
        SystemExit) always re-raise: a Ctrl-C must stop the sweep,
        not journal a bogus permanent 'unknown'."""
        if sv.strict_enabled() or not isinstance(e, Exception):
            raise e
        if sv.is_oom_error(e):
            parts.append((bucket, _oom_backdown(
                encs, bucket, mesh, eff_budget, kw, tr, phases, e),
                None, False, None))
        else:
            parts.append((bucket,
                          _quarantine_bucket(bucket, "pack", e, tr),
                          None, False, None))

    _FAILED = object()

    if pack_thread_enabled() and len(buckets) > 1:
        # Staged pipeline: the packer thread owns pack + h2d; `sem`
        # counts device-resident buckets (transferred, not yet
        # resolved) so pack can run one bucket ahead while h2d waits
        # for an envelope slot.
        out: _queue.Queue = _queue.Queue()
        sem = threading.Semaphore(depth)
        stop = threading.Event()
        _DONE = object()

        def producer():
            try:
                for b in buckets:
                    # per-bucket isolation: a history that breaks
                    # packing must not kill the producer (and with it
                    # every later bucket's verdict) — the failure
                    # rides the queue as a marker for the caller's
                    # quarantine/backdown policy
                    try:
                        item = _prep_bucket(encs, b, mesh, dp,
                                            eff_budget, tr, phases)
                    except BaseException as e:
                        out.put((_FAILED, b, e))
                        continue
                    sem.acquire()
                    if stop.is_set():
                        return
                    try:
                        out.put(_h2d_bucket(item, phases))
                    except BaseException as e:
                        sem.release()   # no dispatch will free this slot
                        out.put((_FAILED, b, e))
                out.put(_DONE)
            except BaseException as e:   # surfaced on the caller
                out.put(e)

        th = threading.Thread(target=producer, name="pack-h2d",
                              daemon=True)
        th.start()
        try:
            while True:
                # a main-thread stall on the producer is its own phase
                # ("feed"): with pack/h2d accruing on their own thread,
                # the main thread's wall clock partitions into
                # feed/dispatch/collect instead
                t0 = time.perf_counter()
                item = out.get()
                _acc_phase(phases, "feed", t0)
                if item is _DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                if isinstance(item, tuple) and item and \
                        item[0] is _FAILED:
                    handle_failed(item[1], item[2])
                    continue
                if not dispatch(item):
                    # resolved synchronously: the envelope slot the
                    # producer acquired for it frees right now, or the
                    # producer parks forever while we wait on its queue
                    sem.release()
                elif len(inflight) >= depth:
                    # release an envelope slot as soon as the pipeline
                    # is full: the producer's h2d for bucket N+depth
                    # waits on this resolve, which itself overlaps
                    # bucket N+1's compute
                    resolve_oldest()
                    sem.release()
        finally:
            stop.set()
            for _ in buckets:   # unblock a producer parked on sem
                sem.release()
            th.join(timeout=30)
    else:
        for bucket in buckets:
            while len(inflight) >= depth:
                resolve_oldest()
            try:
                item = _h2d_bucket(
                    _prep_bucket(encs, bucket, mesh, dp, eff_budget,
                                 tr, phases), phases)
            except BaseException as e:
                handle_failed(bucket, e)
                continue
            dispatch(item)
    for bucket in oversized:
        # strictly-alone dispatch: drain EVERYTHING first so this
        # history's unavoidable footprint is the only thing resident
        # (the mesh-padding check may use the full budget — nothing
        # shares the envelope with it)
        while inflight:
            resolve_oldest()
        try:
            item = _h2d_bucket(
                _prep_bucket(encs, bucket, mesh, dp, budget_cells,
                             tr, phases), phases)
        except BaseException as e:
            handle_failed(bucket, e)
            continue
        dispatch(item)
    return PendingVerdicts(len(encs), parts, finish)


def check_bucketed(encs: Sequence, mesh: Mesh | None = None, *,
                   classify: bool = True, realtime: bool = False,
                   process_order: bool = False,
                   budget_cells: int = 1 << 27,
                   two_pass: bool | None = None,
                   fused: bool | None = None,
                   phases: dict | None = None,
                   stats_out: list | None = None) -> list[dict]:
    """Check many encoded histories bucketed by length: one device
    dispatch per bucket, results returned in input order.

    With classify=True the default strategy is the FUSED detect/classify
    kernel (kernels.fused_classify_enabled): one dispatch per bucket
    runs the detect closure and only fires the classification closures
    (via lax.cond) when some history in the bucket is cyclic, reusing
    the detect pass's full closure for the cycle/G2 tests. On the
    production regime — sweeps that are mostly valid — every bucket runs
    at the detect rate with no re-dispatch, which is what lets the
    streaming pipeline stay async end to end. The cond is per BUCKET:
    one positive makes its whole bucket pay the classification
    closures (~3x detect), trading that for zero re-dispatch, no
    re-pack, and no per-subset recompiles; a sweep whose positives are
    dense enough to trip most buckets can pin two_pass=True (or
    JEPSEN_TPU_FUSED_CLASSIFY=0) to get the flagged-subset re-dispatch
    back.

    two_pass=True (the pre-fusion strategy, and the default when
    JEPSEN_TPU_FUSED_CLASSIFY=0) sweeps every bucket in detect mode and
    re-dispatches ONLY flagged histories with the chained classification
    closures. Verdicts are identical on every strategy because a
    cycle-free graph classifies to zero flags.

    `stats_out` (a list) is EXTENDED with one `kernels.stats_row` dict
    per input history — the kernel-stats telemetry path
    (JEPSEN_TPU_KERNEL_STATS); entries are None for quarantined or
    backdown-recovered histories. On the two-pass strategy the stats
    come from the DETECT pass (the from-scratch full closure — the
    uniform definition); the classify re-dispatch runs stats-free."""
    if not len(encs):
        return []
    if fused is None:
        fused = K.fused_classify_enabled()
        pl = _planner.get()
        if pl is not None and classify:
            # the planner may flip the classify strategy when the
            # costdb has measured BOTH fused and two-pass at this
            # workload's geometry (verdicts are pinned identical
            # across strategies); an explicit fused= argument or a
            # cold planner keeps the gate's choice
            t_pad = K.pad_to(max((_size_of(e) for e in encs),
                                 default=1), 128)
            fused = pl.fused_choice(fused, classify=classify,
                                    t_pad=t_pad)
    if two_pass is None:
        two_pass = classify and not fused
    if classify and two_pass:
        detect = check_bucketed(encs, mesh, classify=False,
                                realtime=realtime,
                                process_order=process_order,
                                budget_cells=budget_cells, phases=phases,
                                stats_out=stats_out)
        # quarantined sentinels pass straight through: there is
        # nothing to classify for a history the supervisor abandoned
        flagged = [i for i, f in enumerate(detect)
                   if f and not isinstance(f, sv.Quarantined)]
        if not flagged:
            return detect
        # the re-dispatch population is all-cyclic, where the chained
        # warm starts beat the fused kernel's unseeded detect closure
        full = check_bucketed([encs[i] for i in flagged], mesh,
                              classify=True, realtime=realtime,
                              process_order=process_order,
                              budget_cells=budget_cells, two_pass=False,
                              fused=False, phases=phases)
        out = list(detect)
        for i, r in zip(flagged, full):
            out[i] = r
        return out
    pv = check_bucketed_async(
        encs, mesh, classify=classify, realtime=realtime,
        process_order=process_order, budget_cells=budget_cells,
        fused=fused, phases=phases,
        with_stats=stats_out is not None)
    res = pv.result(phases)
    if stats_out is not None:
        stats_out.extend(pv.stats())
    return res
