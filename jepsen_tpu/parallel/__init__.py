"""The analysis data plane: mesh construction and sharded batch checking.

The reference's only distribution mechanism is SSH fan-out on the control
plane (SURVEY.md §5.8) — analysis is single-JVM. This module is the
north-star addition: history batches are sharded over a TPU device mesh
with named axes

  dp  data parallel over histories (the primary axis, SURVEY.md §2.5)
  mp  model parallel within one history: the [T,T] adjacency/closure
      matrices are column-sharded, so each closure matmul runs as a
      distributed dense matmul with XLA inserting the collectives over
      ICI (the sequence-parallel analogue for long histories)

The batched formulation here (explicit [B,T,T] einsum instead of vmap)
exists so sharding constraints can be placed on the matrices themselves.
"""

from __future__ import annotations

import functools
import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..checker.elle import kernels as K
from ..devices import default_devices


def factor2(n: int) -> tuple[int, int]:
    """Split n into (a, b), a*b == n, as square as possible, a >= b."""
    b = int(math.isqrt(n))
    while n % b:
        b -= 1
    return n // b, b


def make_mesh(devices: Sequence | None = None,
              axes: tuple[str, str] = ("dp", "mp")) -> Mesh:
    """A 2-D device mesh: data parallel over histories × model parallel
    within a history's closure matmuls."""
    devices = list(devices if devices is not None else default_devices())
    dp, mp = factor2(len(devices))
    return Mesh(np.asarray(devices).reshape(dp, mp), axes)


def sharded_check_fn(mesh: Mesh | None, shape: K.BatchShape, *,
                     classify: bool = True, realtime: bool = False,
                     process_order: bool = False):
    """Build a jitted batched checker around kernels.check_batched_impl.
    With a mesh, inputs are expected sharded over 'dp' and the closure
    matrices are constrained to P('dp', None, 'mp'); without one, it's a
    plain single-device jit."""
    if mesh is not None:
        spec = P("dp", None, "mp")

        def constrain(x):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec))
    else:
        def constrain(x):
            return x

    f = functools.partial(
        K.check_batched_impl, n_keys=shape.n_keys, max_pos=shape.max_pos,
        n_txns=shape.n_txns, steps=K.closure_steps(shape.n_txns),
        classify=classify, realtime=realtime, process_order=process_order,
        constrain=constrain)
    if mesh is None:
        return jax.jit(f)
    in_shard = NamedSharding(mesh, P("dp"))
    out_shard = NamedSharding(mesh, P("dp"))
    return jax.jit(f, in_shardings=(in_shard,) * 6, out_shardings=out_shard)


def shard_batch(mesh: Mesh | None, packed: dict) -> tuple:
    """Device-put packed batch arrays, sharded over dp when a mesh is
    given. Returns the 6 positional args for the check fn."""
    names = ("appends", "reads", "invoke_index", "complete_index",
             "process", "n_txns")
    args = [jnp.asarray(packed[k]) for k in names]
    if mesh is not None:
        s = NamedSharding(mesh, P("dp"))
        args = [jax.device_put(a, s) for a in args]
    return tuple(args)
