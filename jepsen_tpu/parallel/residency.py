"""Executable residency + device-slot ownership, split out of sweep
orchestration (the unlocking refactor ROADMAP items 1 and 2 share).

The bucket dispatcher in `parallel/__init__.py` used to own three
unrelated concerns at once: bucket scheduling (its real job), WHICH
compiled executables are resident for repeat dispatches, and WHO holds
the donated device-buffer slots while a dispatch is in flight. The
multi-host mesh sweep (analyze-store --mesh) runs one long-lived
dispatch loop per shard, and the future `serve` daemon (ROADMAP item
2) runs one per process forever — both need executables and donated
buffers held resident across requests without re-owning the
bookkeeping, so the two non-scheduling concerns live here:

  * `ExecutableResidency` — resolves the callable for one dispatch:
    the jitted fn as-is for mesh-sharded dispatches (XLA must insert
    the collectives), or the persistent AOT-compiled executable
    (jepsen_tpu.aot) for single-device dispatches, keyed by kernel
    flags + resolved formulation + batch geometry, so a warm owner
    pays zero XLA compiles however many dispatch loops it runs.
  * `DeviceSlots` — ownership of donated device-buffer slots: the
    donation policy gate (single-device only, JEPSEN_TPU_DONATE_
    BUFFERS) plus the supervisor's process-wide slot ledger. A slot
    is acquired per donated dispatch and MUST be released exactly once
    when the dispatch's fate is decided — success, watchdog
    quarantine, or OOM backdown re-plan (the split halves acquire
    their own slots; an ancestor's is never held through recovery).

Both are plain objects so a second dispatch owner (a serve daemon's
continuous batcher) can hold its own `DeviceSlots` over a different
ledger while sharing the one process-wide executable residency.
"""

from __future__ import annotations


class ExecutableResidency:
    """Which compiled executables are resident for repeat dispatches.

    jax's in-memory jit cache already dedups same-shape compiles within
    a process; this layer adds the cross-process persistence (the AOT
    executable cache) behind one stable key, so callers ask for "the
    callable for this dispatch" and never learn how executables are
    stored."""

    def dispatch_fn(self, fn, bucket_mesh, shape, kw: dict, args,
                    donate: bool):
        """The callable for one bucket dispatch: `fn` (the jitted
        check fn) for mesh-sharded dispatches, else the persistent
        compiled executable when the AOT cache is on. Dispatches that
        stay on the plain jitted fn (a mesh, or the AOT cache off)
        still feed the device cost observatory — a one-time
        `jit.lower()` per geometry reads `cost_analysis()` without
        forcing a second XLA compile (obs.device, JEPSEN_TPU_COSTDB;
        the compiled path captures inside aot.compiled_for)."""
        if bucket_mesh is not None or not self._aot_enabled():
            from ..obs import device as device_obs
            device_obs.observe(
                device_obs.dispatch_cost_key(
                    kw, shape, bucket_mesh is None, donate),
                args, fn, source="lowered")
            return fn
        from .. import aot
        return aot.compiled_for(
            fn, args, self.dispatch_key(kw, shape, donate))

    @staticmethod
    def _aot_enabled() -> bool:
        from .. import aot
        return aot.enabled()

    @staticmethod
    def resident_count() -> int:
        """How many compiled executables this process holds resident
        (the AOT in-memory map — jax's own jit cache is opaque)."""
        from .. import aot
        return aot.resident_count()

    @staticmethod
    def dispatch_key(kw: dict, shape, donate: bool) -> tuple:
        """The stable half of the AOT cache key for a single-device
        dispatch: kernel flags + the RESOLVED closure formulation +
        batch geometry (aot itself adds input avals, backend topology
        and jax/jaxlib versions). A kernel-stats dispatch
        (JEPSEN_TPU_KERNEL_STATS) returns a second output and so
        compiles a different executable — the marker is APPENDED only
        when the flag is on, so the gate-off key (and every cached
        executable keyed under it) is byte-identical to before."""
        from ..checker.elle import kernels as K
        use_pallas, use_int8 = K.resolve_formulation(single_device=True)
        return (kw.get("classify", True), kw.get("realtime", False),
                kw.get("process_order", False), kw.get("fused"),
                use_pallas, use_int8, donate,
                shape.n_keys, shape.max_pos, shape.n_txns) \
            + (("stats",) if kw.get("with_stats") else ())


class DeviceSlots:
    """Donated device-buffer slot ownership for one dispatch owner.

    Wraps the donation policy (the gate + the single-device-only rule)
    and a `supervisor.DeviceSlotLedger` so every acquire/release pair
    goes through one object — a drained owner with nonzero inflight is
    a leak, which the warm-path tests pin to zero."""

    def __init__(self, ledger=None):
        if ledger is None:
            from .. import supervisor as sv
            ledger = sv.slot_ledger
        self.ledger = ledger

    def donate_active(self, bucket_mesh) -> bool:
        """Does donation apply to this dispatch? Single-device only
        (the mesh flag is normalized away so it can't split the
        compile cache) and gated by JEPSEN_TPU_DONATE_BUFFERS; on CPU
        the spurious 'donated buffers not usable' warning is filtered
        at this dispatch site (pytest resets warning filters per test,
        so a one-time install would not survive)."""
        from .. import supervisor as sv
        active = bucket_mesh is None and sv.donate_buffers_enabled()
        if active:
            self._filter_cpu_donation_warning()
        return active

    @staticmethod
    def _filter_cpu_donation_warning() -> None:
        import jax
        if jax.default_backend() == "cpu":
            import warnings
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")

    def note_donation(self, tr, args=None) -> None:
        """One donated dispatch: six input buffers handed to XLA, one
        ledger slot held until the dispatch resolves. With `args` (and
        the cost observatory on) the donated BYTES are counted too —
        the residency surface the HBM ledger publishes."""
        self.ledger.acquire()
        tr.counter("buffers_donated").inc(6)
        if args is not None:
            from ..obs import device as device_obs
            if device_obs.enabled():
                try:
                    tr.counter("donated_bytes").inc(
                        sum(int(a.nbytes) for a in args))
                except Exception:   # observability never sinks dispatch
                    pass

    def release(self) -> None:
        self.ledger.release()

    def inflight(self) -> int:
        return self.ledger.inflight()


def publish_residency_gauges(tr, modeled_bytes: int | None = None
                             ) -> None:
    """THE residency-gauge publication point (obs.device calls it at
    each dispatch open/close): resident executables, modeled HBM in
    flight, and — throttled by JEPSEN_TPU_RESIDENCY_INTERVAL_S — the
    backend's own `memory_stats()` where the platform reports one.
    The gauges land in the metrics registry, so metrics.json,
    `/metrics` and health.json's device section all agree."""
    tr.gauge("resident_executables").set(
        ExecutableResidency.resident_count())
    if modeled_bytes is not None:
        tr.gauge("hbm_modeled_bytes").set(int(modeled_bytes))
    from ..obs import device as device_obs
    device_obs.maybe_poll_memory_stats(tr)
