"""Batch folding: the continuous-batcher's scheduler seam.

`check_bucketed_async` answers "given THIS list of histories, sweep
them efficiently" — the batch question. A verdict service asks the
inverse: many tenants' admission queues are filling concurrently, and
as device slots free up the daemon must decide WHICH pending histories
form the next shared bucket dispatch. That decision lives here, next
to the dispatcher it feeds, in two pieces:

  * `plan_fold` — weighted deficit round-robin (DRR) across per-tenant
    lanes under a padded-cell budget. The cost unit is `fold_cost`
    (T_pad² closure cells — the same geometry `bucket_by_length`
    budgets), so admission control is BY HISTORY SIZE, not request
    count: the complexity bounds in arxiv 1908.04509 make cost grow
    with history length, and a fairness scheme that charged a 5-txn
    and a 5000-txn history the same would let one tenant's long tail
    starve everyone. Deficits persist across folds (the caller owns
    the lanes), so a tenant whose head is briefly unaffordable earns
    credit instead of starving.
  * `FoldDispatcher` — one owner's dispatch loop over the folds:
    routes each fold through `check_bucketed` (OOM backdown, watchdog
    quarantine, donated slots and the shared `ExecutableResidency` all
    included — a fold is just a caller-chosen chunk) and renders the
    SAME verdict dicts `analyze-store` persists, so a streamed verdict
    is byte-identical to the post-hoc one for the same history. A fold
    that fails outright quarantines ONLY its own histories: a poisoned
    tenant costs its bucket share, never the daemon.

Both are plain objects with no socket/tenant knowledge — the serve
daemon composes them; the mesh sweep can too. The cost-aware planner
(jepsen_tpu/planner.py, JEPSEN_TPU_PLANNER) slots in above this
layer: it replaces the `fold_cost` PRICE with a model prediction in
the same cell unit, while `plan_fold`'s DRR mechanics — which only
ever read `.cost` as a positive number — stay untouched.
"""

from __future__ import annotations

import logging

log = logging.getLogger(__name__)

#: Default fold budget in padded closure cells — the same envelope
#: `check_bucketed_async` budgets per dispatch pipeline.
DEFAULT_FOLD_CELLS = 1 << 27

#: DRR safety valve: rounds are bounded so a pathological lane set can
#: never spin the scheduler (a full-budget head is affordable within
#: ~8·lanes rounds at the default quantum; 1024 is far past any real
#: shape).
_MAX_ROUNDS = 1024


def fold_cost(n_txns: int, multiple: int = 128) -> int:
    """The padded closure footprint one history contributes to a
    shared bucket: T_pad² cells with the txn axis rounded up to the
    MXU tile — `bucket_by_length`'s unit, restated jax-free so
    admission can price a request before any device work. This is
    the ANALYTIC proxy; with JEPSEN_TPU_PLANNER on, the serve daemon
    prices admission with `planner.admission_cost` — the fitted cost
    model's prediction normalized back to this same cell unit, with
    this function as its bit-exact cold-start fallback."""
    t = max(int(n_txns), 1)
    t = max(multiple, ((t + multiple - 1) // multiple) * multiple)
    return t * t


class Lane:
    """One tenant's scheduling lane: a FIFO of cost-carrying items, a
    fairness weight, and the DRR deficit counter `plan_fold` maintains.
    The queue is a plain deque; the OWNER serializes access (the serve
    daemon holds its admission lock around admit and plan)."""

    __slots__ = ("name", "weight", "deficit", "queue")

    def __init__(self, name: str, weight: float = 1.0):
        from collections import deque
        self.name = name
        # a zero/negative weight would never earn deficit — clamp to a
        # small positive floor so every admitted tenant eventually runs
        self.weight = max(float(weight), 1e-3)
        self.deficit = 0.0
        self.queue = deque()


def plan_fold(lanes, *, budget_cells: int = DEFAULT_FOLD_CELLS,
              max_histories: int | None = None) -> list:
    """Pick the next fold: weighted deficit round-robin over `lanes`
    (Lane objects), popping items until the padded-cell budget (or
    `max_histories`) is reached. Returns [(lane, item), ...] in pick
    order; items must carry `.cost` (a `fold_cost` value).

    Contract: with equally-sized items and saturated queues, pick
    counts converge to the weight ratio; an item larger than the whole
    budget still dispatches (alone — the dispatcher's oversized-
    singleton path owns it from there); a lane's deficit resets when
    its queue drains, so idle tenants can't hoard credit."""
    active = [ln for ln in lanes if ln.queue]
    if not active:
        return []
    # quantum granularity bounds fairness error: one round must not
    # hand a lane more credit than ~one typical item, or the first
    # lane drains the whole fold before the second's turn — so the
    # quantum is capped at the smallest head cost (and at an 1/8th
    # budget share for the monster-head case)
    quantum = max(1.0, min(float(budget_cells) / (8 * len(active)),
                           float(min(ln.queue[0].cost
                                     for ln in active))))
    picked: list = []
    cells = 0

    def fits(cost: int) -> bool:
        if picked and cells + cost > budget_cells:
            return False
        return max_histories is None or len(picked) < max_histories

    for _ in range(_MAX_ROUNDS):
        earned = False
        for ln in active:
            if not ln.queue or not fits(ln.queue[0].cost):
                continue
            ln.deficit += ln.weight * quantum
            earned = True
            while ln.queue and ln.deficit >= ln.queue[0].cost \
                    and fits(ln.queue[0].cost):
                item = ln.queue.popleft()
                picked.append((ln, item))
                cells += item.cost
                ln.deficit -= item.cost
        if not earned:
            break   # fold full, or every queue drained
    if not picked and active:
        # _MAX_ROUNDS safety valve tripped: take one head anyway —
        # the scheduler must always make progress
        ln = active[0]
        picked.append((ln, ln.queue.popleft()))
        ln.deficit = 0.0
    for ln in lanes:
        if not ln.queue:
            ln.deficit = 0.0
    return picked


class FoldDispatcher:
    """Dispatch one fold of encoded histories and render the exact
    verdict dicts `analyze-store` would persist for them.

    Shares the process-wide `ExecutableResidency` (AOT-cached
    executables stay resident across folds — the daemon's whole point)
    and the supervisor's recovery ladder via `check_bucketed`: OOM
    backdown, watchdog quarantine, per-history `Quarantined`
    sentinels. Any error that still escapes quarantines the WHOLE
    fold's histories (`valid? unknown`, cause attached) instead of
    propagating — one tenant's poison costs its bucket share, never
    the dispatch loop."""

    def __init__(self, mesh=None, budget_cells: int = DEFAULT_FOLD_CELLS,
                 max_inflight: int = 2):
        self.mesh = mesh
        self.budget_cells = budget_cells
        self.max_inflight = max_inflight
        self.phases: dict = {}

    @staticmethod
    def _host_only() -> bool:
        from .. import gates
        return gates.get("JEPSEN_TPU_BACKEND") == "cpu"

    def verdicts(self, encs: list, checker: str = "append",
                 stats_out: list | None = None) -> list[dict]:
        """Per-history verdict dicts for one fold, aligned with
        `encs`. Entries that are Exceptions (a failed encode riding
        the queue) quarantine individually at the `encode` stage.
        `stats_out` (a list, JEPSEN_TPU_KERNEL_STATS) is extended with
        one kernel-stats dict per history, aligned with the verdicts
        (None for quarantined/failed ones) — the serve daemon attaches
        them to reply frames BESIDE the result, so streamed verdicts
        stay byte-identical to the post-hoc sweep's."""
        from .. import supervisor as sv
        out: list = [None] * len(encs)
        stats: list = [None] * len(encs)
        good_idx = [i for i, e in enumerate(encs)
                    if not isinstance(e, Exception)]
        for i, e in enumerate(encs):
            if isinstance(e, Exception):
                out[i] = sv.quarantine_verdict(e, "encode", checker)
        good = [encs[i] for i in good_idx]
        if good:
            gs: list | None = [] if stats_out is not None else None
            try:
                rendered = self._check(good, checker, stats_out=gs)
            except Exception as e:
                log.warning("fold dispatch failed; quarantining %d "
                            "histories", len(good), exc_info=True)
                rendered = [sv.quarantine_verdict(e, "dispatch",
                                                  checker)
                            for _ in good]
                gs = None
            for j, (i, res) in enumerate(zip(good_idx, rendered)):
                out[i] = res
                if gs is not None and j < len(gs):
                    stats[i] = gs[j]
        if stats_out is not None:
            stats_out.extend(stats)
        return out

    def _check(self, encs: list, checker: str,
               stats_out: list | None = None) -> list[dict]:
        from .. import parallel, supervisor as sv
        from ..checker import elle
        from ..checker.elle import kernels as elle_kernels
        from ..checker.elle import wr as elle_wr
        host_only = self._host_only()
        want_stats = stats_out is not None and not host_only
        fold_stats: list = [None] * len(encs)
        if checker == "append":
            prohibited = elle.AppendChecker().prohibited
            if host_only:
                cycles_per = [elle.cycle_anomalies_cpu(e) for e in encs]
            else:
                # the sweep's exact routing: histories past the dense
                # [T,T] limit go through SCC condensation
                # (check_long_history), everything else through the
                # bucketed dispatch — a streamed verdict for a 100k-op
                # history must match the post-hoc one, not quarantine
                # on a doomed dense closure
                cycles_per: list = [None] * len(encs)
                dense = [i for i, e in enumerate(encs)
                         if e.n <= parallel.DENSE_TXN_LIMIT]
                if dense:
                    ds: list | None = [] if want_stats else None
                    got = parallel.check_bucketed(
                        [encs[i] for i in dense], self.mesh,
                        budget_cells=self.budget_cells,
                        phases=self.phases, stats_out=ds)
                    for j, (i, cy) in enumerate(zip(dense, got)):
                        cycles_per[i] = cy
                        if ds is not None:
                            fold_stats[i] = ds[j]
                for i, e in enumerate(encs):
                    if e.n <= parallel.DENSE_TXN_LIMIT:
                        continue
                    hs: list | None = [] if want_stats else None
                    try:
                        cycles_per[i] = parallel.check_long_history(
                            e, None,
                            dense_limit=parallel.DENSE_TXN_LIMIT,
                            stats_out=hs)
                        if hs:
                            fold_stats[i] = hs[0]
                    except Exception as err:
                        # one monster history fails alone (the cli
                        # huge-path contract)
                        cycles_per[i] = sv.Quarantined("check",
                                                       repr(err))
            out = []
            for enc, cycles in zip(encs, cycles_per):
                if isinstance(cycles, sv.Quarantined):
                    out.append(cycles.verdict("append"))
                    continue
                res = elle.render_verdict(enc, cycles, prohibited)
                res["checker"] = "append"
                out.append(res)
            if stats_out is not None:
                stats_out.extend(fold_stats)
            return out
        if checker == "wr":
            prohibited = elle_wr.WrChecker().prohibited
            if host_only:
                cycles_per = [elle_wr.cycle_anomalies_cpu(e)
                              for e in encs]
            else:
                # the wr sweep's exact backdown ladder (bucketed batch
                # -> singletons -> quarantine), shared with cli so the
                # two dispatch owners can't drift
                from ..cli import _wr_chunk_with_backdown
                ws: list | None = [] if want_stats else None
                cycles_per = _wr_chunk_with_backdown(
                    [(None, e) for e in encs], elle_kernels, elle_wr,
                    stats_out=ws)
                if ws is not None:
                    fold_stats[:len(ws)] = ws
            out = []
            for enc, cycles in zip(encs, cycles_per):
                if hasattr(cycles, "verdict"):   # supervisor.Quarantined
                    out.append(cycles.verdict("wr"))
                    continue
                res = elle_wr.render_wr_verdict(enc, cycles, prohibited)
                res["checker"] = "wr"
                out.append(res)
            if stats_out is not None:
                stats_out.extend(fold_stats)
            return out
        raise ValueError(f"unknown checker {checker!r}")
