"""The pure generator DSL: an immutable algebra for scheduling operations.

Counterpart of the reference's jepsen.generator.pure
(jepsen/src/jepsen/generator/pure.clj) — the deprecated stateful generator
is intentionally not ported (pure.clj:23-34 explains why).

A generator is asked for operations with

    op(gen, test, ctx)  ->  None                 exhausted
                         |  (PENDING, gen')      nothing *yet*
                         |  (op_dict, gen')      an operation + next state

and told about events (invocations and completions) with

    update(gen, test, ctx, event) -> gen'

Plain Python values lift into generators (pure.clj:504-566):

  None        the empty generator
  dict        yields exactly one op shaped like itself, with type/process/
              time filled from context
  callable    called (with (test, ctx) if it accepts two args) to produce
              a generator; re-called when that generator is exhausted
  list/tuple  a sequence of generators, run one after the next

The context tracks logical time (nanos), which threads are free, and the
thread->process map (pure.clj:417-426). Thread ids are ints plus
"nemesis".
"""

from __future__ import annotations

import inspect
import logging
import random
import weakref
from typing import Any, Callable, Iterable

log = logging.getLogger(__name__)

#: module-local alias: attribute lookups cost in the per-op hot path
_rand = random.random


class _Pending:
    __slots__ = ()

    def __repr__(self):
        return ":pending"


PENDING = _Pending()

NEMESIS = "nemesis"


def secs_to_nanos(s: float) -> int:
    return int(s * 1e9)


class Context:
    """Generator context: immutable; mutators return new contexts."""

    __slots__ = ("time", "free_threads", "workers")

    def __init__(self, time: int, free_threads: frozenset, workers: dict):
        self.time = time
        self.free_threads = free_threads
        self.workers = workers

    @staticmethod
    def for_test(test: dict) -> "Context":
        threads = frozenset(range(test.get("concurrency", 5))) | {NEMESIS}
        return Context(0, threads, {t: t for t in threads})

    def with_time(self, t: int) -> "Context":
        return Context(t, self.free_threads, self.workers)

    def busy(self, thread) -> "Context":
        return Context(self.time, self.free_threads - {thread}, self.workers)

    def free(self, thread) -> "Context":
        return Context(self.time, self.free_threads | {thread}, self.workers)

    def with_worker(self, thread, process) -> "Context":
        w = dict(self.workers)
        w[thread] = process
        return Context(self.time, self.free_threads, w)

    def restrict(self, pred: Callable[[Any], bool]) -> "Context":
        """Context containing only threads satisfying pred
        (on-threads-context, pure.clj:797-808)."""
        return Context(self.time,
                       frozenset(t for t in self.free_threads if pred(t)),
                       {t: p for t, p in self.workers.items() if pred(t)})

    # -- queries (pure.clj:440-487) ---------------------------------------

    def free_processes(self) -> list:
        return [self.workers[t] for t in self.free_threads]

    def some_free_process(self):
        for t in self.free_threads:
            return self.workers[t]
        return None

    def all_processes(self) -> list:
        return list(self.workers.values())

    def all_threads(self) -> list:
        return list(self.workers.keys())

    def process_to_thread(self, process):
        for t, p in self.workers.items():
            if p == process:
                return t
        return None

    def thread_to_process(self, thread):
        return self.workers.get(thread)

    def next_process(self, thread):
        """Process to replace a crashed one: p + (count of int processes)
        (pure.clj:478-486)."""
        if isinstance(thread, int):
            return self.workers[thread] + sum(
                1 for p in self.workers.values() if isinstance(p, int))
        return thread


def fill_in_op(op: dict, ctx: Context):
    """Fill :type/:process/:time from context; PENDING if no process free
    (pure.clj:489-502)."""
    p = ctx.some_free_process()
    if p is None:
        return PENDING
    out = dict(op)
    out.setdefault("time", ctx.time)
    out.setdefault("process", p)
    out.setdefault("type", "invoke")
    return out


class Generator:
    """Base class for combinators. Plain values need not subclass this —
    the `op`/`update` module functions lift them."""

    # empty slots so hot subclasses' __slots__ actually elide __dict__
    # (subclasses that don't declare slots still get one implicitly)
    __slots__ = ()

    def op(self, test: dict, ctx: Context):
        raise NotImplementedError

    def update(self, test: dict, ctx: Context, event: dict) -> "Generator":
        return self


_fn_arity = weakref.WeakKeyDictionary()


def _call_fn(f: Callable, test: dict, ctx: Context):
    """Call an fn generator with (test, ctx) or no args, whichever its
    signature wants. The arity is memoized per function object — this
    sits in the interpreter's per-op hot loop (pure.clj:66-70's
    >20k ops/sec figure), and inspect.signature costs more than the
    whole rest of an op step. Bound methods are keyed on their
    underlying __func__ (a fresh method object is created per
    attribute access, so keying on the method itself would never hit);
    the cache stores the arity of the CALL — `self` already bound —
    which is the same for every binding of one function."""
    key = getattr(f, "__func__", f)
    try:
        nargs = _fn_arity[key]
    except (KeyError, TypeError):   # TypeError: non-weakrefable callable
        try:
            sig = inspect.signature(f)
            nargs = len([p for p in sig.parameters.values()
                         if p.default is p.empty and
                         p.kind in (p.POSITIONAL_ONLY,
                                    p.POSITIONAL_OR_KEYWORD)])
        except (TypeError, ValueError):
            nargs = 0
        try:
            _fn_arity[key] = nargs
        except TypeError:
            pass
    return f(test, ctx) if nargs == 2 else f()


class Seq(Generator):
    """A sequence of generators, run one after the next — the lifted form
    of a list/tuple. Only the head's state evolves, so stepping is O(1)
    (the raw-list path would copy the tail on every op)."""

    __slots__ = ("head", "items", "idx")

    def __init__(self, head, items: tuple, idx: int):
        self.head = head      # current generator (items[idx-1]'s state)
        self.items = items    # shared, never mutated
        self.idx = idx        # next unstarted element

    @staticmethod
    def of(items) -> "Seq | None":
        items = tuple(items)
        if not items:
            return None
        return Seq(items[0], items, 1)

    def op(self, test, ctx):
        head, idx = self.head, self.idx
        while True:
            res = op(head, test, ctx)
            if res is not None:
                o, g2 = res
                if idx >= len(self.items):
                    # Tail exhausted: unwrap to the head's own state so
                    # chained Seqs don't nest one level per op
                    # (pure.clj:536-548's cons/gen' distinction).
                    return (o, g2)
                return (o, Seq(g2, self.items, idx))
            if idx >= len(self.items):
                return None
            head = self.items[idx]
            idx += 1

    def update(self, test, ctx, event):
        # Updates go to the first (current) generator only.
        return Seq(update(self.head, test, ctx, event), self.items, self.idx)


def op(gen, test: dict, ctx: Context):
    """Ask any generator-like value for its next operation."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.op(test, ctx)
    if isinstance(gen, dict):
        o = fill_in_op(gen, ctx)
        return (o, gen if o is PENDING else None)
    if isinstance(gen, (list, tuple)):
        return op(Seq.of(gen), test, ctx)
    if callable(gen):
        produced = _call_fn(gen, test, ctx)
        if produced is None:
            return None
        return op(Seq.of([produced, gen]), test, ctx)
    raise TypeError(f"not a generator: {gen!r}")


def update(gen, test: dict, ctx: Context, event: dict):
    """Tell any generator-like value about an event."""
    if gen is None or isinstance(gen, dict) or callable(gen):
        return gen
    if isinstance(gen, Generator):
        return gen.update(test, ctx, event)
    if isinstance(gen, (list, tuple)):
        seq = Seq.of(gen)
        return None if seq is None else seq.update(test, ctx, event)
    raise TypeError(f"not a generator: {gen!r}")


def soonest_op_vec(a, b):
    """Of two (op, ...) tuples, the one whose op occurs first; op maps
    before PENDING before None (pure.clj:818-836)."""
    if a is None:
        return b
    if b is None:
        return a
    if a[0] is PENDING:
        return b
    if b[0] is PENDING:
        return a
    return a if a[0].get("time", 0) <= b[0].get("time", 0) else b


# ---------------------------------------------------------------------------
# Combinators
# ---------------------------------------------------------------------------

class Validate(Generator):
    """Asserts the generator contract op-by-op (pure.clj:568-622)."""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        if not isinstance(res, tuple) or len(res) != 2:
            raise ValueError(
                f"generator op should return None or a pair: {res!r}")
        o, g2 = res
        if o is not PENDING:
            if not isinstance(o, dict):
                raise ValueError(f"op should be PENDING or a map: {o!r}")
            free = ctx.free_processes()
            if o.get("type") not in ("sleep", "log") and \
                    o.get("process") not in free:
                raise ValueError(
                    f"process {o.get('process')!r} is not free: {free!r}")
            if o.get("time") is None:
                raise ValueError(f"op missing :time: {o!r}")
        return (o, Validate(g2))

    def update(self, test, ctx, event):
        return Validate(update(self.gen, test, ctx, event))


class FriendlyExceptions(Generator):
    """Wraps op/update, re-raising with the generator attached
    (pure.clj:624-664)."""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        try:
            res = op(self.gen, test, ctx)
        except Exception as e:
            raise RuntimeError(
                f"generator threw during op: {self.gen!r}") from e
        if res is None:
            return None
        o, g2 = res
        return (o, FriendlyExceptions(g2))

    def update(self, test, ctx, event):
        try:
            return FriendlyExceptions(update(self.gen, test, ctx, event))
        except Exception as e:
            raise RuntimeError(
                f"generator threw during update: {self.gen!r}") from e


class Trace(Generator):
    """Logs every op/update (pure.clj:666-709)."""

    def __init__(self, k, gen):
        self.k = k
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        log.info("%s op -> %r", self.k, None if res is None else res[0])
        if res is None:
            return None
        o, g2 = res
        return (o, Trace(self.k, g2))

    def update(self, test, ctx, event):
        log.info("%s update <- %r", self.k, event)
        return Trace(self.k, update(self.gen, test, ctx, event))


class Map(Generator):
    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return (o if o is PENDING else self.f(o), Map(self.f, g2))

    def update(self, test, ctx, event):
        return Map(self.f, update(self.gen, test, ctx, event))


def map_gen(f, gen):
    return Map(f, gen)


def f_map(fm: dict, gen):
    """Rewrite op :f according to the map fm (pure.clj:729-735)."""
    return Map(lambda o: {**o, "f": fm.get(o.get("f"), o.get("f"))}, gen)


class Filter(Generator):
    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        gen = self.gen
        while True:
            res = op(gen, test, ctx)
            if res is None:
                return None
            o, g2 = res
            if o is PENDING or self.f(o):
                return (o, Filter(self.f, g2))
            gen = g2

    def update(self, test, ctx, event):
        return Filter(self.f, update(self.gen, test, ctx, event))


def filter_gen(f, gen):
    return Filter(f, gen)


class IgnoreUpdates(Generator):
    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        return op(self.gen, test, ctx)

    def update(self, test, ctx, event):
        return self


class OnUpdate(Generator):
    """Custom update handler: f(this, test, ctx, event) -> gen
    (pure.clj:767-776)."""

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return (o, OnUpdate(self.f, g2))

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f, gen):
    return OnUpdate(f, gen)


class OnThreads(Generator):
    """Restrict a generator to threads satisfying f (pure.clj:810-833)."""

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx.restrict(self.f))
        if res is None:
            return None
        o, g2 = res
        return (o, OnThreads(self.f, g2))

    def update(self, test, ctx, event):
        # A crashed op's process was already remapped away from its
        # thread, so the lookup may yield None — the predicate still
        # decides (the reference's clients predicate accepts nil threads,
        # so crash completions reach client generators; pure.clj:819-822).
        thread = ctx.process_to_thread(event.get("process"))
        if self.f(thread):
            return OnThreads(
                self.f, update(self.gen, test, ctx.restrict(self.f), event))
        return self


on_threads = OnThreads
on = OnThreads


def clients(client_gen, nemesis_gen=None):
    """Restrict to client threads; or route clients/nemesis
    (pure.clj:989-1000)."""
    c = OnThreads(lambda t: t != NEMESIS, client_gen)
    if nemesis_gen is None:
        return c
    return any_gen(c, nemesis(nemesis_gen))


def nemesis(nemesis_gen):
    return OnThreads(lambda t: t == NEMESIS, nemesis_gen)


class Any(Generator):
    """Operations from whichever generator is soonest (pure.clj:838-858)."""

    def __init__(self, gens: list):
        self.gens = list(gens)

    def op(self, test, ctx):
        soonest = None
        for i, gen in enumerate(self.gens):
            res = op(gen, test, ctx)
            if res is not None:
                soonest = soonest_op_vec(soonest, (*res, i))
        if soonest is None:
            return None
        o, g2, i = soonest
        gens = list(self.gens)
        gens[i] = g2
        return (o, Any(gens))

    def update(self, test, ctx, event):
        return Any([update(g, test, ctx, event) for g in self.gens])


def any_gen(*gens):
    if not gens:
        return None
    if len(gens) == 1:
        return gens[0]
    return Any(list(gens))


class EachThread(Generator):
    """An independent copy of the generator per thread (pure.clj:861-909)."""

    def __init__(self, fresh_gen, gens: dict | None = None):
        self.fresh_gen = fresh_gen
        self.gens = gens or {}

    def _thread_ctx(self, ctx, thread):
        return Context(ctx.time, frozenset({thread}),
                       {thread: ctx.workers[thread]})

    def op(self, test, ctx):
        soonest = None
        for thread in ctx.free_threads:
            gen = self.gens.get(thread, self.fresh_gen)
            res = op(gen, test, self._thread_ctx(ctx, thread))
            if res is not None:
                soonest = soonest_op_vec(soonest, (*res, thread))
        if soonest is not None:
            o, g2, thread = soonest
            gens = dict(self.gens)
            gens[thread] = g2
            return (o, EachThread(self.fresh_gen, gens))
        if len(ctx.free_threads) != len(ctx.workers):
            return (PENDING, self)  # busy threads may still want ops
        return None  # every thread exhausted

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        if thread is None:
            return self
        gen = self.gens.get(thread, self.fresh_gen)
        tctx = Context(ctx.time,
                       ctx.free_threads & frozenset({thread}),
                       {thread: ctx.workers[thread]})
        gens = dict(self.gens)
        gens[thread] = update(gen, test, tctx, event)
        return EachThread(self.fresh_gen, gens)


def each_thread(gen):
    return EachThread(gen)


class Reserve(Generator):
    """Dedicate thread ranges to generators (pure.clj:911-987)."""

    def __init__(self, ranges: list[frozenset], gens: list):
        # gens has len(ranges)+1 entries; last is the default generator.
        self.ranges = ranges
        self.all_ranges = frozenset().union(*ranges) if ranges else frozenset()
        self.gens = list(gens)

    def op(self, test, ctx):
        soonest = None
        for i, threads in enumerate(self.ranges):
            res = op(self.gens[i], test, ctx.restrict(threads.__contains__))
            if res is not None:
                soonest = soonest_op_vec(soonest, (*res, i))
        res = op(self.gens[-1], test,
                 ctx.restrict(lambda t: t not in self.all_ranges))
        if res is not None:
            soonest = soonest_op_vec(soonest, (*res, len(self.ranges)))
        if soonest is None:
            return None
        o, g2, i = soonest
        gens = list(self.gens)
        gens[i] = g2
        return (o, Reserve(self.ranges, gens))

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        i = len(self.ranges)
        for j, r in enumerate(self.ranges):
            if thread in r:
                i = j
                break
        gens = list(self.gens)
        gens[i] = update(gens[i], test, ctx, event)
        return Reserve(self.ranges, gens)


def reserve(*args):
    """reserve(5, write_gen, 10, cas_gen, default_gen)."""
    *pairs, default = args
    assert default is not None
    assert len(pairs) % 2 == 0
    ranges: list[frozenset] = []
    gens: list = []
    n = 0
    for i in range(0, len(pairs), 2):
        count, gen = pairs[i], pairs[i + 1]
        ranges.append(frozenset(range(n, n + count)))
        gens.append(gen)
        n += count
    gens.append(default)
    return Reserve(ranges, gens)


class Mix(Generator):
    """Uniform random mixture; ignores updates (pure.clj:1020-1046)."""

    __slots__ = ("gens", "i")

    def __init__(self, gens: list, i: int | None = None):
        self.gens = list(gens)
        self.i = int(_rand() * len(gens)) if i is None and gens else (i or 0)

    @classmethod
    def _share(cls, gens: list) -> "Mix":
        """A re-rolled Mix over an UNCHANGED gens list, skipping the
        defensive copy — the single alternate construction path for
        the per-op fast path below (keep in sync with __init__)."""
        nxt = cls.__new__(cls)
        nxt.gens = gens
        nxt.i = int(_rand() * len(gens))
        return nxt

    def op(self, test, ctx):
        if not self.gens:
            return None
        res = op(self.gens[self.i], test, ctx)
        if res is not None:
            o, g2 = res
            if g2 is self.gens[self.i]:
                # unchanged sub-generator (Repeat/dict/Limit-PENDING):
                # share the gens list and only re-roll the choice —
                # the per-op hot path of every mix-of-repeats workload
                return (o, Mix._share(self.gens))
            gens = list(self.gens)
            gens[self.i] = g2
            return (o, Mix(gens))
        gens = self.gens[: self.i] + self.gens[self.i + 1:]
        if not gens:
            return None
        return Mix(gens).op(test, ctx)


def mix(gens):
    gens = list(gens)
    return Mix(gens) if gens else None


class Limit(Generator):

    __slots__ = ("remaining", "gen")
    def __init__(self, remaining: int, gen):
        self.remaining = remaining
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING and g2 is self.gen:
            return (o, self)    # no-op step: nothing changed
        n = self.remaining if o is PENDING else self.remaining - 1
        return (o, Limit(n, g2))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Limit(self.remaining, g2)


def limit(n, gen):
    return Limit(n, gen)


def once(gen):
    return Limit(1, gen)


def log_gen(msg):
    """A special op that logs a message (pure.clj:1069-1073)."""
    return {"type": "log", "value": msg}


class Repeat(Generator):
    """Re-yield the underlying generator's op without consuming it
    (pure.clj:1075-1102). remaining < 0 means forever."""

    __slots__ = ("remaining", "gen")

    def __init__(self, remaining: int, gen):
        self.remaining = remaining
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, _ = res
        if self.remaining < 0 or o is PENDING:
            return (o, self)    # forever / no-op step: nothing changed
        return (o, Repeat(self.remaining - 1, self.gen))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        return self if g2 is self.gen else Repeat(self.remaining, g2)


def repeat_gen(gen, n: int = -1):
    return Repeat(n, gen)


class Cycle(Generator):
    """Endlessly restart `source` when it's exhausted — the semantics
    of the reference's `(cycle [...])` nemesis schedules
    (etcd.clj:174-178). Unlike Repeat (which re-yields the FIRST op
    forever, pure.clj:1075), Cycle consumes the whole sequence and
    starts over. `source` must be a pure generator value (lists of op
    maps/sleeps are), since each lap re-reads it."""

    def __init__(self, source, current=None):
        self.source = source
        self.current = current if current is not None else source

    def op(self, test, ctx):
        res = op(self.current, test, ctx)
        if res is None:
            res = op(self.source, test, ctx)   # start the next lap
            if res is None:
                return None                    # source yields nothing
        o, g2 = res
        return (o, Cycle(self.source, g2))

    def update(self, test, ctx, event):
        return Cycle(self.source, update(self.current, test, ctx, event))


def cycle(gen):
    return Cycle(gen)


class ProcessLimit(Generator):
    """Emit ops for at most n distinct processes (pure.clj:1104-1129)."""

    def __init__(self, n: int, procs: frozenset, gen):
        self.n = n
        self.procs = procs
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, ProcessLimit(self.n, self.procs, g2))
        procs = self.procs | frozenset(
            p for p in ctx.all_processes() if isinstance(p, int))
        if len(procs) > self.n:
            return None
        return (o, ProcessLimit(self.n, procs, g2))

    def update(self, test, ctx, event):
        return ProcessLimit(self.n, self.procs,
                            update(self.gen, test, ctx, event))


def process_limit(n, gen):
    return ProcessLimit(n, frozenset(), gen)


class TimeLimit(Generator):
    """Emit ops for dt seconds after the first op (pure.clj:1131-1155)."""

    def __init__(self, limit_nanos: int, cutoff: int | None, gen):
        self.limit = limit_nanos
        self.cutoff = cutoff
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, TimeLimit(self.limit, self.cutoff, g2))
        cutoff = self.cutoff if self.cutoff is not None \
            else o.get("time", 0) + self.limit
        if o.get("time", 0) >= cutoff:
            return None
        return (o, TimeLimit(self.limit, cutoff, g2))

    def update(self, test, ctx, event):
        return TimeLimit(self.limit, self.cutoff,
                         update(self.gen, test, ctx, event))


def time_limit(dt_secs: float, gen):
    return TimeLimit(secs_to_nanos(dt_secs), None, gen)


class Stagger(Generator):
    """Schedule ops at uniformly random intervals averaging dt
    (pure.clj:1157-1199). Applies to all ops, not per-thread."""

    def __init__(self, dt: int, next_time: int, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, self)
        nt = self.next_time + int(random.random() * self.dt)
        if self.next_time <= o.get("time", 0):
            return (o, Stagger(self.dt, nt, g2))
        return ({**o, "time": self.next_time}, Stagger(self.dt, nt, g2))

    def update(self, test, ctx, event):
        return Stagger(self.dt, self.next_time,
                       update(self.gen, test, ctx, event))


def stagger(dt_secs: float, gen):
    return Stagger(secs_to_nanos(2 * dt_secs), 0, gen)


class DelayTil(Generator):
    """Align invocation times to multiples of dt (pure.clj:1233-1262)."""

    def __init__(self, dt: int, anchor: int | None, gen):
        self.dt = dt
        self.anchor = anchor
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, DelayTil(self.dt, self.anchor, g2))
        t = o.get("time", 0)
        anchor = self.anchor if self.anchor is not None else t
        t = t + (self.dt - ((t - anchor) % self.dt)) % self.dt
        return ({**o, "time": t}, DelayTil(self.dt, anchor, g2))

    def update(self, test, ctx, event):
        return DelayTil(self.dt, self.anchor,
                        update(self.gen, test, ctx, event))


def delay_til(dt_secs: float, gen):
    return DelayTil(secs_to_nanos(dt_secs), None, gen)


def delay(dt_secs: float, gen):
    """Ops at least dt apart — reference aliases this to delay-til."""
    return delay_til(dt_secs, gen)


def sleep(dt_secs: float):
    """One special op making its process do nothing for dt seconds
    (pure.clj:1264-1268)."""
    return {"type": "sleep", "value": dt_secs}


class Synchronize(Generator):
    """Wait until all workers are free, then become gen
    (pure.clj:1270-1290)."""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        if len(ctx.free_threads) == len(ctx.workers):
            return op(self.gen, test, ctx)
        return (PENDING, self)

    def update(self, test, ctx, event):
        return Synchronize(update(self.gen, test, ctx, event))


def synchronize(gen):
    return Synchronize(gen)


def phases(*gens):
    """Each generator runs to completion, with a barrier between
    (pure.clj:1292-1297)."""
    return [Synchronize(g) for g in gens]


def then(a, b):
    """b, then (synchronize a). Argument order matches the reference for
    pipeline composition (pure.clj:1299-1308)."""
    return [b, Synchronize(a)]


class UntilOk(Generator):
    """Yield ops until one completes :ok (pure.clj:1310-1328)."""

    def __init__(self, gen, done: bool = False):
        self.gen = gen
        self.done = done

    def op(self, test, ctx):
        if self.done:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return (o, UntilOk(g2, False))

    def update(self, test, ctx, event):
        if event.get("type") == "ok":
            return UntilOk(self.gen, True)
        return UntilOk(update(self.gen, test, ctx, event), self.done)


def until_ok(gen):
    return UntilOk(gen)


class FlipFlop(Generator):
    """Alternate between generators; stop when one is exhausted
    (pure.clj:1330-1344)."""

    def __init__(self, gens: list, i: int = 0):
        self.gens = list(gens)
        self.i = i

    def op(self, test, ctx):
        res = op(self.gens[self.i], test, ctx)
        if res is None:
            return None
        o, g2 = res
        gens = list(self.gens)
        gens[self.i] = g2
        return (o, FlipFlop(gens, (self.i + 1) % len(gens)))


def flip_flop(a, b):
    return FlipFlop([a, b])
