"""The generator interpreter: pure generators meet real threads.

Counterpart of jepsen.generator.interpreter
(jepsen/src/jepsen/generator/interpreter.clj): spawns one worker thread
per context thread (clients + nemesis), pumps invocations through
per-worker queues, applies them with the test's client/nemesis, and
journals invocations and completions into the history.

Key behaviors preserved from the reference:
  * completions are drained before new invocations (latency-sensitive;
    interpreter.clj:196-204)
  * a crashed client op (:info) permanently retires that process; the
    thread is reassigned process p + concurrency and gets a fresh client
    (interpreter.clj:216-219)
  * :sleep and :log special ops execute on workers but stay out of the
    history (goes_in_history, interpreter.clj:167-173)
  * when the generator is pending, we wait at most 1 ms before asking it
    again (max-pending-interval, interpreter.clj:161-165)
  * generator exceptions cancel workers once, then queue :exit
    (interpreter.clj:276-292)
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any

from .. import client as jclient
from .. import generator as gen
from ..util import relative_time_nanos

log = logging.getLogger(__name__)

MAX_PENDING_INTERVAL_S = 0.001  # 1 ms


def goes_in_history(op: dict) -> bool:
    return op.get("type") not in ("sleep", "log")


class ClientWorker:
    """Owns the client for whatever process its thread currently runs
    (interpreter.clj:32-63)."""

    def __init__(self, node: str):
        self.node = node
        self.process = None
        self.client = None

    def invoke(self, test: dict, op: dict) -> dict:
        if self.process != op.get("process"):
            self.close(test)
            try:
                base = test.get("client") or jclient.noop()
                self.client = base.open(test, self.node)
                self.process = op.get("process")
            except Exception as e:
                log.warning("Error opening client: %s", e)
                self.client = None
                return {**op, "type": "fail", "error": ["no-client", str(e)]}
        return self.client.invoke(test, op)

    def close(self, test: dict) -> None:
        if self.client is not None:
            try:
                self.client.close(test)
            finally:
                self.client = None


class NemesisWorker:
    def invoke(self, test: dict, op: dict) -> dict:
        nem = test.get("nemesis")
        if nem is None:
            return {**op, "type": "info"}
        return nem.invoke(test, op)

    def close(self, test: dict) -> None:
        pass


def _make_worker(test: dict, wid) -> Any:
    if isinstance(wid, int):
        nodes = test.get("nodes") or ["local"]
        return ClientWorker(nodes[wid % len(nodes)])
    return NemesisWorker()


def _worker_loop(test: dict, wid, in_q: queue.Queue, out_q: queue.Queue,
                 drain_event: threading.Event):
    worker = _make_worker(test, wid)
    try:
        while True:
            op = in_q.get()
            t = op.get("type")
            if t == "exit":
                return
            try:
                if t == "sleep":
                    # interruptible: once the generator is exhausted the
                    # event loop sets drain_event, so a long nemesis
                    # sleep can't hold the whole run open past its
                    # time limit (the sleep's pacing is moot by then)
                    drain_event.wait(op.get("value") or 0)
                    out_q.put(op)
                elif t == "log":
                    log.info("%s", op.get("value"))
                    out_q.put(op)
                else:
                    out_q.put(worker.invoke(test, op))
            except BaseException as e:  # crashes become :info completions
                log.warning("Process %r crashed: %s", op.get("process"), e)
                out_q.put({**op, "type": "info",
                           "error": f"indeterminate: {e}",
                           "exception": {"class": type(e).__name__,
                                         "message": str(e)}})
    finally:
        worker.close(test)


def run(test: dict) -> list[dict]:
    """Evaluate all ops from test["generator"], returning the history.
    Callers must be inside util.relative_time (t=0 anchor)."""
    ctx = gen.Context.for_test(test)
    worker_ids = ctx.all_threads()
    completions: queue.Queue = queue.Queue()
    invocations: dict = {}
    drain_event = threading.Event()
    threads = []
    for wid in worker_ids:
        in_q: queue.Queue = queue.Queue(maxsize=1)
        invocations[wid] = in_q
        th = threading.Thread(
            target=_worker_loop,
            args=(test, wid, in_q, completions, drain_event),
            name=f"jepsen-worker-{wid}", daemon=True)
        th.start()
        threads.append(th)

    g = gen.Validate(gen.FriendlyExceptions(test.get("generator")))
    history: list = []
    outstanding = 0
    poll_timeout = 0.0
    try:
        while True:
            op_c = None
            try:
                if poll_timeout > 0:
                    op_c = completions.get(timeout=poll_timeout)
                else:
                    op_c = completions.get_nowait()
            except queue.Empty:
                op_c = None

            if op_c is not None:
                thread = ctx.process_to_thread(op_c.get("process"))
                now = relative_time_nanos()
                op_c = {**op_c, "time": now}
                ctx = ctx.with_time(now).free(thread)
                if thread != gen.NEMESIS and op_c.get("type") == "info":
                    ctx = ctx.with_worker(thread, ctx.next_process(thread))
                g = gen.update(g, test, ctx, op_c)
                if goes_in_history(op_c):
                    history.append(op_c)
                outstanding -= 1
                poll_timeout = 0.0
                continue

            now = relative_time_nanos()
            ctx = ctx.with_time(now)
            res = gen.op(g, test, ctx)
            if res is None:
                drain_event.set()   # wake any sleeping workers
                if outstanding > 0:
                    poll_timeout = MAX_PENDING_INTERVAL_S
                    continue
                for in_q in invocations.values():
                    in_q.put({"type": "exit"})
                for th in threads:
                    th.join()
                return history
            o, g2 = res
            if o is gen.PENDING:
                poll_timeout = MAX_PENDING_INTERVAL_S
                continue
            if now < o.get("time", 0):
                # Not time yet; wait for completions until it's due.
                poll_timeout = (o["time"] - now) / 1e9
                continue
            thread = ctx.process_to_thread(o.get("process"))
            invocations[thread].put(o)
            ctx = ctx.with_time(o.get("time", now)).busy(thread)
            g2 = gen.update(g2, test, ctx, o)
            if goes_in_history(o):
                history.append(o)
            g = g2
            outstanding += 1
            poll_timeout = 0.0
    except BaseException:
        log.info("Shutting down workers after abnormal exit")
        drain_event.set()
        for in_q in invocations.values():
            try:
                # Workers drain their single-slot queue quickly; if one is
                # truly wedged it's a daemon thread and dies with us.
                in_q.put({"type": "exit"}, timeout=1.0)
            except queue.Full:
                pass
        raise
