"""Live health snapshots: `<store>/health.json`, atomically, every N s.

The snapshot answers the three operator questions about a RUNNING
sweep — how far along (runs verdicted / total, buckets dispatched vs
resolved, inflight depth), how healthy (the supervisor's quarantine/
OOM/watchdog counters), how fast (throughput + ETA) — plus a
monotonic heartbeat so a wedged sweep is distinguishable from a slow
one: a fresh heartbeat over stale progress means the process is alive
but stuck; a stale heartbeat means it is gone.

Writes go temp-file → `os.replace`, so a concurrent reader (or a
scrape of `/healthz`, which serves the same dict) never sees a torn
file. Gated by `JEPSEN_TPU_HEALTH_INTERVAL_S` (default off): with the
gate unset a sweep pays one `gates.get` and nothing else.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from pathlib import Path

from .. import gates, trace
from . import events

log = logging.getLogger(__name__)

HEALTH_NAME = "health.json"


def health_interval_s() -> float | None:
    """The JEPSEN_TPU_HEALTH_INTERVAL_S gate (seconds; unset/<=0 =
    off, the default — live telemetry is opt-in)."""
    v = gates.get("JEPSEN_TPU_HEALTH_INTERVAL_S")
    return v if v is not None and v > 0 else None


def health_snapshot(tracer=None, *, seq: int = 0,
                    started_mono: float | None = None,
                    extra: dict | None = None) -> dict:
    """The one snapshot shape health.json and `/healthz` both serve,
    derived entirely from the current tracer's metrics (plus the
    sampler's own heartbeat bookkeeping). Works against the NullTracer
    too — every field the metrics can't answer is null, never absent.
    `extra` merges owner-specific top-level sections into the snapshot
    (the serve daemon's `"serve"` section rides this seam); the core
    keys always win on a collision."""
    tr = tracer if tracer is not None else trace.get_current()
    md = tr.metrics_dict() if getattr(tr, "enabled", False) else {}
    c = md.get("counters", {})
    g = md.get("gauges", {})
    now = time.monotonic()
    done = c.get("runs_verdicted", 0)
    total = g.get("runs_total")
    elapsed = (now - started_mono) if started_mono is not None else None
    rate = (done / elapsed) if elapsed and elapsed > 0 else None
    eta = None
    if rate and isinstance(total, (int, float)) and total > done:
        eta = (total - done) / rate
    return {
        **(extra or {}),
        "v": 1,
        "run": getattr(tr, "run", None),
        # the liveness signal: seq strictly increases per write and
        # monotonic/wall give the reader both clocks — progress can
        # stall while the heartbeat stays fresh (wedged, not dead)
        "heartbeat": {"seq": seq,
                      "monotonic": round(now, 6),
                      "wall": round(time.time(), 6)},
        "progress": {
            "runs_total": total,
            "runs_verdicted": done,
            "buckets_dispatched": c.get("buckets_dispatched", 0),
            "buckets_resolved": c.get("buckets_resolved", 0),
            "inflight_depth": g.get("inflight_depth"),
        },
        "robustness": {k: c.get(k, 0)
                       for k in ("quarantined", "oom_retries",
                                 "bucket_splits", "watchdog_timeouts")},
        # the HBM residency ledger (jepsen_tpu/obs/device.py, gated by
        # JEPSEN_TPU_COSTDB): resident AOT executables, modeled device
        # bytes in flight, the backend's own accounting where the
        # platform reports one, and cumulative donated bytes — null
        # (not absent) when the observatory never published
        "device": {
            "resident_executables": g.get("resident_executables"),
            "hbm_modeled_bytes": g.get("hbm_modeled_bytes"),
            "hbm_device_bytes": g.get("hbm_device_bytes"),
            "donated_bytes": c.get("donated_bytes"),
        },
        "throughput": {
            "elapsed_secs": round(elapsed, 3) if elapsed is not None
            else None,
            "runs_per_sec": round(rate, 4) if rate is not None else None,
            "eta_secs": round(eta, 1) if eta is not None else None,
        },
    }


def write_health(path, snap: dict) -> Path | None:
    """Atomic snapshot write (trace.atomic_write_text: temp in the
    same directory, then `os.replace`) — a reader sees the previous
    complete file or the new complete file, never bytes of both.
    Best-effort (None on failure): observability must never sink the
    sweep."""
    try:
        return trace.atomic_write_text(path, json.dumps(snap, indent=2))
    except OSError:
        log.debug("health snapshot write failed for %s", path,
                  exc_info=True)
        return None


class HealthSampler:
    """The background sampler: a daemon thread that writes
    `<dir>/health.json` every `interval` seconds until stopped, plus
    one final snapshot at stop so the file always reflects the sweep's
    end state. `tracer_fn` is read at each tick (not captured), so a
    `fresh_run` swap mid-flight is picked up automatically."""

    def __init__(self, store_base, interval: float,
                 tracer_fn=trace.get_current, extra_fn=None):
        self.path = Path(store_base) / HEALTH_NAME
        self.interval = float(interval)
        self._tracer_fn = tracer_fn
        # owner-specific snapshot section (the serve daemon's "serve"
        # dict), read at each tick like the tracer; None = core only
        self._extra_fn = extra_fn
        self._seq = 0
        self._t0 = time.monotonic()
        # serializes the tick thread against /healthz handler threads
        # (both call write_snapshot): seq stays strictly increasing
        # and two writers can't interleave on the shared temp path
        self._wlock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="obs-health", daemon=True)

    def start(self) -> "HealthSampler":
        events.emit("health_sample", seq=0,
                    interval_s=self.interval, path=str(self.path))
        self.write_snapshot()
        self._thread.start()
        return self

    def write_snapshot(self) -> dict:
        with self._wlock:
            self._seq += 1
            extra = None
            if self._extra_fn is not None:
                try:
                    extra = self._extra_fn()
                except Exception:
                    log.debug("health extra section failed",
                              exc_info=True)
            snap = health_snapshot(self._tracer_fn(), seq=self._seq,
                                   started_mono=self._t0, extra=extra)
            write_health(self.path, snap)
        return snap

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.write_snapshot()
            except Exception:
                # never let a bad tick kill the sampler thread
                log.debug("health sample tick failed", exc_info=True)

    def stop(self) -> None:
        """Stop the thread and write the final snapshot."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=max(5.0, 2 * self.interval))
        try:
            snap = self.write_snapshot()
            events.emit("health_sample", seq=snap["heartbeat"]["seq"],
                        final=True)
        except Exception:
            log.debug("final health snapshot failed", exc_info=True)


def maybe_start_health_sampler(store_base,
                               tracer_fn=trace.get_current,
                               extra_fn=None) -> HealthSampler | None:
    """Start the sampler when JEPSEN_TPU_HEALTH_INTERVAL_S enables it;
    None (and zero work) otherwise — the sweep's one-line integration
    point."""
    interval = health_interval_s()
    if interval is None:
        return None
    return HealthSampler(store_base, interval, tracer_fn=tracer_fn,
                         extra_fn=extra_fn).start()
