"""jepsen_tpu.obs — LIVE telemetry over the per-run tracer.

PR-2 made every run self-attributing, but only *post hoc*: counters
live inside the Tracer until the sweep exits, so a running sweep is a
black box — an operator (or the multi-host coordinator / the future
`serve` daemon) cannot ask "how far along, how healthy, how fast"
mid-flight. The online-checking literature (PAPERS.md, arxiv
2504.01477) makes the same point about checkers themselves:
infrastructure that runs continuously must be observable continuously.
This package is that layer, in four stdlib-only pieces:

  * `health` — a background sampler thread (gated by
    `JEPSEN_TPU_HEALTH_INTERVAL_S`, default off) that every N seconds
    atomically writes `<store>/health.json`: sweep progress (runs
    verdicted / total, buckets dispatched vs resolved, inflight
    depth), robustness posture (quarantine/OOM/watchdog counters),
    throughput + ETA, and a monotonic heartbeat so a wedged sweep is
    distinguishable from a slow one. Write-to-temp-then-rename: a
    reader never sees a torn file.
  * `prom` — the Prometheus text-exposition renderer plus an optional
    `http.server` endpoint (`JEPSEN_TPU_METRICS_PORT`) serving
    `/metrics` (counters/gauges/histograms; log2 magnitude buckets map
    to cumulative `_bucket` series) and `/healthz` (the same snapshot
    as health.json) — the scrape surface the future `serve` daemon and
    per-shard mesh sweeps will expose.
  * `events` — the flight recorder: an append-only
    `<store>/events.jsonl` of TYPED lifecycle events (sweep
    start/resume/end, quarantine with cause, OOM split, watchdog fire,
    journal seal, cache rebuild), each line flushed as it lands (the
    VerdictJournal discipline), so a post-mortem on a SIGKILLed sweep
    has a causal record even when trace.json was never written. Lint
    rule JT-TRACE-003 requires every event to go through
    `events.emit` with a declared kind — no ad-hoc dict writes.
  * `bench_report` — the trajectory gate: `python -m jepsen_tpu.cli
    bench-report` loads the `BENCH_*.json` series, prints a per-metric
    trend table, and exits non-zero when the latest round regresses
    past a declared threshold vs its same-backend predecessor.
  * `attribution` — the critical-path report over the MERGED sweep
    timeline (parent phases + per-worker spool tracks + device
    windows): serial bottleneck decomposition, device-gap stall
    accounting, and what-if headroom, persisted by `analyze-store
    --report` as `<store>/report.json` + `report.md` and embedded in
    the bench's north_star/cache_warm blocks.
  * `device` — the device cost observatory (JEPSEN_TPU_COSTDB,
    default off): per-executable XLA cost/memory analyses joined
    with measured dispatch windows, the HBM residency gauges, and
    the persistent `<store>/costdb.jsonl` the cost-aware planner
    consumes; `--report` grows a device roofline section from the
    same records.

The whole package imports nothing but the stdlib (plus `gates` and
`trace`, themselves stdlib-only); jax is never touched. Everything is
gated off by default — with both gates unset a sweep pays nothing but
one `gates.get` per entry point.
"""

from __future__ import annotations

from . import attribution, device, events
from .events import EVENT_KINDS, emit, install_events, load_events, reset_events
from .health import HealthSampler, health_snapshot, maybe_start_health_sampler
from .prom import MetricsServer, maybe_start_metrics_server, render_prometheus

__all__ = [
    "EVENT_KINDS", "HealthSampler", "MetricsServer", "attribution",
    "device", "emit", "events", "health_snapshot", "install_events",
    "load_events", "maybe_start_health_sampler",
    "maybe_start_metrics_server", "render_prometheus", "reset_events",
]
