"""Prometheus text exposition + the optional `/metrics` endpoint.

`render_prometheus` maps the tracer's metric registry 1:1 onto the
text exposition format (version 0.0.4): counters and gauges keep their
registry names (sanitized, `jepsen_tpu_` prefixed) so a scraped value
always matches the same key in the final metrics.json — no renaming
layer to drift. Histograms are summary-stat + log2 magnitude buckets
in the registry; each magnitude bucket `b` (values in [2^b, 2^(b+1)))
becomes the cumulative `_bucket{le="2^(b+1)"}` series, closed by
`+Inf`/`_sum`/`_count` as the format requires.

`MetricsServer` is a stdlib `http.server` on a daemon thread serving
`/metrics` (exposition) and `/healthz` (the health.json snapshot
dict) — gated by `JEPSEN_TPU_METRICS_PORT` (unset = off; `0` binds an
ephemeral port, printed, for tests and parallel CI). It reads the
CURRENT tracer at scrape time, so a long-lived process that rotates
tracers per sweep serves whichever is live.
"""

from __future__ import annotations

import json
import logging
import re
import threading

from .. import gates, trace
from . import events
from .health import health_snapshot

log = logging.getLogger(__name__)

PREFIX = "jepsen_tpu_"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _name(metric: str) -> str:
    return PREFIX + _NAME_RE.sub("_", metric)


def render_prometheus(tracer=None) -> str:
    """The full exposition page for a tracer's metrics dict."""
    tr = tracer if tracer is not None else trace.get_current()
    md = tr.metrics_dict() if getattr(tr, "enabled", False) else {}
    lines: list[str] = []
    for k, v in md.get("counters", {}).items():
        n = _name(k)
        lines += [f"# TYPE {n} counter", f"{n} {v}"]
    for k, v in md.get("gauges", {}).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue    # unset gauges don't render a bogus 0
        n = _name(k)
        lines += [f"# TYPE {n} gauge", f"{n} {v}"]
    for k, h in md.get("histograms", {}).items():
        n = _name(k)
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        for b, cnt in sorted((int(kb), vb) for kb, vb in
                             h.get("log2_buckets", {}).items()):
            cum += cnt
            lines.append(f'{n}_bucket{{le="{2.0 ** (b + 1)!r}"}} {cum}')
        lines.append(f'{n}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{n}_sum {h['sum']}")
        lines.append(f"{n}_count {h['count']}")
    de = md.get("dropped_events")
    if de is not None:
        n = _name("dropped_events")
        lines += [f"# TYPE {n} gauge", f"{n} {de}"]
    return "\n".join(lines) + "\n"


def metrics_port() -> int | None:
    """The JEPSEN_TPU_METRICS_PORT gate: unset = off; 0 = ephemeral."""
    v = gates.get("JEPSEN_TPU_METRICS_PORT")
    return v if v is not None and v >= 0 else None


class MetricsServer:
    """The scrape endpoint: `/metrics` (Prometheus text exposition of
    the current tracer) and `/healthz` (the live health snapshot as
    JSON). ThreadingHTTPServer on a daemon thread — scrapes never
    block the sweep, and the process never waits on the server to
    exit. `health_fn` defaults to an uptime-less snapshot; the sweep
    wires its sampler's so `/healthz` and health.json agree."""

    def __init__(self, port: int, host: str = "0.0.0.0",
                 tracer_fn=trace.get_current, health_fn=None):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer
        self._tracer_fn = tracer_fn
        self._health_fn = health_fn if health_fn is not None \
            else (lambda: health_snapshot(tracer_fn()))
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):     # noqa: N802 (http.server API)
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = render_prometheus(
                            outer._tracer_fn()).encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif self.path.split("?")[0] in ("/healthz",
                                                     "/health"):
                        body = json.dumps(outer._health_fn()).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception:
                    log.debug("scrape handler failed", exc_info=True)
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass    # scrapes must not spam the sweep's log

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="obs-metrics",
            daemon=True)
        self._thread.start()
        events.emit("metrics_serve", port=self.port)
        log.info("obs metrics endpoint on :%d (/metrics, /healthz)",
                 self.port)

    def stop(self) -> None:
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except Exception:
            log.debug("metrics server stop failed", exc_info=True)
        self._thread.join(timeout=5)


def maybe_start_metrics_server(tracer_fn=trace.get_current,
                               health_fn=None) -> MetricsServer | None:
    """Start the endpoint when JEPSEN_TPU_METRICS_PORT enables it;
    None (and zero work) otherwise. A port that cannot bind (taken,
    privileged) degrades to a warning — observability must never sink
    the sweep."""
    port = metrics_port()
    if port is None:
        return None
    try:
        return MetricsServer(port, tracer_fn=tracer_fn,
                             health_fn=health_fn)
    except OSError as e:
        log.warning("metrics endpoint failed to bind port %d: %s",
                    port, e)
        return None
