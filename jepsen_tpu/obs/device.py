"""The device cost observatory: per-executable XLA cost/memory capture
joined with measured dispatch windows, feeding a persistent costdb.

Until now device time was one opaque span: the tracer records each
dispatch's enqueue→block window, but nothing ever read XLA's own
`cost_analysis()` / `memory_analysis()` even though every bucket
dispatch flows through `aot.compiled_for`'s `lower().compile()` hook —
so MFU was an analytic estimate against a hard-coded v5e peak and the
cost-aware planner (ROADMAP item 4) had no empirical
per-(kernel, geometry) cost model to train on. This module closes
that loop in three parts, all behind `JEPSEN_TPU_COSTDB` (default
off ⇒ zero new files, <1µs per dispatch):

  * **capture** — `observe()` runs once per (kernel flags +
    formulation + bucket geometry) key: the compiled executable's
    `cost_analysis()` (flops, bytes accessed, transcendentals) and
    `memory_analysis()` (argument/output/temp/generated-code bytes),
    called from `aot.compiled_for` for single-device dispatches and
    from `residency.ExecutableResidency.dispatch_fn` (via a one-time
    `jit.lower()`, no compile) for mesh-sharded ones.
  * **join** — `begin_dispatch`/`close_dispatch` bracket each bucket
    dispatch's measured device window (the same enqueue→materialized
    window the tracer's device track records) and accumulate it into
    the key's record, so every record is analysis × measurement.
    The same bracket maintains the residency gauges: resident
    executables (the AOT in-memory map), modeled HBM in flight
    (argument + temp + output bytes of outstanding dispatches) and —
    throttled by `JEPSEN_TPU_RESIDENCY_INTERVAL_S` — the backend's
    own `device.memory_stats()` where the platform reports one.
  * **persist** — `flush()` appends one JSON line per (executable,
    geometry) record to `<store>/costdb.jsonl` (store.append_costdb:
    flushed per line, torn tails skipped on load like the journal);
    mesh shards flush to `costdb-shard<k>.jsonl` and the coordinator
    merges them (`merge_records`) into one deduplicated costdb.

Records carry a `provenance` field — `"measured"` only when the
windows were taken on a real accelerator backend; a CPU host's wall
windows are honest host measurements but NOT TPU numbers, so they tag
`"estimated"` instead of silently impersonating hardware. Everything
here is best-effort: any capture failure degrades to a debug log,
never to a failed sweep, and verdicts are byte-identical with the
gate on or off.

Module-level imports are stdlib-only (gates/trace); jax is touched
only inside functions, after the dispatch layer has already loaded it.
"""

from __future__ import annotations

import logging
import threading
import time

from .. import gates, trace

log = logging.getLogger(__name__)

#: Layout of the dispatch cost key — MUST match
#: `parallel.residency.ExecutableResidency.dispatch_key` (pinned by
#: tests/test_costdb.py so the two can't drift): (classify, realtime,
#: process_order, fused, use_pallas, use_int8, donate, n_keys,
#: max_pos, n_txns).
_KEY_FIELDS = ("classify", "realtime", "process_order", "fused",
               "use_pallas", "use_int8", "donate", "n_keys",
               "max_pos", "n_txns")

_LOCK = threading.Lock()

#: (key_parts, B) -> mutable record dict.
_records: dict[tuple, dict] = {}

#: id(device flags array) -> (record key, modeled bytes) for
#: dispatches in flight — the join between a dispatch's enqueue and
#: its materialized flags.
_pending: dict[int, tuple] = {}

_inflight_bytes = 0
_last_mem_poll = 0.0


def enabled() -> bool:
    """The JEPSEN_TPU_COSTDB gate (default off)."""
    return gates.get("JEPSEN_TPU_COSTDB")


def residency_interval_s() -> float:
    """The JEPSEN_TPU_RESIDENCY_INTERVAL_S gate: minimum seconds
    between `device.memory_stats()` polls (<=0 disables the poll)."""
    v = gates.get("JEPSEN_TPU_RESIDENCY_INTERVAL_S")
    return float(v) if v is not None else 0.0


def reset() -> None:
    """Drop every captured record and pending window (sweep start,
    tests) — the observatory is per-sweep state like the tracer."""
    global _inflight_bytes, _last_mem_poll
    with _LOCK:
        _records.clear()
        _pending.clear()
        _inflight_bytes = 0
        _last_mem_poll = 0.0


def dispatch_cost_key(kw: dict, shape, single_device: bool,
                      donate: bool) -> tuple:
    """THE cost key for one bucket dispatch. For single-device
    dispatches it IS `ExecutableResidency.dispatch_key` (so the AOT
    cache and the costdb key the same executable identically); mesh
    dispatches build the same tuple with the mesh-resolved
    formulation."""
    from ..parallel.residency import ExecutableResidency
    if single_device:
        return ExecutableResidency.dispatch_key(kw, shape, donate)
    from ..checker.elle import kernels as K
    use_pallas, use_int8 = K.resolve_formulation(single_device=False)
    # the kernel-stats marker is appended only when on, mirroring
    # ExecutableResidency.dispatch_key: the gate-off key never churns
    return (kw.get("classify", True), kw.get("realtime", False),
            kw.get("process_order", False), kw.get("fused"),
            use_pallas, use_int8, bool(donate),
            shape.n_keys, shape.max_pos, shape.n_txns) \
        + (("stats",) if kw.get("with_stats") else ())


def _cost_dict(obj) -> dict | None:
    """Normalized `cost_analysis()` of a Compiled/Lowered, or None.
    jax returns a single dict or a one-element list depending on
    version; keys of interest are `flops`, `bytes accessed` and
    `transcendentals`."""
    try:
        ca = obj.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None

    def num(k):
        v = ca.get(k)
        return float(v) if isinstance(v, (int, float)) else None

    return {"flops": num("flops"),
            "bytes_accessed": num("bytes accessed"),
            "transcendentals": num("transcendentals")}


def _memory_dict(obj) -> dict | None:
    """Normalized `memory_analysis()` (CompiledMemoryStats), or None —
    Lowered objects and some deserialized executables have none."""
    try:
        ma = obj.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def b(attr):
        v = getattr(ma, attr, None)
        return int(v) if isinstance(v, int) else None

    return {"argument_bytes": b("argument_size_in_bytes"),
            "output_bytes": b("output_size_in_bytes"),
            "temp_bytes": b("temp_size_in_bytes"),
            "alias_bytes": b("alias_size_in_bytes"),
            "generated_code_bytes": b("generated_code_size_in_bytes")}


def _backend_info() -> tuple[str, str]:
    try:
        import jax
        d = jax.devices()[0]
        return d.platform, str(d.device_kind)
    except Exception:
        return "unknown", "unknown"


def observe(key_parts: tuple, args, obj, source: str) -> None:
    """Capture one executable's analyses under (key_parts, batch) —
    once per key per process; repeats are a dict probe. `obj` is a
    Compiled executable (`source="compiled"`, the aot.compiled_for
    path — memory analysis included) or a jitted fn
    (`source="lowered"`: one `lower()` trace, no compile — the
    mesh-sharded path, where forcing a second XLA compile just to
    read costs would defeat the point). Best-effort: never raises."""
    if not enabled():
        return
    try:
        B = int(args[0].shape[0])
        key = (tuple(key_parts), B)
        with _LOCK:
            if key in _records:
                return
        if source == "lowered" and not hasattr(obj, "cost_analysis"):
            try:
                obj = obj.lower(*args)
            except Exception:
                log.debug("costdb: lower() for cost capture failed",
                          exc_info=True)
                obj = None
        cost = _cost_dict(obj) if obj is not None else None
        memory = _memory_dict(obj) if obj is not None else None
        platform, device_kind = _backend_info()
        geometry = {
            "B": B,
            "n_txns": int(key_parts[9]),
            "n_keys": int(key_parts[7]),
            "max_pos": int(key_parts[8]),
            "n_appends": int(args[0].shape[1]),
            "n_reads": int(args[1].shape[1]),
        }
        arg_bytes = sum(int(a.nbytes) for a in args)
        rec = {
            "key_parts": tuple(key_parts),
            "kernel": {f: key_parts[i] for i, f in
                       enumerate(_KEY_FIELDS[:4])},
            "formulation": (("pallas" if key_parts[4] else "xla")
                            + ("-int8" if key_parts[5] else "-bf16")),
            "donated": bool(key_parts[6]),
            "geometry": geometry,
            "backend": platform,
            "device_kind": device_kind,
            "analysis": source,
            "cost": cost,
            "memory": memory,
            "argument_bytes_actual": arg_bytes,
            "windows": {"dispatches": 0, "device_secs": 0.0,
                        "min_secs": None, "max_secs": None,
                        "histories": 0},
        }
        with _LOCK:
            fresh = key not in _records
            if fresh:
                _records[key] = rec
        if fresh:
            trace.counter("cost_records").inc()
    except Exception:
        log.debug("costdb capture failed", exc_info=True)


def _modeled_bytes(rec: dict, args) -> int:
    """The modeled HBM footprint of one in-flight dispatch: its real
    argument bytes plus the executable's own temp/output claim when
    the memory analysis reported one."""
    n = sum(int(a.nbytes) for a in args)
    mem = rec.get("memory") or {}
    for k in ("temp_bytes", "output_bytes"):
        v = mem.get(k)
        if isinstance(v, int):
            n += v
    return n


def begin_dispatch(flags, kw: dict, shape, single_device: bool,
                   donate: bool, args, tr=None) -> None:
    """Open one dispatch's measured window: remember which record the
    flags array (the live device result) belongs to, add its modeled
    HBM to the in-flight gauge, and publish the residency gauges.
    No-op (one gates read) when the gate is off; never raises."""
    if not enabled():
        return
    try:
        global _inflight_bytes
        key = (dispatch_cost_key(kw, shape, single_device, donate),
               int(args[0].shape[0]))
        with _LOCK:
            rec = _records.get(key)
        nbytes = _modeled_bytes(rec or {}, args)
        with _LOCK:
            _pending[id(flags)] = (key, nbytes)
            _inflight_bytes += nbytes
        _publish_gauges(tr)
    except Exception:
        log.debug("costdb begin_dispatch failed", exc_info=True)


def close_dispatch(flags, t_disp, histories: int, tr=None) -> None:
    """Close one dispatch's window (enqueue time `t_disp` →
    now, the same semantics as the tracer's device track) and fold it
    into its record's aggregate. O(1) no-op for flags that were never
    begun (gate off, bare PendingVerdicts)."""
    global _inflight_bytes
    with _LOCK:
        ent = _pending.pop(id(flags), None)
        if ent is not None:
            _inflight_bytes = max(0, _inflight_bytes - ent[1])
    if ent is None or t_disp is None:
        return
    try:
        secs = max(0.0, time.perf_counter() - t_disp)
        key = ent[0]
        with _LOCK:
            rec = _records.get(key)
            if rec is not None:
                w = rec["windows"]
                w["dispatches"] += 1
                w["device_secs"] += secs
                w["min_secs"] = secs if w["min_secs"] is None \
                    else min(w["min_secs"], secs)
                w["max_secs"] = secs if w["max_secs"] is None \
                    else max(w["max_secs"], secs)
                w["histories"] += int(histories)
        _publish_gauges(tr)
    except Exception:
        log.debug("costdb close_dispatch failed", exc_info=True)


def discard_dispatch(flags, tr=None) -> None:
    """Drop a pending window without recording it — the dispatch's
    fate was quarantine/OOM recovery, whose device time the backdown's
    own windows account for."""
    global _inflight_bytes
    with _LOCK:
        ent = _pending.pop(id(flags), None)
        if ent is not None:
            _inflight_bytes = max(0, _inflight_bytes - ent[1])
    if ent is not None:
        _publish_gauges(tr)


def _publish_gauges(tr=None) -> None:
    """Residency gauges into the metrics registry (→ /metrics,
    health.json): delegated to parallel.residency so the residency
    layer owns its own publication surface."""
    try:
        from ..parallel import residency
        residency.publish_residency_gauges(
            tr if tr is not None else trace.get_current(),
            modeled_bytes=_inflight_bytes)
    except Exception:
        log.debug("residency gauge publish failed", exc_info=True)


def maybe_poll_memory_stats(tr) -> None:
    """The backend's own memory accounting (`device.memory_stats()` —
    TPU/GPU report `bytes_in_use`; CPU reports nothing) into the
    `hbm_device_bytes` gauge, at most once per
    JEPSEN_TPU_RESIDENCY_INTERVAL_S."""
    global _last_mem_poll
    interval = residency_interval_s()
    if interval <= 0:
        return
    now = time.monotonic()
    if now - _last_mem_poll < interval and _last_mem_poll > 0:
        return
    _last_mem_poll = now
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if isinstance(stats, dict) \
                and isinstance(stats.get("bytes_in_use"), int):
            tr.gauge("hbm_device_bytes").set(stats["bytes_in_use"])
    except Exception:
        log.debug("device memory_stats poll failed", exc_info=True)


# ---------------------------------------------------------------------------
# Records out: roofline derivation, flush, cross-shard merge.
# ---------------------------------------------------------------------------

def _finalize(rec: dict) -> dict:
    """One registry record → the published costdb line: achieved
    rates from the measured windows, roofline utilization against the
    device_kind-keyed peak table, and the honesty tag — `provenance:
    measured` only for windows taken on a real accelerator."""
    from ..checker.elle import kernels as K
    out = {k: v for k, v in rec.items() if k != "key_parts"}
    out = {"v": 1, **out}
    w = rec["windows"]
    peak = K.device_peak(rec.get("device_kind"))
    out["peak"] = peak
    measured = w["dispatches"] > 0 and rec.get("backend") \
        not in ("cpu", "unknown")
    out["provenance"] = "measured" if measured else "estimated"
    cost = rec.get("cost") or {}
    achieved = {"flops_per_sec": None, "bytes_per_sec": None}
    roofline = {"flops_utilization": None, "bandwidth_utilization": None}
    if w["dispatches"] > 0 and w["device_secs"] > 0:
        per_sec = w["dispatches"] / w["device_secs"]
        if isinstance(cost.get("flops"), (int, float)):
            achieved["flops_per_sec"] = cost["flops"] * per_sec
            peak_ops = (peak["int8_tops"] if "int8" in
                        (rec.get("formulation") or "")
                        else peak["bf16_tflops"]) * 1e12
            roofline["flops_utilization"] = round(
                achieved["flops_per_sec"] / peak_ops, 6)
        if isinstance(cost.get("bytes_accessed"), (int, float)):
            achieved["bytes_per_sec"] = cost["bytes_accessed"] * per_sec
            roofline["bandwidth_utilization"] = round(
                achieved["bytes_per_sec"] / (peak["hbm_gbps"] * 1e9), 6)
    out["achieved"] = achieved
    out["roofline"] = roofline
    return out


def records() -> list[dict]:
    """Every captured record, finalized (achieved rates, roofline,
    provenance), in capture order."""
    with _LOCK:
        raw = [dict(r, windows=dict(r["windows"])) for r in
               _records.values()]
    return [_finalize(r) for r in raw]


def record_key(rec: dict) -> tuple:
    """The dedup identity of a finalized record — what two shards
    compiling the same executable over the same geometry share."""
    g = rec.get("geometry") or {}
    k = rec.get("kernel") or {}
    return (tuple(sorted(k.items())), rec.get("formulation"),
            bool(rec.get("donated")),
            tuple(sorted((n, g.get(n)) for n in
                         ("B", "n_txns", "n_keys", "max_pos",
                          "n_appends", "n_reads"))),
            rec.get("analysis"))


def merge_records(record_lists) -> list[dict]:
    """Fold finalized records from several sources (mesh shards) into
    one deduplicated set: same key → one record with the window
    aggregates summed and the achieved/roofline numbers re-derived.
    A record whose twin carries a real memory analysis adopts it."""
    merged: dict[tuple, dict] = {}
    order: list[tuple] = []
    for recs in record_lists:
        for rec in recs or []:
            if not isinstance(rec, dict):
                continue
            k = record_key(rec)
            cur = merged.get(k)
            if cur is None:
                merged[k] = dict(rec,
                                 windows=dict(rec.get("windows") or {}))
                order.append(k)
                continue
            w, wn = cur.get("windows") or {}, rec.get("windows") or {}
            w["dispatches"] = w.get("dispatches", 0) \
                + wn.get("dispatches", 0)
            w["device_secs"] = w.get("device_secs", 0.0) \
                + wn.get("device_secs", 0.0)
            w["histories"] = w.get("histories", 0) \
                + wn.get("histories", 0)
            for f, pick in (("min_secs", min), ("max_secs", max)):
                vals = [v for v in (w.get(f), wn.get(f))
                        if v is not None]
                w[f] = pick(vals) if vals else None
            cur["windows"] = w
            if cur.get("memory") is None and rec.get("memory"):
                cur["memory"] = rec["memory"]
            if "measured" in (cur.get("provenance"),
                              rec.get("provenance")):
                cur["provenance"] = "measured"
    out = []
    for k in order:
        rec = merged[k]
        # re-derive the rates over the merged windows
        raw = {kk: vv for kk, vv in rec.items()
               if kk not in ("v", "peak", "provenance", "achieved",
                             "roofline")}
        fin = _finalize(raw)
        # a merged-measured set stays measured even if re-derivation
        # (cpu coordinator finalizing tpu shards) would demote it
        if rec.get("provenance") == "measured":
            fin["provenance"] = "measured"
        out.append(fin)
    return out


def flush(path, store_base=None) -> int:
    """Append every captured record to the costdb at `path` (one
    flushed JSON line each — store.append_costdb) and emit the
    flight-recorder mark. Returns the record count; 0 (and no file)
    when the gate is off or nothing was captured."""
    if not enabled():
        return 0
    recs = records()
    if not recs:
        return 0
    from ..store import append_costdb
    n = append_costdb(path, recs)
    if n:
        from . import events
        events.emit("costdb_flush", path=str(path), records=n)
    return n


def bandwidth_share(recs: list[dict]) -> dict | None:
    """The sweep-level achieved-bandwidth share: total bytes accessed
    over total measured device seconds, against the peak HBM bandwidth
    the records resolved — the single number bench-report trends.
    None when no record carries both a cost analysis and windows."""
    bytes_total = 0.0
    secs_total = 0.0
    flops_total = 0.0
    peak_bw = None
    provenance = "estimated"
    for r in recs or []:
        w = r.get("windows") or {}
        cost = r.get("cost") or {}
        if not w.get("dispatches") or not isinstance(
                cost.get("bytes_accessed"), (int, float)):
            continue
        bytes_total += cost["bytes_accessed"] * w["dispatches"]
        if isinstance(cost.get("flops"), (int, float)):
            flops_total += cost["flops"] * w["dispatches"]
        secs_total += w.get("device_secs", 0.0)
        peak_bw = (r.get("peak") or {}).get("hbm_gbps", peak_bw)
        if r.get("provenance") == "measured":
            provenance = "measured"
    if secs_total <= 0 or peak_bw is None:
        return None
    return {
        "achieved_bw_share": round(
            bytes_total / secs_total / (peak_bw * 1e9), 6),
        "achieved_gbps": round(bytes_total / secs_total / 1e9, 3),
        "achieved_tflops": round(flops_total / secs_total / 1e12, 4),
        "device_secs": round(secs_total, 6),
        "peak_hbm_gbps": peak_bw,
        "provenance": provenance,
    }
