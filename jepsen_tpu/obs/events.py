"""The flight-recorder event log: `<store>/events.jsonl`.

trace.json is written at EXIT — a sweep that dies by SIGKILL leaves no
causal record of what it was doing or why runs went unknown. The
flight recorder closes that gap the way VerdictJournal does for
verdicts: every discrete lifecycle event appends one JSON line,
written and flushed as it happens, so the on-disk record is always as
current as the last event.

Events are TYPED: `emit(kind, **fields)` refuses a kind that is not
declared in `EVENT_KINDS` (the same discipline as the gates registry —
a typo must fail loudly, not fork an event stream), and lint rule
JT-TRACE-003 enforces at the AST level that no module outside this one
writes the events file or emits an undeclared kind.

Concurrency/crash posture: each emit is one `open(append) → write one
line → close`; the line is a single short `write()` on an O_APPEND
descriptor, so concurrent emitters (the sampler thread, the sweep
thread) interleave at line granularity and a crash tears at most the
line in flight — `load_events` skips unparseable lines, like the
journal's truncated-tail rule. Pool worker processes never install a
log, so their `emit` calls are no-ops by construction. Retention is
the registry-declared `rotated` class: with
`JEPSEN_TPU_EVENTS_MAX_BYTES` set, a log over the cap is renamed
aside to `events.jsonl.1` (atomic `os.replace`) and the fresh log
opens with an `events_rotated` event naming it.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from pathlib import Path

log = logging.getLogger(__name__)

EVENTS_NAME = "events.jsonl"

#: The declared event kinds. An undeclared kind raises ValueError at
#: emit time (and JT-TRACE-003 at lint time) — the event stream is an
#: API surface, not a scratch pad.
EVENT_KINDS = frozenset({
    "sweep_start",        # checker, runs, resume
    "sweep_resume",       # skipped (already-journaled runs)
    "sweep_end",          # exit_code
    "quarantine",         # stage, cause, histories|run
    "oom_split",          # histories (bucket size being halved)
    "watchdog_fire",      # timeout_s, attempt
    "journal_seal",       # path (crash-torn journal tail sealed)
    "cache_rebuild",      # path (corrupt/stale sidecar discarded)
    "health_sample",      # seq (periodic heartbeat mark, first+last)
    "metrics_serve",      # port (endpoint came up)
    "shard_done",         # shard, exit_code (mesh shard completed)
    "shard_lost",         # shard, shards (no done marker at merge —
    #                       re-assignable via JEPSEN_TPU_MESH_SHARD)
    "costdb_flush",       # path, records (device cost observatory
    #                       appended its per-executable records)
    "analytics_flush",    # path, records (kernel search telemetry
    #                       appended its per-history stats lines)
    "events_rotated",     # rotated_to, size (the log hit
    #                       JEPSEN_TPU_EVENTS_MAX_BYTES and was
    #                       renamed aside; first line of the new log)
    # -- the serve_* group: the verdict daemon's lifecycle ---------------
    "serve_start",        # socket|port, store (daemon accepting)
    "serve_tenant_connect",   # tenant, weight, journaled (replayable)
    "serve_admit",        # histories, tenants (one continuous-batch
    #                       fold formed from the admission queues)
    "serve_backpressure",  # tenant, depth (explicit retry-after frame
    #                       sent — a full queue never drops silently)
    "serve_drain",        # pending, reason (SIGTERM/stop: admission
    #                       closed, queued work finishing)
    "serve_stop",         # verdicts, drained (daemon exit)
    # -- the fleet_* group: the serve-fleet router's lifecycle -----------
    "fleet_start",        # daemons, socket, epoch (router accepting)
    "fleet_daemon_up",    # instance, pid (beacon observed live)
    "fleet_daemon_dead",  # instance, cause (beacon stale / conn
    #                       refused / process exit — fenced next)
    "fleet_failover",     # instance, successor, tenants, epoch (dead
    #                       daemon's tenants reassigned; journals
    #                       replay on the successor)
    "fleet_spill",        # tenant, affine, chosen, depth (backpressure
    #                       routed a check off its affine daemon)
    "fleet_fence",        # instance, epoch (a fenced daemon observed
    #                       its own death mark and dropped a fold
    #                       instead of double-serving — zombie fence)
    "fleet_stop",         # verdicts, daemons (router exit)
})

_lock = threading.Lock()
_path: Path | None = None


def install_events(store_base) -> Path | None:
    """Point the flight recorder at `<store_base>/events.jsonl` (the
    only place the file name exists — JT-TRACE-003 flags the literal
    anywhere else). Best-effort: an uncreatable directory leaves the
    recorder uninstalled rather than sinking the sweep."""
    global _path
    base = Path(store_base)
    if not base.is_dir():
        # a sweep of a nonexistent store is a usage error (exit 254);
        # the recorder must not fabricate the directory for it
        _path = None
        return None
    _path = base / EVENTS_NAME
    return _path


def reset_events() -> None:
    """Uninstall the recorder (emit becomes a no-op)."""
    global _path
    _path = None


def current_path() -> Path | None:
    return _path


def _max_bytes() -> int | None:
    """The JEPSEN_TPU_EVENTS_MAX_BYTES rotation cap (unset/<=0 = off,
    the default) — the registry-declared `rotated` retention class of
    the flight recorder, made real."""
    from .. import gates
    v = gates.get("JEPSEN_TPU_EVENTS_MAX_BYTES")
    return v if v is not None and v > 0 else None


#: A crashed rotator's lockfile is broken after this many seconds —
#: rotation pauses (the log grows past the cap), it never loses data.
_ROTLOCK_STALE_S = 60.0


def _maybe_rotate(p: Path) -> str | None:
    """Rotate the log aside (atomic rename to `<name>.1`) when it
    exceeds the cap; returns the `events_rotated` line to open the
    fresh log with, or None. `_lock` serializes threads; PROCESSES
    (mesh shards share one store log) are serialized by an
    O_CREAT|O_EXCL lockfile, and the size is re-stat'ed after the
    claim — a stale pre-claim stat from a racing emitter can't
    rename the freshly-rotated log over the generation it just kept.
    Losing the claim (or any OSError) skips rotation for this emit:
    the next emit retries, nothing is lost."""
    cap = _max_bytes()
    if cap is None:
        return None
    try:
        if p.stat().st_size < cap:
            return None
    except OSError:
        return None
    lock = p.with_name(p.name + ".rotlock")
    try:
        fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        # another process holds the rotation; break only a stale
        # lock (its holder crashed mid-rotation) and retry NEXT
        # emit. The break is rename-then-verify, never unlink-by-
        # path: between our staleness stat and the unlink a live
        # claimant could have replaced the stale lock, and deleting
        # ITS claim would let two rotators run at once. os.rename is
        # atomic (exactly one breaker gets the inode), and the
        # renamed file's mtime proves which lock we actually took —
        # a live claim is renamed straight back.
        try:
            if time.time() - lock.stat().st_mtime <= _ROTLOCK_STALE_S:
                return None
            breaking = lock.with_name(f"{lock.name}.{os.getpid()}")
            os.rename(lock, breaking)
            if time.time() - breaking.stat().st_mtime \
                    > _ROTLOCK_STALE_S:
                os.unlink(breaking)        # broke the crashed holder
            else:
                os.rename(breaking, lock)  # stole a live claim: undo
        except OSError:
            pass
        return None
    except OSError:
        return None
    try:
        os.close(fd)
        # re-stat under the lock: the crossing this emit observed may
        # already have been rotated by the previous lock holder
        try:
            size = p.stat().st_size
        except OSError:
            return None
        if size < cap:
            return None
        rotated = p.with_name(p.name + ".1")
        try:
            os.replace(p, rotated)
        except OSError:
            log.debug("events rotation failed for %s", p,
                      exc_info=True)
            return None
        return json.dumps({"event": "events_rotated",
                           "t_mono": round(time.monotonic(), 6),
                           "t_wall": round(time.time(), 6),
                           "pid": os.getpid(),
                           "rotated_to": rotated.name,
                           "size": size}) + "\n"
    finally:
        try:
            os.unlink(lock)
        except OSError:
            pass


def emit(kind: str, **fields) -> bool:
    """Append one typed event; returns True when a line was written.
    No-op (False) when no log is installed — callers never guard.
    Undeclared kinds raise: that is a bug in the caller, caught by
    lint and tests long before production."""
    if kind not in EVENT_KINDS:
        raise ValueError(f"undeclared obs event kind {kind!r} "
                         "(declare it in obs.events.EVENT_KINDS)")
    p = _path
    if p is None:
        return False
    rec = {"event": kind,
           "t_mono": round(time.monotonic(), 6),
           "t_wall": round(time.time(), 6),
           "pid": os.getpid(), **fields}
    try:
        line = json.dumps(rec) + "\n"
    except (TypeError, ValueError):
        log.debug("unserializable obs event %r dropped", kind,
                  exc_info=True)
        return False
    try:
        with _lock:
            rot = _maybe_rotate(p)
            if rot is not None:
                # the rotation mark and the record open the fresh log
                # as ONE write — a crash between two writes would
                # leave a log whose first record isn't the rotation
                line = rot + line
            with open(p, "a") as f:
                f.write(line)
                f.flush()
        return True
    except OSError:
        # a read-only store mount must not sink the sweep
        log.debug("obs event append failed for %s", p, exc_info=True)
        return False


def load_events(path) -> list[dict]:
    """Events from an existing log, in file order; unparseable lines
    (the crash-torn tail) are skipped, mirroring VerdictJournal.load."""
    out: list[dict] = []
    p = Path(path)
    if p.is_dir():
        p = p / EVENTS_NAME
    if not p.is_file():
        return out
    try:
        lines = p.read_text().splitlines()
    except OSError:
        return out
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            e = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(e, dict) and "event" in e:
            out.append(e)
    return out
