"""Kernel search telemetry: the per-store analytics ledger and its
report aggregation (`JEPSEN_TPU_KERNEL_STATS`).

Every observability layer before this one instruments the HOST around
an opaque device span: the checker kernels returned verdict booleans
and nothing about the search they ran, so the cost-aware router
(ROADMAP item 4 — whose complexity bounds are stated per graph/history
shape) and the adversarial near-miss generator (item 3) had no
per-history signals to train or seed on. Behind the gate, the kernels
now return a compact stats vector per history (elle:
`kernels.STAT_FIELDS` — edge counts per relation, closure rounds vs
bound, SCC shape, the decision-boundary margin; knossos: WGL
frontier/backtrack counters), and this module is where those rows
land host-side:

  * `record()` accumulates one record per checked history for the
    current sweep (the cost-observatory discipline: per-sweep module
    state, `reset()` at sweep start) and publishes the `kernel.*`
    metrics, so `/metrics` and metrics.json carry live aggregates;
  * `flush()` journals the records to `<store>/analytics.jsonl`
    (`store.append_analytics`: one flushed JSON line each, torn tails
    skipped on load — the VerdictJournal discipline, declared in the
    JT-DUR registry; mesh shards write `analytics-shard<k>.jsonl`,
    merged by the coordinator). `JEPSEN_TPU_KERNEL_STATS_SAMPLE=N`
    journals every Nth record; the in-memory aggregates and the
    report section still cover all of them;
  * `search_section()` aggregates the records into the report's
    "search" section — anomaly rate, closure-round and margin
    distributions, edge density, and the edge-density-vs-device-time
    join against costdb records (the empirical complexity model the
    planner trains on).

Everything is best-effort and gate-off-free: with the gate off no
record is ever created, no file written, and the only cost is the
caller's one `enabled()` read per sweep. Verdicts are byte-identical
either way — stats ride BESIDE results, never inside them.
"""

from __future__ import annotations

import logging
import threading

from .. import gates, trace

log = logging.getLogger(__name__)

#: A cyclic history whose cycle only appeared after this many squaring
#: rounds is flagged `near_miss`: its cycle needs long dependency
#: paths, so few mutations separate it from a valid history — the
#: seed population for ROADMAP item 3's mutation search. (Valid
#: histories order by `margin` directly; the flag marks the anomalous
#: side, where high margin means fragile.)
NEAR_MISS_MARGIN = 2

_LOCK = threading.Lock()
_records: list[dict] = []


def enabled() -> bool:
    """The JEPSEN_TPU_KERNEL_STATS gate (default off)."""
    return gates.get("JEPSEN_TPU_KERNEL_STATS")


def sample_every() -> int:
    """The JEPSEN_TPU_KERNEL_STATS_SAMPLE journaling stride (>=1)."""
    v = gates.get("JEPSEN_TPU_KERNEL_STATS_SAMPLE")
    return max(1, int(v) if v else 1)


def reset() -> None:
    """Drop every accumulated record (sweep start, tests) — per-sweep
    state like the tracer and the cost observatory."""
    with _LOCK:
        _records.clear()


def note_metrics(stats: dict, tr=None) -> None:
    """Publish one stats row's `kernel.*` metrics WITHOUT accumulating
    a ledger record — the long-lived serve daemon's path (accumulating
    per-verdict records forever would be an unbounded-memory bug, the
    exact class the retention registry exists to prevent)."""
    try:
        tr = tr if tr is not None else trace.get_current()
        tr.counter("kernel.stats_records").inc()
        if stats.get("cycle_txns", 0) > 0:
            tr.counter("kernel.cyclic_histories").inc()
        rounds = stats.get("closure_rounds", -1)
        if isinstance(rounds, int) and rounds >= 0:
            tr.histogram("kernel.closure_rounds").observe(rounds)
        margin = stats.get("margin", -1)
        if isinstance(margin, int) and margin >= 0:
            tr.histogram("kernel.margin").observe(margin)
        if any(k in stats for k in ("ww_edges", "wr_edges",
                                    "rw_edges")):
            # guard like the other observes: a register/WGL record
            # carries no edge counts, and observing a fabricated 0
            # would pollute the distribution
            tr.histogram("kernel.edges").observe(
                sum(stats.get(k, 0) or 0 for k in
                    ("ww_edges", "wr_edges", "rw_edges")))
        if stats.get("scc_max"):
            tr.histogram("kernel.scc_max").observe(stats["scc_max"])
        if isinstance(stats.get("backtracks"), int):
            tr.histogram("kernel.backtracks").observe(
                stats["backtracks"])
    except Exception:   # observability never sinks a sweep
        log.debug("kernel-stats metrics publish failed", exc_info=True)


def record(run, checker: str, stats: dict | None,
           anomalies=None) -> None:
    """Accumulate one history's stats record for the current sweep and
    publish its metrics. `stats` None (a quarantined or stats-free
    history) is a no-op — the ledger only carries real telemetry.
    `anomalies` (the cycle dict / anomaly-name iterable the verdict
    rendered from) rides along so the ledger line pairs structure with
    outcome without re-reading results.json."""
    if stats is None:
        return
    try:
        rec = {"v": 1, "dir": str(run), "checker": str(checker),
               **stats}
        if anomalies:
            try:
                rec["anomalies"] = sorted(str(a) for a in anomalies)
            except TypeError:
                pass
        margin = rec.get("margin", -1)
        if rec.get("cycle_txns", 0) and isinstance(margin, int) \
                and margin >= NEAR_MISS_MARGIN:
            rec["near_miss"] = True
        with _LOCK:
            _records.append(rec)
        note_metrics(rec)
    except Exception:
        log.debug("kernel-stats record failed", exc_info=True)


def records() -> list[dict]:
    """Every accumulated record, in record order."""
    with _LOCK:
        return [dict(r) for r in _records]


def flush(path) -> int:
    """Journal the accumulated records to the analytics ledger at
    `path` (every `sample_every()`-th record; store.append_analytics —
    one flushed line each) and emit the flight-recorder mark. Returns
    the line count; 0 (and no file) when the gate is off or nothing
    was recorded."""
    if not enabled():
        return 0
    recs = records()
    if not recs:
        return 0
    k = sample_every()
    recs = recs[::k]
    from ..store import append_analytics
    n = append_analytics(path, recs)
    if n:
        from . import events
        events.emit("analytics_flush", path=str(path), records=n)
    return n


# ---------------------------------------------------------------------------
# Report aggregation — the "search" section.
# ---------------------------------------------------------------------------

def _dist(vals: list) -> dict | None:
    """min/mean/max + a small histogram over non-negative ints."""
    vals = [v for v in vals if isinstance(v, (int, float)) and v >= 0]
    if not vals:
        return None
    hist: dict[str, int] = {}
    for v in vals:
        hist[str(int(v))] = hist.get(str(int(v)), 0) + 1
    return {"count": len(vals), "min": min(vals), "max": max(vals),
            "mean": round(sum(vals) / len(vals), 4),
            "histogram": dict(sorted(hist.items(),
                                     key=lambda kv: int(kv[0])))}


def search_section(recs: list[dict],
                   cost_records: list[dict] | None = None
                   ) -> dict | None:
    """The report's "search" section: anomaly-rate and margin/round
    distributions over the ledger, edge density, and the per-geometry
    edge-density-vs-device-time join against the costdb (device
    seconds per history at each bucket pad — the empirical complexity
    table the cost-aware planner trains on). Register-sweep records
    (WGL counters, no graph margin) aggregate into their own
    `register` subsection so a register-only sweep still reports.
    None when no record exists at all (gate off)."""
    all_recs = [r for r in recs or [] if isinstance(r, dict)]
    recs = [r for r in all_recs if "margin" in r]
    reg = [r for r in all_recs if "margin" not in r]
    if not recs:
        if not reg:
            return None
        return {"histories": len(reg),
                "register": _register_section(reg)}
    cyclic = [r for r in recs if r.get("cycle_txns", 0)]
    valid = [r for r in recs if not r.get("cycle_txns", 0)]
    edges = [sum(r.get(k, 0) or 0 for k in
                 ("ww_edges", "wr_edges", "rw_edges", "rt_edges",
                  "proc_edges")) for r in recs]
    density = [e / max(r.get("n_txns", 1) or 1, 1)
               for e, r in zip(edges, recs)]
    sec = {
        "histories": len(recs),
        "anomalous": len(cyclic),
        "anomaly_rate": round(len(cyclic) / len(recs), 4),
        "near_miss": sum(1 for r in recs if r.get("near_miss")),
        "closure_rounds": _dist([r.get("closure_rounds", -1)
                                 for r in recs]),
        "margin": {
            "anomalous": _dist([r.get("margin", -1) for r in cyclic]),
            "valid": _dist([r.get("margin", -1) for r in valid]),
        },
        "edges_per_txn_mean": (round(sum(density) / len(density), 4)
                               if density else None),
        "scc_max": max((r.get("scc_max", 0) or 0 for r in recs),
                       default=0),
    }
    # the empirical complexity join: group ledger rows by bucket pad
    # and attach the costdb's measured device seconds per history at
    # the same geometry — edge density vs device time, per T_pad
    by_pad: dict[int, dict] = {}
    for r, e in zip(recs, edges):
        t = r.get("t_pad")
        if not isinstance(t, int):
            continue
        g = by_pad.setdefault(t, {"histories": 0, "edges": 0,
                                  "rounds": [], "device_secs": None,
                                  "cost_histories": 0})
        g["histories"] += 1
        g["edges"] += e
        rd = r.get("closure_rounds", -1)
        if isinstance(rd, int) and rd >= 0:
            g["rounds"].append(rd)
    for c in cost_records or []:
        if not isinstance(c, dict):
            continue
        t = (c.get("geometry") or {}).get("n_txns")
        w = c.get("windows") or {}
        if t in by_pad and w.get("histories"):
            g = by_pad[t]
            g["device_secs"] = (g["device_secs"] or 0.0) \
                + w.get("device_secs", 0.0)
            g["cost_histories"] += w["histories"]
    rows = []
    for t in sorted(by_pad):
        g = by_pad[t]
        secs_per = (g["device_secs"] / g["cost_histories"]
                    if g["device_secs"] and g["cost_histories"]
                    else None)
        rows.append({
            "t_pad": t, "histories": g["histories"],
            "edges_mean": round(g["edges"] / g["histories"], 2),
            "rounds_mean": (round(sum(g["rounds"]) / len(g["rounds"]),
                                  2) if g["rounds"] else None),
            "device_secs_per_history": (round(secs_per, 6)
                                        if secs_per else None)})
    sec["by_geometry"] = rows
    if reg:
        sec["histories"] = len(all_recs)
        sec["register"] = _register_section(reg)
    return sec


def _register_section(reg: list[dict]) -> dict:
    """Register-sweep aggregate: per-run WGL counters summed/maxed
    (the per-run records already aggregated their keys)."""
    out: dict = {"runs": len(reg),
                 "keys": sum(r.get("keys", 0) or 0 for r in reg)}
    for f, agg in (("configs", sum), ("backtracks", sum),
                   ("rounds", sum), ("frontier_peak", max),
                   ("max_depth", max)):
        vals = [r[f] for r in reg if isinstance(r.get(f), int)]
        if vals:
            out[f] = agg(vals)
    return out


def render_search_md(sec: dict) -> list[str]:
    """The report.md "Search" section for one aggregate."""
    lines = ["", "## Search telemetry (kernel stats)", ""]
    if "anomaly_rate" in sec:
        lines.append(
            f"{sec.get('histories', 0)} histories with kernel stats; "
            f"anomaly rate **{sec.get('anomaly_rate', 0):.2%}** "
            f"({sec.get('anomalous', 0)} anomalous, "
            f"{sec.get('near_miss', 0)} near-miss), largest SCC "
            f"{sec.get('scc_max', 0)} txns, "
            f"{sec.get('edges_per_txn_mean')} edges/txn mean.")
    else:
        lines.append(f"{sec.get('histories', 0)} histories with "
                     "kernel stats.")
    rg = sec.get("register") or {}
    if rg:
        lines.append(
            f"Register sweeps: {rg.get('runs', 0)} run(s), "
            f"{rg.get('keys', 0)} key subhistories, "
            f"{rg.get('configs', 0)} WGL configs explored, "
            f"{rg.get('backtracks', 0)} backtracks.")
    cr = sec.get("closure_rounds") or {}
    if cr:
        lines.append(f"Closure rounds: mean {cr.get('mean')} "
                     f"(min {cr.get('min')}, max {cr.get('max')}).")
    m = sec.get("margin") or {}
    for side in ("anomalous", "valid"):
        d = m.get(side)
        if d:
            lines.append(f"Margin ({side}): mean {d.get('mean')}, "
                         f"histogram {d.get('histogram')}.")
    rows = sec.get("by_geometry") or []
    if rows:
        lines += ["", "| T_pad | histories | edges mean | rounds mean "
                  "| device s/history |", "|---|---|---|---|---|"]
        for r in rows:
            def num(v):
                return f"{v:g}" if isinstance(v, (int, float)) else "—"
            lines.append(
                f"| {r['t_pad']} | {r['histories']} | "
                f"{num(r['edges_mean'])} | {num(r['rounds_mean'])} | "
                f"{num(r['device_secs_per_history'])} |")
    return lines
