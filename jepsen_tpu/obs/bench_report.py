"""The bench-trajectory regression gate: `jepsen-tpu bench-report`.

The repo ships one `BENCH_rNN.json` artifact per growth round, but
nothing READ them: a regression in the north-star sweep, the warm
ingest, or dp8 efficiency would only be noticed by a human diffing
JSON. This module loads the whole series, prints a per-metric trend
table, and exits non-zero when the latest round regresses past a
declared threshold — `make bench-report` makes the trajectory police
itself.

Comparability rules (the series is heterogeneous by design):

  * An artifact is either the driver wrapper ({"parsed": {...}}) or a
    raw bench line; both load. A round whose bench died (no parseable
    JSON) stays in the table as a dash column.
  * A metric value only counts when it is a real number AND no dict on
    its path carries an "error" key — a 0.0 that rode an outage is an
    outage, not a measurement.
  * Rounds are grouped by the artifact's "backend" field: a CPU
    number is not comparable to a TPU number, so the gate compares the
    LATEST present value of each metric against its most recent
    same-backend predecessor only.

Each metric declares its direction and a relative tolerance; `lint
open findings` is absolute-zero-tolerance (any increase regresses).
Exit codes: 0 clean, 1 regression(s), 254 nothing to report.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path


@dataclass(frozen=True)
class MetricSpec:
    key: str            # table row id
    label: str          # human label
    path: tuple         # path into the parsed bench dict
    higher_is_better: bool
    tolerance: float    # allowed relative slack before "regressed"
    # absolute ceiling: the latest value exceeding it regresses even
    # with no predecessor to compare against — "a stage silently
    # regrowing past a declared share fails the gate" for
    # lower-is-better metrics, and a sanity bound for ratios that
    # cannot legitimately exceed it (an achieved-bandwidth share past
    # ~1 means the byte model is wrong, not that the chip got faster)
    ceiling: float | None = None
    # absolute floor: the latest value falling below it regresses even
    # with no predecessor — the mesh scaling-efficiency contract
    # ("2 shards must buy ≥1.4x") holds from the first round that
    # reports it. Also usable WITH a ceiling to pin a deterministic
    # value from both sides (the seeded search anomaly rate: a
    # collapse to 0 must not read as an improvement).
    floor: float | None = None


#: The declared trajectory metrics and their regression thresholds.
#: Tolerances are deliberately loose for wall-clock-noisy rates (CI
#: boxes jitter) and tight for ratios the repo pins elsewhere.
METRICS: tuple[MetricSpec, ...] = (
    MetricSpec("elle_rate", "elle-append hist/s", ("value",),
               True, 0.30),
    MetricSpec("ns_rate", "north-star hist/s",
               ("north_star", "value"), True, 0.30),
    MetricSpec("ns_sweep_secs", "north-star sweep secs",
               ("north_star", "sweep_secs"), False, 0.30),
    MetricSpec("warm_ingest_x", "warm-ingest speedup",
               ("north_star", "cache_warm", "ingest_speedup_vs_cold"),
               True, 0.30),
    # the warm-path zero-copy contract: bytes the warm sweep copied on
    # the host for cache-loaded histories (target 0 — tolerance 0.0
    # means ANY growth over the predecessor regresses) and the warm
    # sweep's executable-cache hit rate (target 1.0; a 10% dip means
    # shapes started recompiling)
    MetricSpec("warm_copy_b", "warm-copy bytes",
               ("north_star", "cache_warm", "warm_copy_bytes"),
               False, 0.0),
    MetricSpec("compile_hit_rate", "warm compile-cache hit rate",
               ("north_star", "cache_warm", "compile_cache_hit_rate"),
               True, 0.10),
    MetricSpec("dp8_eff", "dp8 efficiency",
               ("dp_scaling", "dp8_efficiency"), True, 0.15),
    MetricSpec("mfu", "north-star MFU",
               ("north_star", "mfu_measured"), True, 0.20),
    MetricSpec("lint_open", "lint open findings",
               ("lint", "findings_open"), False, 0.0),
    # the analyzer's own wall time: the self-hosting gate runs every
    # commit, so the engine growing (CFG/dataflow/ABI passes) must not
    # quietly turn `make lint` into minutes — loose tolerance, CI
    # boxes jitter, but a blowup past 2x the predecessor regresses
    MetricSpec("lint_wall", "lint wall secs",
               ("lint", "wall_secs"), False, 1.0),
    # the critical-path decomposition (obs.attribution, embedded in
    # the north_star block since the trace fabric): the device share
    # should grow or hold as overlap improves, and the two host-stall
    # shares must not silently regrow — each also carries an absolute
    # ceiling, so a stage creeping past its declared share fails the
    # gate even on the first round that reports it
    MetricSpec("ns_device_share", "north-star device share",
               ("north_star", "attribution", "shares", "device"),
               True, 0.30),
    MetricSpec("ns_parse_share", "north-star parse-stall share",
               ("north_star", "attribution", "shares", "parse"),
               False, 0.25, ceiling=0.95),
    MetricSpec("warm_idle_share", "warm-sweep idle share",
               ("north_star", "cache_warm", "attribution", "shares",
                "idle"), False, 0.30, ceiling=0.90),
    # the multi-host mesh block: store->verdict throughput of the
    # 2-shard simulated mesh, and its scaling efficiency vs the
    # single-process sweep of the same store — the dp8-style gate for
    # scale-OUT. The 0.70 floor is the declared contract: 2 shards
    # must buy ≥1.4x, first round included.
    MetricSpec("mesh_rate", "mesh sweep hist/s", ("mesh", "value"),
               True, 0.30),
    MetricSpec("mesh_eff", "mesh 2-shard scaling efficiency",
               ("mesh", "scaling_efficiency"), True, 0.15, floor=0.70),
    # the verdict service under the open-loop two-tenant load
    # generator: sustained streamed-verdict throughput, and the p99
    # end-to-end verdict latency the daemon is contractually required
    # to bound — the 30 s ceiling is the declared threshold (at ~70%
    # of probed capacity a p99 past it means queueing broke, whatever
    # the predecessor did), and the 0.50 tolerance absorbs CI jitter
    # between rounds
    MetricSpec("serve_rate", "serve streamed verdicts/sec",
               ("serve", "value"), True, 0.30),
    MetricSpec("serve_p99_ms", "serve p99 verdict latency (ms)",
               ("serve", "p99_ms"), False, 0.50, ceiling=30_000.0),
    # the serve fleet: N-daemon burst throughput (rate vs daemon
    # count; the scale-OUT counterpart of serve_rate) and the
    # post-SIGKILL recovery latency — the bounded-failover contract
    # trended per round. The 30 s ceiling is the declared bound: a
    # failover that stalls a tenant past it broke the contract no
    # matter what the predecessor round did.
    MetricSpec("fleet_rate", "fleet N-daemon verdicts/sec",
               ("fleet", "value"), True, 0.30),
    MetricSpec("fleet_recovery_ms", "fleet post-SIGKILL recovery (ms)",
               ("fleet", "recovery_ms"), False, 1.0, ceiling=30_000.0),
    # the device cost observatory's roofline number: XLA-modeled bytes
    # accessed over measured device seconds, as a share of the
    # peak-table HBM bandwidth. Estimated-provenance rounds (CPU-only
    # boxes) carry "error" in the device_cost block, so they read as
    # outages here — the PR-6 convention — never as zeros. The 1.05
    # ceiling is a sanity bound: a share past ~1 means the byte model
    # or the window join broke, which must fail the gate, not read as
    # a speedup.
    MetricSpec("ns_bw_share", "north-star achieved-bandwidth share",
               ("north_star", "device_cost", "achieved_bw_share"),
               True, 0.30, ceiling=1.05),
    # the kernel search-telemetry block: the seeded anomaly rate is
    # DETERMINISTIC (every 4th synthetic history carries a G1c), so
    # the gate pins BOTH directions from the first reporting round —
    # ceiling 0.30 catches false positives, floor 0.20 catches the
    # kernels going blind (a collapse to 0 must not read as an
    # improvement; the seeded truth is 0.25). verdict parity is a
    # hard floor-1.0 contract: stats changing a single verdict fails
    # the round outright. The stats dispatch's wall overhead vs the
    # stats-free kernel is bounded too: telemetry creeping past ~2x
    # the plain closure would defeat the always-on ambition.
    MetricSpec("search_anomaly_rate", "search seeded anomaly rate",
               ("search", "anomaly_rate"), False, 0.0,
               ceiling=0.30, floor=0.20),
    MetricSpec("search_parity", "search verdict parity",
               ("search", "parity_ok"), True, 0.0, floor=1.0),
    MetricSpec("search_overhead_x", "kernel-stats overhead (x)",
               ("search", "stats_overhead_x"), False, 0.50,
               ceiling=3.0),
    # the cost-aware planner block: planner_speedup is planner-on
    # wall over the BEST fixed geometry's wall on a mixed workload —
    # the tentpole claim is >= ~1.0 (the modeled router never loses
    # to a fixed config it could have picked). The 0.85 floor sits
    # under the CI noise band so only a real routing regression (the
    # model steering into a slower geometry) fails the round; the
    # parity pin is the absolute contract — one placement decision
    # changing one verdict fails outright.
    MetricSpec("planner_speedup", "planner vs best fixed config (x)",
               ("planner", "planner_speedup"), True, 0.15,
               floor=0.85),
    MetricSpec("planner_parity", "planner verdict parity",
               ("planner", "parity_ok"), True, 0.0, floor=1.0),
)


def load_round(path) -> dict | None:
    """The parsed bench dict of one artifact, or None when the round
    recorded no parseable bench output (an outage round)."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict):
        return None
    parsed = data.get("parsed", data) if "parsed" in data else data
    return parsed if isinstance(parsed, dict) else None


def metric_value(parsed: dict | None, spec: MetricSpec):
    """The metric's numeric value, or None when absent or tainted: any
    dict on the path carrying "error" voids the reading (a bench block
    that crashed reports value 0.0 — an outage, not a measurement)."""
    d = parsed
    for k in spec.path:
        if not isinstance(d, dict) or d.get("error"):
            return None
        d = d.get(k)
    if isinstance(d, bool) or not isinstance(d, (int, float)):
        return None
    return float(d)


def _regressed(spec: MetricSpec, prev: float, last: float) -> bool:
    if spec.higher_is_better:
        return last < prev * (1.0 - spec.tolerance)
    if prev == 0:
        return last > spec.tolerance
    return last > prev * (1.0 + spec.tolerance)


def default_artifacts(root) -> list[Path]:
    return sorted(Path(root).glob("BENCH_*.json"))


def report(paths, out=print) -> int:
    """Load the series, print the trend table, gate the latest round.
    Returns the exit code."""
    paths = [Path(p) for p in paths]
    if not paths:
        out("bench-report: no BENCH_*.json artifacts found")
        return 254
    rounds = []     # (name, backend, parsed|None)
    for p in paths:
        parsed = load_round(p)
        backend = parsed.get("backend") if isinstance(parsed, dict) \
            else None
        name = p.stem.replace("BENCH_", "")
        rounds.append((name, backend or "?", parsed))

    name_w = max(len("metric"), *(len(s.label) for s in METRICS))
    col_w = max(9, *(len(n) for n, _b, _p in rounds))
    header = " | ".join([f"{'metric':<{name_w}}"]
                        + [f"{n:>{col_w}}" for n, _b, _p in rounds])
    out(header)
    out(" | ".join([f"{'backend':<{name_w}}"]
                   + [f"{b:>{col_w}}" for _n, b, _p in rounds]))
    out("-" * len(header))

    regressions: list[str] = []
    for spec in METRICS:
        cells = []
        series = []     # (round name, backend, value) — present only
        for name, backend, parsed in rounds:
            v = metric_value(parsed, spec)
            if v is None:
                cells.append("—")
            else:
                series.append((name, backend, v))
                cells.append(f"{v:g}")
        # gate each backend group's LAST transition: a cpu regression
        # must not hide behind a trailing hardware round, and cpu/tpu
        # numbers are never compared to each other
        groups: dict[str, list[tuple[str, float]]] = {}
        for name, backend, v in series:
            groups.setdefault(backend, []).append((name, v))
        notes = []
        for backend, vals in groups.items():
            # the absolute ceiling applies to each group's LATEST
            # value, predecessor or not — a newly-reported share
            # already past its declared bound must not ride in free
            if spec.ceiling is not None and vals:
                c_name, c_last = vals[-1]
                if c_last > spec.ceiling:
                    notes.append(f"[{backend} {c_last:g} > ceiling "
                                 f"{spec.ceiling:g}] REGRESSED")
                    regressions.append(
                        f"{spec.label} ({backend}): {c_last:g} "
                        f"({c_name}) exceeds the declared ceiling "
                        f"{spec.ceiling:g}")
            # the floor is the ceiling's higher-is-better twin: a
            # newly-reported efficiency already below its declared
            # bound must not ride in free either
            if spec.floor is not None and vals:
                f_name, f_last = vals[-1]
                if f_last < spec.floor:
                    notes.append(f"[{backend} {f_last:g} < floor "
                                 f"{spec.floor:g}] REGRESSED")
                    regressions.append(
                        f"{spec.label} ({backend}): {f_last:g} "
                        f"({f_name}) falls below the declared floor "
                        f"{spec.floor:g}")
            if len(vals) < 2:
                continue
            (p_name, prev), (l_name, last) = vals[-2], vals[-1]
            delta = (last - prev) / prev if prev else 0.0
            arrow = "+" if delta >= 0 else ""
            note = f"[{backend} {arrow}{delta * 100:.1f}% vs {p_name}]"
            if _regressed(spec, prev, last):
                note += " REGRESSED"
                regressions.append(
                    f"{spec.label} ({backend}): {prev:g} ({p_name}) "
                    f"-> {last:g} ({l_name}), tolerance "
                    f"{spec.tolerance * 100:g}% "
                    f"({'higher' if spec.higher_is_better else 'lower'}"
                    f" is better)")
            notes.append(note)
        verdict = ("  " + " ".join(notes)) if notes else ""
        out(" | ".join([f"{spec.label:<{name_w}}"]
                       + [f"{c:>{col_w}}" for c in cells]) + verdict)

    out("")
    if regressions:
        out(f"bench-report: {len(regressions)} metric(s) regressed "
            "past their declared threshold:")
        for r in regressions:
            out(f"  - {r}")
        return 1
    out(f"bench-report: trajectory clean over {len(rounds)} round(s), "
        f"{len(METRICS)} metrics")
    return 0


def add_args(p) -> None:
    """The bench-report CLI surface (shared by the cli.py subcommand)."""
    p.add_argument("artifacts", nargs="*",
                   help="BENCH_*.json artifacts in round order "
                        "(default: BENCH_*.json in --root, sorted)")
    p.add_argument("--root", default=".",
                   help="directory to glob BENCH_*.json from when no "
                        "explicit artifacts are given")


def run_from_args(args) -> int:
    paths = [Path(a) for a in args.artifacts] \
        or default_artifacts(args.root)
    return report(paths)
