"""`python -m jepsen_tpu.obs.smoke` — the one-command live-telemetry
smoke behind `make obs-smoke`.

Builds a tiny throwaway store, runs a real POOLED `analyze-store`
sweep (JEPSEN_TPU_PIPELINE=1 forces the worker pool even on 1-core
boxes) with the health sampler, the `/metrics` endpoint and the
attribution report force-enabled (interval 0.2 s, ephemeral port),
scrapes `/metrics` and `/healthz` once mid-flight via a hook, and
asserts the contract the acceptance criteria pin: health.json
snapshots exist and parse, the scraped counters match the final
metrics.json, the flight recorder holds the sweep's start/end events,
the merged trace.json carries at least one worker-process track with
encode spans, and report.json exists with stage shares summing to
~1.0. Exit 0 on success, 1 with a reason on any violation. CPU-only,
a few seconds end to end.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile
import urllib.request
from pathlib import Path


def main() -> int:
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from .. import cli, gates, trace
    from ..checker.elle.synth import synth_append_history
    from ..store import Store

    gates.export("JEPSEN_TPU_HEALTH_INTERVAL_S", 0.2)
    gates.export("JEPSEN_TPU_METRICS_PORT", 0)    # ephemeral
    # a REAL pooled sweep, even on a 1-core box: the trace-fabric
    # assertions below need actual worker processes spooling spans
    gates.export("JEPSEN_TPU_PIPELINE", 1)
    # the device cost observatory: the assertions below pin the
    # residency gauges on /metrics + health.json, the report's device
    # section and the costdb contract
    gates.export("JEPSEN_TPU_COSTDB", 1)
    # kernel search telemetry: the assertions below pin the analytics
    # ledger, the report "search" section, and the kernel.* series on
    # a live sweep
    gates.export("JEPSEN_TPU_KERNEL_STATS", 1)

    root = Path(tempfile.mkdtemp(prefix="obs-smoke-"))
    try:
        store = Store(root / "store")
        for i in range(3):
            d = store.base / "smoke" / f"2020010{i + 1}T000000"
            d.mkdir(parents=True)
            hist = synth_append_history(T=40, K=4, seed=i)
            (d / "history.jsonl").write_text(
                "\n".join(json.dumps(o) for o in hist) + "\n")

        scraped: dict = {}

        def on_obs_up(server, sampler):
            """Mid-sweep scrape hook: the endpoint is live, the
            sampler has written its first snapshot."""
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                scraped["metrics"] = r.read().decode()
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as r:
                scraped["healthz"] = json.loads(r.read().decode())

        rc = cli.analyze_store(store, checker="append",
                               obs_hook=on_obs_up, report=True)
        if rc != 0:
            print(f"obs-smoke: sweep failed rc={rc}")
            return 1
        if "metrics" not in scraped:
            print("obs-smoke: endpoint never scraped")
            return 1
        health = json.loads((store.base / "health.json").read_text())
        if health["heartbeat"]["seq"] < 1 \
                or health["progress"]["runs_total"] != 3 \
                or health["progress"]["runs_verdicted"] != 3:
            print(f"obs-smoke: bad final health snapshot: {health}")
            return 1
        if scraped["healthz"].get("v") != 1:
            print(f"obs-smoke: bad /healthz: {scraped['healthz']}")
            return 1
        if "jepsen_tpu_shm_stale_reclaimed " not in scraped["metrics"]:
            print("obs-smoke: mid-flight /metrics page malformed:\n"
                  + scraped["metrics"])
            return 1
        # exposition↔metrics.json parity: rendering the sweep tracer
        # now (it is still current) must carry every final counter at
        # its final value — the mid-flight page is the same renderer
        # over earlier state
        from .prom import _name, render_prometheus
        page_lines = render_prometheus(
            trace.get_current()).splitlines()
        final = json.loads((store.base / "metrics.json").read_text())
        for name, v in final["counters"].items():
            want = f"{_name(name)} {v}"
            # whole-line match: a renderer bug that extends the value
            # by a digit must not pass a prefix check
            if want not in page_lines:
                print(f"obs-smoke: {want!r} not in /metrics render")
                return 1
        for name in ("buckets_dispatched", "buckets_resolved",
                     "runs_verdicted"):
            if name not in final["counters"]:
                print(f"obs-smoke: counter {name} missing from "
                      "metrics.json")
                return 1
        from .events import load_events
        evs = [e["event"] for e in load_events(store.base)]
        if "sweep_start" not in evs or "sweep_end" not in evs:
            print(f"obs-smoke: flight recorder incomplete: {evs}")
            return 1
        # -- trace fabric + attribution report contract ---------------
        if not trace.iter_spools(store.base):
            print("obs-smoke: pooled sweep left no worker trace "
                  "spools")
            return 1
        tj = json.loads((store.base / "trace.json").read_text())
        worker_pids = {e["pid"] for e in tj["traceEvents"]
                       if e.get("ph") == "M"
                       and e.get("name") == "process_name"
                       and "worker" in str(e["args"].get("name", ""))}
        if not worker_pids:
            print("obs-smoke: merged trace has no worker-process "
                  "track")
            return 1
        if not any(e.get("ph") == "X" and e.get("name") == "encode"
                   and e.get("pid") in worker_pids
                   for e in tj["traceEvents"]):
            print("obs-smoke: no encode span on any worker track")
            return 1
        rep = json.loads((store.base / "report.json").read_text())
        share_sum = sum(rep["shares"].values())
        if abs(share_sum - 1.0) > 0.02:
            print(f"obs-smoke: report shares sum to {share_sum:.4f}, "
                  "not 1.0 +/- 0.02")
            return 1
        if not (store.base / "report.md").is_file():
            print("obs-smoke: report.md missing")
            return 1
        if final["counters"].get("worker_spans", 0) < 1:
            print("obs-smoke: worker_spans digest never reached the "
                  "parent tracer")
            return 1
        # -- device cost observatory contract --------------------------
        for gname in ("jepsen_tpu_resident_executables",
                      "jepsen_tpu_hbm_modeled_bytes"):
            if not any(ln.startswith(gname + " ")
                       for ln in page_lines):
                print(f"obs-smoke: residency gauge {gname} missing "
                      "from /metrics render")
                return 1
        dev = health.get("device") or {}
        if not isinstance(dev.get("resident_executables"), int):
            print(f"obs-smoke: health.json device section missing "
                  f"residency gauges: {dev}")
            return 1
        from ..store import load_costdb
        cost_recs = load_costdb(store.base)
        if not cost_recs:
            print("obs-smoke: no costdb.jsonl records despite "
                  "JEPSEN_TPU_COSTDB=1")
            return 1
        if any(r.get("provenance") not in ("measured", "estimated")
               for r in cost_recs):
            print(f"obs-smoke: untagged costdb provenance: "
                  f"{cost_recs[:1]}")
            return 1
        if "device" not in rep or not rep["device"].get("records"):
            print("obs-smoke: report.json has no device section")
            return 1
        if "Device roofline" not in \
                (store.base / "report.md").read_text():
            print("obs-smoke: report.md has no device roofline "
                  "section")
            return 1
        # -- kernel search telemetry contract --------------------------
        from ..store import load_analytics
        stats_recs = load_analytics(store.base)
        if len(stats_recs) != 3:
            print(f"obs-smoke: analytics.jsonl has {len(stats_recs)} "
                  "record(s), expected 3 (one per run)")
            return 1
        if any("margin" not in r or "closure_rounds" not in r
               for r in stats_recs):
            print(f"obs-smoke: analytics record missing stat fields: "
                  f"{stats_recs[:1]}")
            return 1
        if "search" not in rep or rep["search"].get("histories") != 3:
            print(f"obs-smoke: report.json search section missing or "
                  f"wrong: {rep.get('search')}")
            return 1
        if "Search telemetry" not in \
                (store.base / "report.md").read_text():
            print("obs-smoke: report.md has no search section")
            return 1
        if not any(ln.startswith("jepsen_tpu_kernel_stats_records ")
                   for ln in page_lines):
            print("obs-smoke: kernel.stats_records missing from "
                  "/metrics render")
            return 1
        print("obs-smoke: OK — health.json "
              f"(seq {health['heartbeat']['seq']}), /metrics scraped "
              f"({len(scraped['metrics'].splitlines())} lines), "
              f"{len(evs)} flight-recorder events, "
              f"{len(worker_pids)} worker track(s), report bound="
              f"{rep.get('bound')}, costdb {len(cost_recs)} "
              f"record(s) [{cost_recs[0]['provenance']}]")
        return 0
    finally:
        trace.reset()
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
