"""Critical-path attribution over a merged sweep timeline.

The tracer answers "what happened when"; this module answers the
question the r05 hardware round could not: "73.1 s sweep, 13.8 s
measured overlap, MFU 0.28 — so WHICH stage is the bottleneck, and
what would the wall clock be if it were fixed?" It walks the merged
Chrome timeline (parent phases + per-process worker encode tracks +
device dispatch windows — trace.merge_traces) and computes, per
sweep:

  * the **serial bottleneck decomposition**: every instant of wall
    time charged to exactly one stage by pipeline priority (device >
    h2d > pack > encode > parse > feed > dispatch > collect > render
    > idle — work overlapped UNDER a downstream stage is hidden, so
    the downstream stage owns the instant). Shares sum to 1.0 by
    construction. The un-prioritized per-stage busy unions are
    reported too; on a strictly serial single-process sweep they
    equal the tracer's `phases` totals exactly (nothing overlaps, so
    charging == presence).
  * **pipeline-stall accounting**: each gap between consecutive
    device dispatch windows classified by what the host was doing —
    ingest-starved (workers/parse active: the pool couldn't feed),
    pack-bound (pack/h2d active: the packer couldn't keep up), or
    other (pure scheduling) — aggregated and itemized per gap.
  * **what-if headroom**: the ideal wall clock under perfect overlap
    is the longest single stage's busy time; the report names the
    bound stage and the seconds a perfectly pipelined sweep would
    save at the current per-stage rates (for a device-bound sweep:
    "ideal wall = device busy seconds at current MFU").

Exposed as `analyze-store --report` -> `<store>/report.json` +
human-readable `report.md`; bench.py embeds the same decomposition in
the north_star and cache_warm blocks and `bench-report` trends the
shares. Stdlib-only; events come in as plain dicts, so this runs on
an archived trace.json as well as a live tracer.
"""

from __future__ import annotations

import bisect
import json
from pathlib import Path

from .. import trace

#: Stage priority for the serial decomposition, downstream first: an
#: instant where the device is busy is device-bound whatever the host
#: does under it; host stages order pack-side over ingest-side the
#: same way.
STAGE_PRIORITY = ("device", "h2d", "pack", "encode", "parse", "feed",
                  "dispatch", "collect", "render")

#: Parent phase spans that map 1:1 onto a stage.
_PHASE_STAGES = frozenset({"parse", "pack", "h2d", "feed", "dispatch",
                           "collect", "render"})

#: Cap on the per-gap stall itemization in report.json.
_MAX_GAPS = 50


# interval arithmetic is shared with ingest.overlap_seconds — ONE
# implementation (trace.merge_intervals / trace.overlap_seconds), so
# the bench's pipeline_overlap_secs and this report can never
# disagree about the same timeline
_union = trace.merge_intervals
_overlap = trace.overlap_seconds


def _clip(iv: list, w0: float, w1: float) -> list:
    return [(max(s, w0), min(e, w1)) for s, e in iv
            if min(e, w1) > max(s, w0)]


def _total(iv: list) -> float:
    return sum(e - s for s, e in iv)


def stage_intervals(events: list, window_us=None):
    """Per-stage (start, end) second-interval unions from a merged
    Chrome event list, plus the worker pids seen. Stage mapping:

      * cat=="device"                          -> device
      * any X event from a worker process      -> encode (worker pids
        are identified by their process_name metadata containing
        "worker"; nested worker spans union away)
      * parent spans on an "ingest-pool*" track -> encode (the
        parent-side mirror of worker parse windows — union with the
        spool spans dedups them)
      * cat=="phase" spans named parse/pack/h2d/feed/dispatch/
        collect/render -> that stage

    Everything else (nested detail spans, instants, quarantine spans)
    is deliberately unmapped: it is either contained in a mapped span
    or not wall-clock-attributable. With `window_us=(a, b)` intervals
    are clipped to the window (bench rounds scope a sweep out of a
    whole-round tracer)."""
    worker_pids: set = set()
    tracknames: dict = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name" \
                and "worker" in str(args.get("name", "")):
            worker_pids.add(e.get("pid"))
        elif e.get("name") == "thread_name":
            tracknames[(e.get("pid"), e.get("tid"))] = \
                str(args.get("name", ""))
    iv: dict[str, list] = {s: [] for s in STAGE_PRIORITY}
    for e in events:
        if e.get("ph") != "X":
            continue
        t0 = e.get("ts", 0.0) / 1e6
        t1 = t0 + e.get("dur", 0.0) / 1e6
        cat = e.get("cat")
        if cat == "device":
            stage = "device"
        elif e.get("pid") in worker_pids:
            stage = "encode"
        elif tracknames.get((e.get("pid"), e.get("tid")),
                            "").startswith("ingest-pool"):
            stage = "encode"
        elif cat == "phase" and e.get("name") in _PHASE_STAGES:
            stage = e["name"]
        else:
            continue
        iv[stage].append((t0, t1))
    if window_us is not None:
        w0, w1 = window_us[0] / 1e6, window_us[1] / 1e6
        iv = {s: _clip(v, w0, w1) for s, v in iv.items()}
    return {s: _union(v) for s, v in iv.items()}, worker_pids


def _charge(unions: dict, w0: float, w1: float) -> dict:
    """The serial decomposition: walk the elementary segments of
    [w0, w1] and charge each to the highest-priority active stage;
    the remainder is idle. Sums to exactly w1 - w0."""
    bounds = {w0, w1}
    for iv in unions.values():
        for s, e in iv:
            if w0 < s < w1:
                bounds.add(s)
            if w0 < e < w1:
                bounds.add(e)
    cuts = sorted(bounds)
    starts = {s: [p[0] for p in iv] for s, iv in unions.items()}
    charged = {s: 0.0 for s in STAGE_PRIORITY}
    charged["idle"] = 0.0
    for a, b in zip(cuts, cuts[1:]):
        mid = (a + b) / 2
        for stage in STAGE_PRIORITY:
            i = bisect.bisect_right(starts[stage], mid) - 1
            if i >= 0 and unions[stage][i][1] > mid:
                charged[stage] += b - a
                break
        else:
            charged["idle"] += b - a
    return charged


def _stalls(unions: dict, w0: float, w1: float) -> dict:
    """Device-gap accounting: every gap between consecutive device
    windows (plus the lead-in from the window start to the first
    dispatch) classified by what the host was doing."""
    dev = unions.get("device", [])
    ingest = _union(unions.get("encode", []) + unions.get("parse", []))
    packing = _union(unions.get("pack", []) + unions.get("h2d", []))
    gaps = []
    prev = w0
    for i, (s, e) in enumerate(dev):
        if s > prev:
            gaps.append((i, prev, s))
        prev = max(prev, e)
    agg = {"ingest_starved_secs": 0.0, "pack_bound_secs": 0.0,
           "other_secs": 0.0}
    items = []
    for i, a, b in gaps:
        g = [(a, b)]
        ing = _overlap(g, ingest)
        pk = _overlap(g, packing)
        if ing >= pk and ing > 0:
            cause = "ingest_starved"
        elif pk > 0:
            cause = "pack_bound"
        else:
            cause = "other"
        agg[f"{cause}_secs"] += b - a
        if len(items) < _MAX_GAPS:
            items.append({"before_dispatch": i, "secs": round(b - a, 6),
                          "cause": cause})
    busy = _total(dev)
    return {
        "device_busy_secs": round(busy, 6),
        "device_idle_secs": round(max(0.0, (w1 - w0) - busy), 6),
        "dispatches": len(dev),
        "gaps": len(gaps),
        **{k: round(v, 6) for k, v in agg.items()},
        "gap_detail": items,
    }


def analyze(events: list, window_us=None, counters=None) -> dict:
    """The attribution report dict for a merged Chrome event list.
    Always returns shares summing to 1.0 (idle included); an empty or
    unmapped timeline reports wall 0 and no bound."""
    unions, worker_pids = stage_intervals(events, window_us=window_us)
    pts = [t for iv in unions.values() for p in iv for t in p]
    if window_us is not None:
        w0, w1 = window_us[0] / 1e6, window_us[1] / 1e6
    elif pts:
        w0, w1 = min(pts), max(pts)
    else:
        w0 = w1 = 0.0
    wall = max(0.0, w1 - w0)
    busy = {s: round(_total(iv), 6) for s, iv in unions.items()}
    if wall <= 0:
        return {"wall_secs": 0.0, "shares": {}, "busy_secs": busy,
                "charged_secs": {}, "stalls": {}, "bound": None,
                "ideal_wall_secs": 0.0, "headroom_secs": 0.0,
                "workers": len(worker_pids)}
    charged = _charge(unions, w0, w1)
    shares = {s: v / wall for s, v in charged.items()}
    # the bound is the single longest stage by PRESENCE (busy union):
    # under perfect pipelining everything else hides beneath it, so
    # its busy time is also the ideal wall clock
    bound = max((s for s in STAGE_PRIORITY), key=lambda s: busy[s])
    if busy[bound] <= 0:
        bound = None
    ideal = busy[bound] if bound else 0.0
    rep = {
        "wall_secs": round(wall, 6),
        "shares": {s: round(v, 4) for s, v in shares.items()},
        "busy_secs": busy,
        "charged_secs": {s: round(v, 6) for s, v in charged.items()},
        "stalls": _stalls(unions, w0, w1),
        "bound": bound,
        "ideal_wall_secs": round(ideal, 6),
        "headroom_secs": round(max(0.0, wall - ideal), 6),
        "workers": len(worker_pids),
    }
    if counters:
        rep["counters"] = dict(counters)
    return rep


def summary_line(rep: dict) -> str:
    """The one-sentence what-if: which stage binds the sweep and what
    a perfectly overlapped sweep would cost."""
    bound = rep.get("bound")
    if not bound:
        return "no attributable timeline"
    return (f"{bound}-bound: ideal wall = "
            f"{rep['ideal_wall_secs']:.3f}s at current per-stage "
            f"rates ({rep['headroom_secs']:.3f}s headroom over the "
            f"measured {rep['wall_secs']:.3f}s)")


def render_report_md(rep: dict) -> str:
    """The human-readable report.md."""
    lines = ["# Sweep attribution report", ""]
    lines.append(f"Wall clock: **{rep.get('wall_secs', 0.0):.3f} s** "
                 f"over {rep.get('workers', 0)} worker process(es); "
                 f"{summary_line(rep)}.")
    lines += ["", "## Serial bottleneck decomposition", "",
              "| stage | share | charged s | busy s |",
              "|---|---|---|---|"]
    shares = rep.get("shares", {})
    busy = rep.get("busy_secs", {})
    charged = rep.get("charged_secs", {})
    for s in (*STAGE_PRIORITY, "idle"):
        if s not in shares:
            continue
        lines.append(f"| {s} | {shares[s]:.1%} | "
                     f"{charged.get(s, 0.0):.3f} | "
                     f"{busy.get(s, 0.0):.3f} |")
    st = rep.get("stalls") or {}
    if st:
        lines += ["", "## Pipeline stalls (device gaps)", "",
                  f"- device busy {st.get('device_busy_secs', 0.0):.3f}"
                  f" s over {st.get('dispatches', 0)} dispatch "
                  f"window(s); idle "
                  f"{st.get('device_idle_secs', 0.0):.3f} s",
                  f"- ingest-starved "
                  f"{st.get('ingest_starved_secs', 0.0):.3f} s · "
                  f"pack-bound {st.get('pack_bound_secs', 0.0):.3f} s "
                  f"· other {st.get('other_secs', 0.0):.3f} s "
                  f"across {st.get('gaps', 0)} gap(s)"]
    per_shard = rep.get("per_shard") or {}
    if per_shard:
        lines += ["", "## Per-shard decomposition (mesh sweep)", "",
                  "| shard | wall s | bound | device | encode | idle |",
                  "|---|---|---|---|---|---|"]
        # numeric-aware order: '10' after '2', not between '1' and '2'
        for k in sorted(per_shard,
                        key=lambda s: (0, int(s)) if str(s).isdigit()
                        else (1, str(s))):
            sr = per_shard[k]
            ss = sr.get("shares", {})
            lines.append(
                f"| {k} | {sr.get('wall_secs', 0.0):.3f} | "
                f"{sr.get('bound') or '—'} | "
                f"{ss.get('device', 0.0):.1%} | "
                f"{ss.get('encode', 0.0):.1%} | "
                f"{ss.get('idle', 0.0):.1%} |")
    dev = rep.get("device") or {}
    if dev:
        lines += render_device_md(dev)
    search_sec = rep.get("search") or {}
    if search_sec:
        from . import search as search_mod
        lines += search_mod.render_search_md(search_sec)
    planner_sec = rep.get("planner") or {}
    if planner_sec:
        from .. import planner as planner_mod
        lines += planner_mod.render_planner_md(planner_sec)
    lines += ["", "## What-if", "", f"- {summary_line(rep)}"]
    if rep.get("counters"):
        keep = ("runs_verdicted", "buckets_dispatched", "cache_hits",
                "cache_misses", "worker_spans", "quarantined")
        rows = [(k, rep["counters"][k]) for k in keep
                if k in rep["counters"]]
        if rows:
            lines += ["", "## Counters", ""]
            lines += [f"- `{k}` = {v}" for k, v in rows]
    return "\n".join(lines) + "\n"


def device_section(records: list) -> dict | None:
    """The report's "device" section from the cost observatory's
    finalized records (jepsen_tpu/obs/device.py — already carrying
    achieved rates, roofline utilization and provenance, so this stays
    stdlib-only): one row per (executable, geometry) with measured
    windows, plus the sweep-level aggregate. None when no records
    were captured (gate off)."""
    rows = []
    provenance = "estimated"
    peak = None
    for r in records or []:
        if not isinstance(r, dict):
            continue
        w = r.get("windows") or {}
        g = r.get("geometry") or {}
        cost = r.get("cost") or {}
        ach = r.get("achieved") or {}
        roof = r.get("roofline") or {}
        peak = r.get("peak") or peak
        if r.get("provenance") == "measured":
            provenance = "measured"
        rows.append({
            "geometry": g,
            "formulation": r.get("formulation"),
            "analysis": r.get("analysis"),
            "flops": cost.get("flops"),
            "bytes_accessed": cost.get("bytes_accessed"),
            "dispatches": w.get("dispatches", 0),
            "device_secs": round(w.get("device_secs", 0.0), 6),
            "histories": w.get("histories", 0),
            "achieved_tflops": (
                round(ach["flops_per_sec"] / 1e12, 4)
                if isinstance(ach.get("flops_per_sec"), (int, float))
                else None),
            "achieved_gbps": (
                round(ach["bytes_per_sec"] / 1e9, 3)
                if isinstance(ach.get("bytes_per_sec"), (int, float))
                else None),
            "flops_utilization": roof.get("flops_utilization"),
            "bandwidth_utilization": roof.get("bandwidth_utilization"),
            "provenance": r.get("provenance"),
        })
    if not rows:
        return None
    return {"records": rows, "peak": peak, "provenance": provenance,
            "device_secs": round(sum(r["device_secs"] for r in rows),
                                 6)}


def render_device_md(dev: dict) -> list[str]:
    """The report.md roofline table for one device section."""
    peak = dev.get("peak") or {}
    lines = ["", "## Device roofline (cost observatory)", "",
             f"Peak: {peak.get('device_kind', '?')} "
             f"[{peak.get('source', '?')}] — "
             f"{peak.get('bf16_tflops', '?')} bf16 TFLOPS / "
             f"{peak.get('int8_tops', '?')} int8 TOPS / "
             f"{peak.get('hbm_gbps', '?')} GB/s HBM; provenance "
             f"**{dev.get('provenance')}**.", "",
             "| geometry | form | dispatches | device s | achieved "
             "TFLOP/s | achieved GB/s | flops util | bw util |",
             "|---|---|---|---|---|---|---|---|"]
    for r in dev.get("records", []):
        g = r.get("geometry") or {}
        geom = (f"B{g.get('B')}xT{g.get('n_txns')}"
                f"(K{g.get('n_keys')},P{g.get('max_pos')})")

        def pct(v):
            return f"{v:.2%}" if isinstance(v, (int, float)) else "—"

        def num(v):
            return f"{v:g}" if isinstance(v, (int, float)) else "—"

        lines.append(
            f"| {geom} | {r.get('formulation')} | "
            f"{r.get('dispatches')} | {r.get('device_secs'):.4f} | "
            f"{num(r.get('achieved_tflops'))} | "
            f"{num(r.get('achieved_gbps'))} | "
            f"{pct(r.get('flops_utilization'))} | "
            f"{pct(r.get('bandwidth_utilization'))} |")
    return lines


def analyze_shards(per_shard_events: dict) -> dict:
    """Per-shard attribution for a mesh sweep: each shard's report is
    computed over ITS OWN event list (its own timeline — cross-host
    clock alignment never touches the shares), so per-shard shares sum
    to 1.0 per shard by the same construction as the merged report."""
    return {str(k): analyze(evs)
            for k, evs in sorted(per_shard_events.items())}


def write_report(store_base, events: list, metrics: dict | None = None,
                 window_us=None, per_shard_events: dict | None = None,
                 device_records: list | None = None,
                 search_records: list | None = None):
    """Write `<store>/report.json` + `report.md` (atomically — the
    journal discipline) and return their paths. With
    `per_shard_events` ({shard: event list} — a mesh sweep's
    coordinator merge) the report additionally carries `per_shard`:
    each shard's own stage-share decomposition, so `bench-report` and
    operators can pin per-shard ceilings, not just fleet-wide ones.
    With `device_records` (the cost observatory's finalized records —
    merged across shards by the coordinator) it carries the `device`
    roofline section: per-(executable, geometry) achieved-vs-peak
    FLOPs and bandwidth from captured `cost_analysis()` joined with
    the measured dispatch windows. With `search_records` (the kernel
    search-telemetry ledger, JEPSEN_TPU_KERNEL_STATS) it carries the
    `search` section: anomaly-rate and margin distributions plus the
    edge-density-vs-device-time join against the costdb."""
    base = Path(store_base)
    rep = analyze(events, window_us=window_us,
                  counters=(metrics or {}).get("counters"))
    rep = {"v": 1, **rep}
    if per_shard_events:
        rep["per_shard"] = analyze_shards(per_shard_events)
    if device_records:
        dev = device_section(device_records)
        if dev is not None:
            rep["device"] = dev
    if search_records:
        from . import search as search_mod
        sec = search_mod.search_section(search_records,
                                        cost_records=device_records)
        if sec is not None:
            rep["search"] = sec
    from .. import planner as planner_mod
    if planner_mod.enabled():
        # the planner section reads the PROCESS state (active plan +
        # this sweep's decision counters) rather than taking another
        # records parameter: a cold sweep still reports its fallback
        # tally, which is the section's whole point
        rep["planner"] = planner_mod.planner_section(
            planner_mod.current_plan(), cost_records=device_records,
            metrics=metrics)
    jp = trace.atomic_write_text(base / "report.json",
                                 json.dumps(rep, indent=2))
    mp = trace.atomic_write_text(base / "report.md",
                                 render_report_md(rep))
    return jp, mp
