"""Fressian binary codec (read + write) for reference store compat.

The reference persists each test as ``test.fressian`` before analysis
(store.clj:31-116 defines custom write handlers; save-1! store.clj:372)
— this module lets those artifacts be loaded (and written) without a
JVM. The wire format follows the public fressian spec
(github.com/Datomic/fressian/wiki, org.fressian.impl.Codes); the subset
implemented is everything jepsen's store emits: nil/bool/ints/doubles/
strings/keywords/symbols/lists/maps/sets/insts, the priority and struct
caches (keywords and repeated tags are cache-referenced on the wire),
and tagged structs for the custom handlers (atoms, Joda DateTime,
multisets, MapEntry — surfaced as TaggedValue/known conversions).

Derived from the spec without a JVM to differentially test against, so
exotica (BIGINT chunks, regexes, metadata) raise cleanly rather than
guessing.
"""

from __future__ import annotations

import datetime
import io
import struct as _struct
from typing import Any

from .edn import Keyword, Symbol

# Code table (org.fressian.impl.Codes).
PRIORITY_CACHE_PACKED_START = 0x80   # ..0x9F
STRUCT_CACHE_PACKED_START = 0xA0     # ..0xAF
MAP = 0xC0
SET = 0xC1
UUID_ = 0xC3
REGEX = 0xC4
URI = 0xC5
BIGINT = 0xC6
BIGDEC = 0xC7
INST = 0xC8
SYM = 0xC9
KEY = 0xCA
GET_PRIORITY_CACHE = 0xCC
PUT_PRIORITY_CACHE = 0xCD
PRECACHE = 0xCE
FOOTER = 0xCF
BYTES_PACKED_START = 0xD0            # ..0xD7
BYTES_CHUNK = 0xD8
BYTES = 0xD9
STRING_PACKED_START = 0xDA           # ..0xE1
STRING_CHUNK = 0xE2
STRING = 0xE3
LIST_PACKED_START = 0xE4             # ..0xEB
LIST = 0xEC
BEGIN_CLOSED_LIST = 0xED
BEGIN_OPEN_LIST = 0xEE
STRUCTTYPE = 0xEF
STRUCT = 0xF0
META = 0xF1
ANY = 0xF4
TRUE = 0xF5
FALSE = 0xF6
NULL = 0xF7
INT = 0xF8
FLOAT = 0xF9
DOUBLE = 0xFA
DOUBLE_0 = 0xFB
DOUBLE_1 = 0xFC
END_COLLECTION = 0xFD
RESET_CACHES = 0xFE
INT_PACKED_1_NEG = 0xFF              # the single-byte -1


class TaggedValue:
    """A struct with a tag this codec has no native mapping for."""

    def __init__(self, tag: str, values: list):
        self.tag = tag
        self.values = values

    def __eq__(self, other):
        return (isinstance(other, TaggedValue) and other.tag == self.tag
                and other.values == self.values)

    def __repr__(self):
        return f"TaggedValue({self.tag!r}, {self.values!r})"


class StructType:
    def __init__(self, tag: str, n_fields: int):
        self.tag = tag
        self.n_fields = n_fields


class FressianError(Exception):
    pass


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------

class Reader:
    def __init__(self, data: bytes):
        self.buf = io.BytesIO(data)
        self.priority_cache: list = []
        self.struct_cache: list[StructType] = []

    # -- raw reads --------------------------------------------------------

    def _u1(self) -> int:
        b = self.buf.read(1)
        if not b:
            raise FressianError("unexpected EOF")
        return b[0]

    def _raw(self, n: int) -> bytes:
        b = self.buf.read(n)
        if len(b) != n:
            raise FressianError("unexpected EOF")
        return b

    def _int_n(self, n: int) -> int:
        """n-byte big-endian unsigned."""
        v = 0
        for b in self._raw(n):
            v = (v << 8) | b
        return v

    # -- object reads -----------------------------------------------------

    def read(self) -> Any:
        return self._read_object(self._u1())

    def at_eof(self) -> bool:
        pos = self.buf.tell()
        more = self.buf.read(1)
        self.buf.seek(pos)
        return not more

    def _read_object(self, code: int) -> Any:
        # Packed small ints 0..63 and -1.
        if code <= 0x3F:
            return code
        if code == INT_PACKED_1_NEG:
            return -1
        # Packed int zones (spec: signed (code-bias) high bits).
        if 0x40 <= code <= 0x5F:
            return ((code - 0x50) << 8) | self._int_n(1)
        if 0x60 <= code <= 0x6F:
            return ((code - 0x68) << 16) | self._int_n(2)
        if 0x70 <= code <= 0x73:
            return ((code - 0x72) << 24) | self._int_n(3)
        if 0x74 <= code <= 0x77:
            return ((code - 0x76) << 32) | self._int_n(4)
        if 0x78 <= code <= 0x7B:
            return ((code - 0x7A) << 40) | self._int_n(5)
        if 0x7C <= code <= 0x7F:
            return ((code - 0x7E) << 48) | self._int_n(6)
        if code == INT:
            return _struct.unpack(">q", self._raw(8))[0]

        if code == NULL:
            return None
        if code == TRUE:
            return True
        if code == FALSE:
            return False
        if code == DOUBLE:
            return _struct.unpack(">d", self._raw(8))[0]
        if code == DOUBLE_0:
            return 0.0
        if code == DOUBLE_1:
            return 1.0
        if code == FLOAT:
            return _struct.unpack(">f", self._raw(4))[0]

        # Strings / bytes.
        if STRING_PACKED_START <= code <= 0xE1:
            return self._raw(code - STRING_PACKED_START).decode("utf-8")
        if code == STRING:
            return self._raw(self._read_int()).decode("utf-8")
        if code == STRING_CHUNK:
            parts = [self._raw(self._read_int()).decode("utf-8")]
            nxt = self._u1()
            while nxt == STRING_CHUNK:
                parts.append(self._raw(self._read_int()).decode("utf-8"))
                nxt = self._u1()
            if nxt != STRING:
                raise FressianError("bad string chunk terminator")
            parts.append(self._raw(self._read_int()).decode("utf-8"))
            return "".join(parts)
        if BYTES_PACKED_START <= code <= 0xD7:
            return self._raw(code - BYTES_PACKED_START)
        if code == BYTES:
            return self._raw(self._read_int())

        # Lists.
        if LIST_PACKED_START <= code <= 0xEB:
            return [self.read() for _ in range(code - LIST_PACKED_START)]
        if code == LIST:
            return [self.read() for _ in range(self._read_int())]
        if code in (BEGIN_CLOSED_LIST, BEGIN_OPEN_LIST):
            out = []
            while True:
                c = self._u1()
                if c == END_COLLECTION:
                    return out
                out.append(self._read_object(c))

        # Caches.
        if PRIORITY_CACHE_PACKED_START <= code <= 0x9F:
            return self._cache_ref(code - PRIORITY_CACHE_PACKED_START)
        if code == GET_PRIORITY_CACHE:
            return self._cache_ref(self._read_int())
        if code == PUT_PRIORITY_CACHE:
            idx = len(self.priority_cache)
            self.priority_cache.append(None)   # reserve slot in order
            v = self.read()
            self.priority_cache[idx] = v
            return v
        if code == PRECACHE:
            idx = len(self.priority_cache)
            self.priority_cache.append(None)
            self.priority_cache[idx] = self.read()
            return self.read()  # precache then the actual object
        if code == RESET_CACHES:
            self.priority_cache = []
            self.struct_cache = []
            return self.read()

        # Structs / named types.
        if code == KEY:
            ns, name = self.read(), self.read()
            return Keyword(f"{ns}/{name}" if ns else str(name))
        if code == SYM:
            ns, name = self.read(), self.read()
            return Symbol(f"{ns}/{name}" if ns else str(name))
        if code == STRUCTTYPE:
            tag = self.read()
            n = self._read_int()
            st = StructType(str(tag), n)
            self.struct_cache.append(st)
            return self._read_struct(st)
        if code == STRUCT:
            tag = self.read()
            n = self._read_int()
            return self._read_struct(StructType(str(tag), n))
        if STRUCT_CACHE_PACKED_START <= code <= 0xAF:
            idx = code - STRUCT_CACHE_PACKED_START
            if idx >= len(self.struct_cache):
                raise FressianError(f"struct cache miss {idx}")
            return self._read_struct(self.struct_cache[idx])

        if code == MAP:
            kvs = self.read()   # a list of alternating k/v
            return dict(zip(kvs[::2], kvs[1::2]))
        if code == SET:
            items = self.read()
            try:
                return frozenset(items)
            except TypeError:
                return tuple(items)
        if code == INST:
            millis = self.read()
            return datetime.datetime.fromtimestamp(
                millis / 1000, tz=datetime.timezone.utc)
        if code == FOOTER:
            # length + magic + checksum follow; stream ends here.
            raise FressianError("footer")

        raise FressianError(f"unsupported fressian code 0x{code:02X}")

    def _cache_ref(self, idx: int) -> Any:
        if idx >= len(self.priority_cache):
            raise FressianError(f"priority cache miss {idx}")
        return self.priority_cache[idx]

    def _read_int(self) -> int:
        v = self.read()
        if not isinstance(v, int):
            raise FressianError(f"expected int, got {type(v)}")
        return v

    def _read_struct(self, st: StructType) -> Any:
        vals = [self.read() for _ in range(st.n_fields)]
        return convert_tagged(st.tag, vals)


def convert_tagged(tag: str, vals: list) -> Any:
    """Map jepsen's custom write handlers (store.clj:31-116) onto
    Python values; unknown tags stay TaggedValue."""
    if tag == "atom" and len(vals) == 1:
        return vals[0]                       # deref'd atom
    if tag in ("clojure/instant", "datetime", "org.joda.time.DateTime") \
            and len(vals) == 1 and isinstance(vals[0], int):
        return datetime.datetime.fromtimestamp(
            vals[0] / 1000, tz=datetime.timezone.utc)
    if tag == "map-entry" and len(vals) == 2:
        # The reference's independent/tuple IS a MapEntry
        # (independent.clj:22-30) — reconstruct the lifted type so
        # re-analysis of reference stores splits per key again.
        from .independent import Tuple
        return Tuple(vals[0], vals[1])
    if tag == "multiset" and len(vals) == 1 and isinstance(vals[0], dict):
        out = []
        for v, n in vals[0].items():
            out.extend([v] * int(n))
        return out
    return TaggedValue(tag, vals)


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

class Writer:
    def __init__(self):
        self.buf = io.BytesIO()
        self.priority_cache: dict = {}

    def getvalue(self) -> bytes:
        return self.buf.getvalue()

    def _w(self, *bs: int) -> None:
        self.buf.write(bytes(bs))

    def write(self, v: Any) -> None:
        if v is None:
            return self._w(NULL)
        if v is True:
            return self._w(TRUE)
        if v is False:
            return self._w(FALSE)
        if isinstance(v, int):
            return self._write_int(v)
        if isinstance(v, float):
            if v == 0.0:
                return self._w(DOUBLE_0)
            if v == 1.0:
                return self._w(DOUBLE_1)
            self._w(DOUBLE)
            return self.buf.write(_struct.pack(">d", v)) and None
        if isinstance(v, Keyword):
            return self._write_named(KEY, str(v))
        if isinstance(v, Symbol):
            return self._write_named(SYM, str(v))
        if isinstance(v, str):
            return self._write_string(v)
        if isinstance(v, (bytes, bytearray)):
            b = bytes(v)
            if len(b) <= 7:
                self._w(BYTES_PACKED_START + len(b))
            else:
                self._w(BYTES)
                self._write_int(len(b))
            return self.buf.write(b) and None
        if isinstance(v, datetime.datetime):
            self._w(INST)
            if v.tzinfo is None:
                # Codec convention: naive datetimes are UTC wall-clock,
                # so fields round-trip identically through the UTC-aware
                # value the reader returns, independent of host tz.
                v = v.replace(tzinfo=datetime.timezone.utc)
            return self._write_int(int(v.timestamp() * 1000))
        if isinstance(v, (set, frozenset)):
            self._w(SET)
            return self._write_list(sorted(v, key=repr))
        if isinstance(v, dict):
            self._w(MAP)
            kvs: list = []
            for k, val in v.items():
                kvs.append(k)
                kvs.append(val)
            return self._write_list(kvs)
        if isinstance(v, (list, tuple)):
            return self._write_list(list(v))
        if isinstance(v, TaggedValue):
            self._w(STRUCT)
            self.write(v.tag)
            self._write_int(len(v.values))
            for x in v.values:
                self.write(x)
            return None
        raise FressianError(f"can't write {type(v)}")

    def _write_int(self, n: int) -> None:
        """Packed ints per the spec's zones (high bits in code - bias)."""
        if -1 <= n <= 63:
            return self._w(n & 0xFF)
        for shift, bias, lo in ((8, 0x50, 0x40), (16, 0x68, 0x60),
                                (24, 0x72, 0x70), (32, 0x76, 0x74),
                                (40, 0x7A, 0x78), (48, 0x7E, 0x7C)):
            high = n >> shift
            code = bias + high
            hi_code = {0x50: 0x5F, 0x68: 0x6F, 0x72: 0x73, 0x76: 0x77,
                       0x7A: 0x7B, 0x7E: 0x7F}[bias]
            if lo <= code <= hi_code:
                self._w(code)
                rest = n & ((1 << shift) - 1)
                return self.buf.write(
                    rest.to_bytes(shift // 8, "big")) and None
        self._w(INT)
        self.buf.write(_struct.pack(">q", n))

    def _write_string(self, s: str) -> None:
        b = s.encode("utf-8")
        if len(b) <= 7:
            self._w(STRING_PACKED_START + len(b))
        else:
            self._w(STRING)
            self._write_int(len(b))
        self.buf.write(b)

    def _write_named(self, code: int, name: str) -> None:
        """Keyword/symbol: code + ns + name, with priority caching of
        the whole form the way the JVM writer caches them."""
        key = (code, name)
        if key in self.priority_cache:
            idx = self.priority_cache[key]
            if idx < 0x9F - PRIORITY_CACHE_PACKED_START:
                return self._w(PRIORITY_CACHE_PACKED_START + idx)
            self._w(GET_PRIORITY_CACHE)
            return self._write_int(idx)
        idx = len(self.priority_cache)
        self.priority_cache[key] = idx
        self._w(PUT_PRIORITY_CACHE)
        self._w(code)
        if "/" in name:
            ns, nm = name.split("/", 1)
            self.write(ns)
            self.write(nm)
        else:
            self.write(None)
            self.write(name)

    def _write_list(self, items: list) -> None:
        if len(items) <= 7:
            self._w(LIST_PACKED_START + len(items))
        else:
            self._w(LIST)
            self._write_int(len(items))
        for x in items:
            self.write(x)


def loads(data: bytes) -> Any:
    """Read the first object from fressian bytes."""
    return Reader(data).read()


def loads_all(data: bytes) -> list:
    r = Reader(data)
    out = []
    while not r.at_eof():
        try:
            out.append(r.read())
        except FressianError as e:
            if "footer" in str(e):
                break
            raise
    return out


def dumps(v: Any) -> bytes:
    w = Writer()
    w.write(v)
    return w.getvalue()


def load_test(path) -> Any:
    """Load a test.fressian artifact (store.clj:181-193's load)."""
    with open(path, "rb") as f:
        return loads(f.read())
