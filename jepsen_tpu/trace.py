"""jepsen_tpu.trace — run-wide tracing + metrics (zero dependencies).

PR 1's phase attribution was a one-off: a mutable `phases` dict
threaded through `parallel.check_bucketed_async` plus hand-rolled
`time.perf_counter()` spans in bench.py, visible only to benches.
This module makes every run self-attributing:

  * `span("pack", bucket=i)` — nestable wall-clock spans recorded into
    a thread-safe per-run `Tracer` (one Chrome-trace track per thread);
  * a metrics registry — counters (`buckets_dispatched`,
    `native_fallback`, `pad_waste_cells`), gauges (`inflight_depth`)
    and histograms (per-phase durations land in `phase.<name>`);
  * Chrome trace-event JSON export (`trace.json`, loadable in Perfetto
    or chrome://tracing) and a `metrics.json` summary — `store.save_2`
    persists both next to `history.edn` in every run directory;
  * device-side kernel timing: the sweep records each dispatch's
    enqueue→`jax.block_until_ready` window on a synthetic "device"
    track (`device_complete`), and `jax_profile_session` optionally
    wraps a run in a real `jax.profiler` capture behind
    `JEPSEN_TPU_JAX_PROFILE=1`.

Since the trace fabric (ISSUE 10) the tracer is also CROSS-PROCESS:
ingest pool workers get their own `Tracer` seeded with the parent's
trace id plus a monotonic clock handshake (`worker_ctx` /
`ensure_worker_tracer`), spool their spans to a per-worker
`trace-<pid>.jsonl` in the store (flushed per encode task, torn tails
skipped on load exactly like the VerdictJournal), and ship a compact
digest back through the existing einfo descriptor path.
`merge_traces` folds the spools into one Chrome trace whose events
carry each contributing process's REAL pid — one process track per
worker, Perfetto-ready — and the attribution report
(jepsen_tpu/obs/attribution.py) walks that merged timeline.

`JEPSEN_TPU_TRACE=0` (or `--no-trace`) swaps in the `NullTracer`:
no file is written (no worker spool files either) and a disabled span
costs well under a microsecond — the dp8-efficiency floor is
unaffected. The module imports nothing but the stdlib (plus the
stdlib-only `gates` registry); `jax` is touched only inside an
explicitly enabled profiler session.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
import uuid
from pathlib import Path

from . import gates

log = logging.getLogger(__name__)

#: The declared metric-name registry. The metrics surface is keyed by
#: string, so a typo silently forks a series; lint rule JT-TRACE-002
#: checks every `counter("...")`/`gauge`/`histogram` literal in the
#: package against this set (and that the KIND matches), so a new
#: metric must be declared here before it can ship.
DECLARED_METRICS: dict[str, frozenset] = {
    "counters": frozenset({
        "bucket_splits", "buckets_dispatched", "buckets_resolved",
        "buffers_donated", "cache_hits", "cache_misses",
        "compile_cache_hits", "compile_cache_misses", "cost_records",
        "donated_bytes", "fleet_failovers", "fleet_fences",
        "fleet_replayed_verdicts", "fleet_spills", "h2d_bytes",
        "kernel.cyclic_histories", "kernel.stats_records",
        "native_fallback", "oom_retries", "pad_waste_cells",
        "planner.cold_starts", "planner.decisions",
        "planner.fallbacks", "planner.pred_checked",
        "quarantined", "runs_verdicted",
        "serve_backpressure", "serve_folds", "serve_replays",
        "serve_requests", "serve_verdicts", "shm_bytes",
        "shm_stale_reclaimed", "sidecar_upgrades", "split.native",
        "split.python", "warm_copy_bytes", "watchdog_timeouts",
        "worker_spans",
    }),
    "gauges": frozenset({"donate_slots_inflight", "fleet_daemons_live",
                         "fleet_epoch", "hbm_device_bytes",
                         "hbm_modeled_bytes", "inflight_depth",
                         "planner.pred_err_permille",
                         "reorder_depth", "resident_executables",
                         "runs_total", "serve_pending",
                         "serve_tenants"}),
    "histograms": frozenset({"bucket_cells",
                             "fleet_failover_ms",
                             "kernel.backtracks",
                             "kernel.closure_rounds", "kernel.edges",
                             "kernel.margin", "kernel.scc_max",
                             "serve_fold_histories",
                             "serve_latency_ms"}),
}

#: Sanctioned dynamic-name families: an f-string metric name must
#: start with one of these (`phase.<key>`, `device.<kernel>`,
#: `native_fallback.<component>`, `worker.<stage>` — the per-task
#: stage-seconds digests ingest relays from pool workers;
#: `planner.<lever>` — per-lever modeled-decision counters).
METRIC_PREFIXES = ("phase.", "device.", "native_fallback.", "worker.",
                   "serve.", "planner.", "fleet.")

#: Synthetic tid for the device track (real thread idents are pthread
#: addresses, nowhere near this; named tracks count down from here).
DEVICE_TID = 2 ** 31 - 1

_MLOCK = threading.Lock()   # shared metric read-modify-write lock


def atomic_write_text(path, text: str) -> Path:
    """Temp-file + `os.rename` persistence for trace.json/metrics.json
    — the torn-tail discipline VerdictJournal already has. A crash
    mid-flush must leave the previous complete artifact (or nothing),
    never a truncated JSON that poisons later tooling."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(f".{p.name}.{os.getpid()}.tmp")
    try:
        tmp.write_text(text)
        os.replace(tmp, p)
    except BaseException:
        try:
            tmp.unlink(missing_ok=True)
        except OSError:
            pass
        raise
    return p


def enabled() -> bool:
    """The JEPSEN_TPU_TRACE gate (default on)."""
    return gates.get("JEPSEN_TPU_TRACE")


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with _MLOCK:
            self.value += n


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def set(self, v) -> None:
        self.value = v


class Histogram:
    """Summary-stat histogram: count/sum/min/max plus powers-of-two
    magnitude buckets, so per-phase distributions export compactly
    without retaining every observation."""

    __slots__ = ("count", "total", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.buckets: dict[int, int] = {}   # floor(log2(v)) -> count

    def observe(self, v: float) -> None:
        with _MLOCK:
            self.count += 1
            self.total += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            b = math.floor(math.log2(v)) if v > 0 else 0
            self.buckets[b] = self.buckets.get(b, 0) + 1

    def summary(self) -> dict:
        return {"count": self.count, "sum": self.total,
                "min": self.min, "max": self.max,
                "mean": (self.total / self.count) if self.count else None,
                "log2_buckets": {str(k): v for k, v in
                                 sorted(self.buckets.items())}}


class _NullMetric:
    """Counter/gauge/histogram stand-in on the disabled path."""

    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def observe(self, v) -> None:
        pass


_NULL_METRIC = _NullMetric()


# ---------------------------------------------------------------------------
# Span context managers
# ---------------------------------------------------------------------------

class _SpanCM:
    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer._complete(self._name, self._t0, time.perf_counter(),
                               self._cat, self._args)
        return False


class _NullCM:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CM = _NullCM()


# ---------------------------------------------------------------------------
# Tracers
# ---------------------------------------------------------------------------

class NullTracer:
    """The JEPSEN_TPU_TRACE=0 tracer: every operation is a no-op (a
    disabled span costs one function call + the singleton context
    manager — well under 1µs), EXCEPT `phase`, which still returns the
    measured duration so `phases`-dict accounting stays exact with
    tracing off."""

    enabled = False
    run = None
    scope = "run"
    trace_id = None
    spool_dir = None
    pid = None

    def span(self, name: str, **args):
        return _NULL_CM

    def rel_us(self, t_perf: float) -> float:
        return 0.0

    def phase(self, key: str, t0: float) -> float:
        return time.perf_counter() - t0

    def device_complete(self, name, t0, t1=None, **args):
        pass

    def add_span(self, name, t0, t1, track=None, clock="perf", **args):
        pass

    def instant(self, name, track=None, **args):
        pass

    def counter(self, name: str):
        return _NULL_METRIC

    def gauge(self, name: str):
        return _NULL_METRIC

    def histogram(self, name: str):
        return _NULL_METRIC

    def phase_totals(self) -> dict:
        return {}

    def export(self, path) -> None:
        return None

    def export_merged(self, path, spool_dir=None) -> None:
        return None

    def export_metrics(self, path) -> None:
        return None


class Tracer:
    """A per-run trace + metrics recorder. Thread-safe: spans from any
    thread land on that thread's own track (event append is a single
    GIL-atomic list.append; metric updates take the shared lock)."""

    enabled = True

    def __init__(self, run: str | None = None,
                 max_events: int | None = None, scope: str = "run"):
        self.run = run
        # "run": a single test run — store.save_2 persists it into the
        # run dir. "sweep": spans many runs (analyze-store); per-run
        # persistence must NOT export it (each run dir would get the
        # whole sweep's events, re-serialized O(runs) times) — the
        # sweep owner exports once at the end.
        self.scope = scope
        # The RECORDING process's pid, captured at construction — the
        # Chrome export stamps events with this, never with the
        # exporter's os.getpid() at export time (a tracer exported
        # post-fork, or folded into another process's merge, must keep
        # attributing its events to the process that recorded them).
        self.pid = os.getpid()
        # Sweep-unique id: worker spools record it, and merge_traces
        # folds only spools carrying THIS id (a stale spool from a
        # previous sweep in the same store never contaminates).
        self.trace_id = uuid.uuid4().hex[:16]
        # Where pool workers spool their spans (trace-<pid>.jsonl);
        # None = workers don't spool. The sweep owner (analyze-store)
        # points this at the store base.
        self.spool_dir = None
        # Bounded event buffer: a day-long soak (or an embedded caller
        # that never rotates the tracer) must not OOM the process it
        # observes — 200k events is ~50MB retained worst case and far
        # more than a Perfetto view needs. Overflow is COUNTED
        # (dropped_events in metrics.json), never silent; phase totals
        # and metrics keep accumulating past the cap.
        if max_events is None:
            # malformed env must not sink the run: the gate accessor
            # falls back to the declared default on parse failure
            max_events = gates.get("JEPSEN_TPU_TRACE_MAX_EVENTS")
        self._max_events = max_events
        self._dropped = 0
        self._origin = time.perf_counter()
        # CLOCK_MONOTONIC -> perf_counter offset, for external spans
        # measured with time.monotonic (ingest pool workers)
        self._mono_off = time.perf_counter() - time.monotonic()
        self._events: list[dict] = []
        self._threads: dict[int, str] = {}
        self._tracks: dict[str, int] = {"device": DEVICE_TID}
        # per named-track lane ends (µs): concurrently-open windows
        # (two in-flight buckets, parallel pool workers) spill to
        # "name-2", "name-3"… so no single tid ever carries partially
        # overlapping X events (which Chrome/Perfetto mis-nest)
        self._lanes: dict[str, list[float]] = {}
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}
        self._phase_totals: dict[str, float] = {}

    # -- recording --------------------------------------------------------

    def span(self, name: str, *, cat: str = "span", **args):
        """A nestable wall-clock span: `with tracer.span("pack",
        bucket=i): ...` records one complete ("X") event on the calling
        thread's track."""
        return _SpanCM(self, name, cat, args or None)

    def _room(self) -> bool:
        if len(self._events) >= self._max_events:
            self._dropped += 1
            return False
        return True

    def _complete(self, name: str, t0: float, t1: float, cat: str,
                  args) -> None:
        if not self._room():
            return
        tid = threading.get_ident()
        if tid not in self._threads:
            self._threads[tid] = threading.current_thread().name
        self._events.append({
            "name": name, "cat": cat, "ph": "X", "tid": tid,
            "ts": (t0 - self._origin) * 1e6,
            "dur": max(0.0, (t1 - t0) * 1e6),
            **({"args": args} if args else {})})

    def phase(self, key: str, t0: float) -> float:
        """Record a completed phase span started at perf_counter() time
        `t0`, accumulate its per-phase total + histogram, and return
        the duration — the adapter `parallel._acc_phase` rides."""
        t1 = time.perf_counter()
        dt = t1 - t0
        self._complete(key, t0, t1, "phase", None)
        with _MLOCK:
            self._phase_totals[key] = self._phase_totals.get(key, 0.0) + dt
        self.histogram(f"phase.{key}").observe(dt)
        return dt

    def device_complete(self, name: str, t0: float,
                        t1: float | None = None, **args) -> None:
        """A device-track event: the dispatch-enqueue →
        block_until_ready window of one kernel dispatch (t0/t1 in
        perf_counter time; t1 defaults to now)."""
        if t0 is None:
            return
        t1 = time.perf_counter() if t1 is None else t1
        if self._room():
            ts = (t0 - self._origin) * 1e6
            dur = max(0.0, (t1 - t0) * 1e6)
            self._events.append({
                "name": name, "cat": "device", "ph": "X",
                "tid": self._laned_tid("device", ts, ts + dur),
                "ts": ts, "dur": dur,
                **({"args": args} if args else {})})
        self.histogram(f"device.{name}").observe(t1 - t0)

    def add_span(self, name: str, t0: float, t1: float,
                 track: str | None = None, clock: str = "perf",
                 **args) -> None:
        """Record an externally measured span — e.g. an ingest pool
        worker's parse window, taken with time.monotonic in another
        process (`clock="monotonic"` converts)."""
        if clock == "monotonic":
            t0 += self._mono_off
            t1 += self._mono_off
        if track is None:
            self._complete(name, t0, t1, "span", args or None)
            return
        if not self._room():
            return
        ts = (t0 - self._origin) * 1e6
        dur = max(0.0, (t1 - t0) * 1e6)
        self._events.append({
            "name": name, "cat": "span", "ph": "X",
            "tid": self._laned_tid(track, ts, ts + dur),
            "ts": ts, "dur": dur,
            **({"args": args} if args else {})})

    def instant(self, name: str, track: str | None = None,
                **args) -> None:
        """A zero-duration mark ("i" event) — fault-path punctuation
        (watchdog fired, worker lost) that has a moment but no
        meaningful span. Lands on the calling thread's track, or a
        named track when given."""
        if not self._room():
            return
        tid = threading.get_ident()
        if track is not None:
            tid = self._track_tid(track)
        elif tid not in self._threads:
            self._threads[tid] = threading.current_thread().name
        self._events.append({
            "name": name, "cat": "instant", "ph": "i", "s": "t",
            "tid": tid,
            "ts": (time.perf_counter() - self._origin) * 1e6,
            **({"args": args} if args else {})})

    def _track_tid(self, name: str) -> int:
        with _MLOCK:
            tid = self._tracks.get(name)
            if tid is None:
                tid = DEVICE_TID - len(self._tracks)
                self._tracks[name] = tid
            return tid

    def _laned_tid(self, base: str, ts_us: float, end_us: float) -> int:
        """The tid for a window on named track `base`, spilling
        overlapping windows to numbered sibling lanes."""
        with _MLOCK:
            lanes = self._lanes.setdefault(base, [])
            for i, lane_end in enumerate(lanes):
                if lane_end <= ts_us:
                    lanes[i] = end_us
                    break
            else:
                i = len(lanes)
                lanes.append(end_us)
        return self._track_tid(base if i == 0 else f"{base}-{i + 1}")

    # -- metrics ----------------------------------------------------------

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with _MLOCK:
                c = self._counters.setdefault(name, Counter())
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with _MLOCK:
                g = self._gauges.setdefault(name, Gauge())
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            with _MLOCK:
                h = self._hists.setdefault(name, Histogram())
        return h

    def phase_totals(self) -> dict[str, float]:
        """Accumulated seconds per phase key — the tracer-derived
        source for bench.py's north-star `phases` block (same keys,
        same semantics as the legacy dict)."""
        with _MLOCK:
            return dict(self._phase_totals)

    # -- export -----------------------------------------------------------

    def rel_us(self, t_perf: float) -> float:
        """A perf_counter time as µs on this tracer's export timeline
        — the public window-conversion callers (bench attribution)
        use instead of reaching into `_origin`."""
        return (t_perf - self._origin) * 1e6

    def origin_mono(self) -> float:
        """This tracer's ts=0 expressed in CLOCK_MONOTONIC seconds —
        the reference point worker spools (recorded with
        time.monotonic, which is system-wide on Linux) align to."""
        return self._origin - self._mono_off

    def chrome_events(self) -> list[dict]:
        """The Chrome trace-event list: one metadata-named track per
        recording thread plus the synthetic device/external tracks;
        every timed event is a complete ("X") event, sorted by ts.
        Metadata and events carry the RECORDING process's pid
        (`self.pid`), and an event that already carries an explicit
        "pid" (a foreign-process event folded in) keeps it — the
        multi-process merge depends on per-event pids never being
        overwritten with the exporter's."""
        pid = self.pid
        ev: list[dict] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": self.run or "jepsen-tpu"}}]
        for tid, tname in sorted(self._threads.items()):
            ev.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
        for tname, tid in sorted(self._tracks.items()):
            ev.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
        ev.extend({**e, "pid": e.get("pid", pid)}
                  for e in sorted(list(self._events),
                                  key=lambda e: e["ts"]))
        return ev

    def export(self, path) -> Path:
        """Write Chrome trace-event JSON (Perfetto / chrome://tracing
        loadable) to `path`; returns the path."""
        return atomic_write_text(
            path, json.dumps({"traceEvents": self.chrome_events(),
                              "displayTimeUnit": "ms"}))

    def export_merged(self, path, spool_dir=None) -> Path:
        """`export`, but with every matching worker spool under
        `spool_dir` (default: this tracer's spool_dir) folded in as
        its own per-process pid track (`merge_traces`). Falls back to
        a plain export when there is nothing to merge."""
        return atomic_write_text(
            path, json.dumps({
                "traceEvents": merge_traces(self, spool_dir),
                "displayTimeUnit": "ms"}))

    def metrics_dict(self) -> dict:
        with _MLOCK:
            return {
                "counters": {k: c.value for k, c in
                             sorted(self._counters.items())},
                "gauges": {k: g.value for k, g in
                           sorted(self._gauges.items())},
                "histograms": {k: h.summary() for k, h in
                               sorted(self._hists.items())},
                "phase_totals_secs": {k: round(v, 6) for k, v in
                                      sorted(self._phase_totals.items())},
                "dropped_events": self._dropped,
            }

    def export_metrics(self, path) -> Path:
        return atomic_write_text(
            path, json.dumps(self.metrics_dict(), indent=2))


# ---------------------------------------------------------------------------
# The current (per-run) tracer
# ---------------------------------------------------------------------------

_NULL = NullTracer()
_current: Tracer | NullTracer | None = None


def get_current() -> Tracer | NullTracer:
    """The process's current tracer, lazily built from the env gate."""
    t = _current
    if t is None:
        return _init()
    return t


def _init() -> Tracer | NullTracer:
    global _current
    _current = Tracer() if enabled() else _NULL
    return _current


def set_current(t: Tracer | NullTracer | None):
    """Install `t` as the current tracer (None = re-init lazily)."""
    global _current
    _current = t
    return _current


def reset() -> None:
    """Drop the current tracer; the next use re-reads the env gate."""
    set_current(None)


def fresh_run(run: str | None = None,
              scope: str = "run") -> Tracer | NullTracer:
    """Install a FRESH per-run tracer (honoring the env gate) — called
    at the top of core.run / analyze sweeps / bench rounds so each
    run's trace.json covers exactly that run. scope="sweep" marks a
    tracer spanning many runs: store.save_2 then skips per-run export
    and the sweep owner writes the one store-level artifact."""
    return set_current(Tracer(run=run, scope=scope)
                       if enabled() else _NULL)


def span(name: str, **args):
    """`with trace.span("pack", bucket=i): ...` on the current tracer.
    Disabled path short-circuits to the shared no-op context manager —
    the <1µs/span contract the tight-loop smoke test pins."""
    t = _current
    if t is None:
        t = _init()
    if not t.enabled:
        return _NULL_CM
    return t.span(name, **args)


def counter(name: str):
    return get_current().counter(name)


def gauge(name: str):
    return get_current().gauge(name)


def histogram(name: str):
    return get_current().histogram(name)


# ---------------------------------------------------------------------------
# The cross-process trace fabric: per-worker span spools + merge.
#
# Pool workers are separate (spawned) processes: their tracers were
# process-local and silently discarded, so every worker-side second of
# a pooled sweep was invisible to trace.json — only counters crossed
# the pipe. Now the parent hands each worker a tiny context
# (`worker_ctx`: trace id + spool dir + a monotonic send stamp); the
# worker installs its own Tracer (`ensure_worker_tracer`), records
# spans normally, and `flush_worker_spool` (called per encode task)
# appends them to `<spool_dir>/trace-<pid>.jsonl` — one JSON line per
# event, flushed as written, torn tails skipped on load — and returns
# a compact digest the parent folds into its own metrics. Timestamps
# are raw CLOCK_MONOTONIC seconds: monotonic is system-wide on Linux,
# so `merge_traces` aligns them against the parent tracer's
# `origin_mono()` with no cross-clock arithmetic; the send/recv
# handshake recorded in the spool's meta line bounds the residual
# alignment error (it can only be scheduling latency, not clock skew).
# ---------------------------------------------------------------------------

def merge_intervals(spans):
    """Sorted union of (start, end) wall-clock pairs — THE interval
    merge shared by `ingest.overlap_seconds` (the measured-overlap
    contract) and the attribution report's stage unions, so the two
    can never disagree about the same timeline."""
    out: list = []
    for s, e in sorted(spans):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def overlap_seconds(spans_a, spans_b) -> float:
    """Total seconds where some span in `a` intersects some span in
    `b` (both lists of (start, end) pairs). Each side is merged first
    so double-counting can't inflate the number."""
    if not spans_a or not spans_b:
        return 0.0
    total, bi = 0.0, 0
    b = merge_intervals(spans_b)
    for s, e in merge_intervals(spans_a):
        while bi < len(b) and b[bi][1] <= s:
            bi += 1
        j = bi
        while j < len(b) and b[j][0] < e:
            total += max(0.0, min(e, b[j][1]) - max(s, b[j][0]))
            j += 1
    return total


#: Worker spool naming — this module is the ONLY place the convention
#: exists (lint rule JT-TRACE-004 flags the literal anywhere else).
SPOOL_PREFIX = "trace-"
SPOOL_VERSION = 1


def worker_trace_enabled() -> bool:
    """The JEPSEN_TPU_WORKER_TRACE gate (default on; moot when
    JEPSEN_TPU_TRACE=0 — no tracer, no spools)."""
    return gates.get("JEPSEN_TPU_WORKER_TRACE")


def spool_path(spool_dir, pid: int) -> Path:
    return Path(spool_dir) / f"{SPOOL_PREFIX}{pid}.jsonl"


def iter_spools(spool_dir):
    """The worker spool files under a directory, sorted."""
    return sorted(Path(spool_dir).glob(f"{SPOOL_PREFIX}*.jsonl"))


def clean_spools(spool_dir) -> int:
    """Remove stale worker spools (sweep start: spools are per-sweep
    derived artifacts keyed by trace id; old ones only cost merge
    filtering and disk). Returns the count removed."""
    n = 0
    try:
        for p in iter_spools(spool_dir):
            try:
                p.unlink()
                n += 1
            except OSError:
                pass
    except OSError:
        pass
    return n


def worker_ctx() -> dict | None:
    """The context the parent hands each pool worker, or None when
    workers should not spool (tracing off, worker tracing gated off,
    or no spool dir registered on the current tracer) — None costs
    the worker nothing (`ensure_worker_tracer` returns immediately)."""
    t = get_current()
    if not t.enabled or t.spool_dir is None \
            or not worker_trace_enabled():
        return None
    return {"trace_id": t.trace_id, "dir": str(t.spool_dir),
            "t_send": time.monotonic()}


#: Worker-process spool state: {"f": file|None, "trace_id": str,
#: "thr": set of tids whose names were already spooled, "tracer": T}.
_wspool: dict | None = None


def ensure_worker_tracer(tctx: dict | None) -> None:
    """Install this worker process's spooling tracer (idempotent per
    trace id). Called at the top of every pooled encode task; a None
    context (or tracing disabled in the inherited env) is a no-op, so
    the JEPSEN_TPU_TRACE=0 path creates no tracer and no file."""
    global _wspool
    if not tctx or not enabled():
        # not spooling: in a POOL WORKER, park the NullTracer so the
        # per-task spans don't accumulate in an enabled tracer's
        # buffer nobody ever flushes or exports (up to _max_events
        # retained per worker over a long sweep, pure waste). Only in
        # a real child process — an in-process caller (tests, the
        # serial path) must keep its own current tracer.
        if _wspool is None:
            import multiprocessing as mp
            if mp.parent_process() is not None:
                set_current(_NULL)
        return
    ws = _wspool
    if ws is not None and ws["trace_id"] == tctx["trace_id"]:
        set_current(ws["tracer"])
        return
    close_worker_spool()
    tr = Tracer(run=f"ingest-worker-{os.getpid()}", scope="worker")
    f = None
    try:
        p = spool_path(tctx["dir"], os.getpid())
        f = open(p, "w")
        f.write(json.dumps({
            "k": "meta", "v": SPOOL_VERSION, "pid": os.getpid(),
            "trace_id": tctx["trace_id"], "proc": "ingest-worker",
            # the clock handshake: t_recv - t_send bounds the spawn/
            # queue latency; on a shared CLOCK_MONOTONIC (Linux) the
            # alignment error is zero and this is pure diagnostics
            "t_send": tctx.get("t_send"),
            "t_recv": time.monotonic()}) + "\n")
        f.flush()
    except OSError:
        log.debug("worker spool open failed", exc_info=True)
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        f = None   # spans still feed the einfo digest
    _wspool = {"f": f, "trace_id": tctx["trace_id"], "thr": set(),
               "tracer": tr}
    set_current(tr)


def flush_worker_spool() -> dict | None:
    """Spool every event recorded since the last flush (one JSON line
    each, flushed — the torn-tail discipline) and return the compact
    digest the parent aggregates: span count + per-name stage seconds.
    The flushed events are dropped from the in-memory buffer, so a
    long sweep's worker holds one task's events, not the sweep's."""
    ws = _wspool
    if ws is None:
        return None
    tr: Tracer = ws["tracer"]
    evs = list(tr._events)
    tr._events.clear()
    om = tr.origin_mono()
    stage: dict[str, float] = {}
    lines: list[dict] = []
    for tid, name in list(tr._threads.items()):
        if tid not in ws["thr"]:
            ws["thr"].add(tid)
            lines.append({"k": "thr", "tid": tid, "name": name})
    for name, tid in list(tr._tracks.items()):
        if tid not in ws["thr"]:
            ws["thr"].add(tid)
            lines.append({"k": "thr", "tid": tid, "name": name})
    spans = 0
    for e in evs:
        t0 = om + e["ts"] / 1e6
        rec = {"k": "ev", "name": e["name"], "cat": e["cat"],
               "ph": e["ph"], "tid": e["tid"], "t0": round(t0, 6)}
        if e["ph"] == "X":
            spans += 1
            rec["t1"] = round(t0 + e["dur"] / 1e6, 6)
            stage[e["name"]] = stage.get(e["name"], 0.0) \
                + e["dur"] / 1e6
        if e.get("args"):
            rec["args"] = e["args"]
        lines.append(rec)
    if ws["f"] is not None and lines:
        try:
            ws["f"].write("".join(json.dumps(ln) + "\n"
                                  for ln in lines))
            ws["f"].flush()
        except OSError:
            log.debug("worker spool append failed", exc_info=True)
    return {"spans": spans,
            "stage_secs": {k: round(v, 6) for k, v in stage.items()}}


def close_worker_spool() -> None:
    """Drop the worker spool state (tests, or a worker re-seeded for a
    different sweep)."""
    global _wspool
    ws = _wspool
    _wspool = None
    if ws is not None and ws["f"] is not None:
        try:
            ws["f"].close()
        except OSError:
            pass


def load_spool(path):
    """One spool file -> (meta | None, {tid: name}, [event dicts]).
    Unparseable or incomplete lines — the crash-torn tail — are
    skipped, mirroring VerdictJournal.load; a spool whose meta line
    never landed returns meta None (the merge then ignores it)."""
    meta = None
    threads: dict[int, str] = {}
    events: list[dict] = []
    try:
        lines = Path(path).read_text().splitlines()
    except OSError:
        return None, threads, events
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict):
            continue
        k = rec.get("k")
        if k == "meta" and meta is None:
            if "pid" in rec and "trace_id" in rec:
                meta = rec
        elif k == "thr":
            try:
                threads[int(rec["tid"])] = str(rec["name"])
            except (KeyError, TypeError, ValueError):
                continue
        elif k == "ev":
            if "name" in rec and "t0" in rec \
                    and isinstance(rec["t0"], (int, float)):
                events.append(rec)
    return meta, threads, events


def merge_traces(tracer, spool_dir=None) -> list[dict]:
    """The merged Chrome trace-event list: the parent tracer's own
    events plus every worker spool under `spool_dir` (default: the
    tracer's registered spool_dir) whose trace id matches — each
    worker becomes its own REAL-pid process track with process/thread
    name metadata, and its monotonic timestamps align to the parent's
    timeline via `origin_mono()` (clamped at 0: a span that somehow
    predates the parent origin must not produce a negative ts Chrome
    renders at the epoch). Metadata events lead, timed events follow
    sorted by ts — the same golden shape as a single-process export."""
    if not getattr(tracer, "enabled", False):
        return []
    evs = tracer.chrome_events()
    d = spool_dir if spool_dir is not None \
        else getattr(tracer, "spool_dir", None)
    if d is None:
        return evs
    meta_evs = [e for e in evs if e["ph"] == "M"]
    x_evs = [e for e in evs if e["ph"] != "M"]
    om = tracer.origin_mono()
    try:
        spools = iter_spools(d)
    except OSError:
        spools = []
    for p in spools:
        meta, threads, wevents = load_spool(p)
        if meta is None or meta.get("trace_id") != tracer.trace_id:
            continue
        try:
            pid = int(meta["pid"])
        except (TypeError, ValueError):
            continue
        meta_evs.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{meta.get('proc', 'worker')} {pid}"}})
        for tid, name in sorted(threads.items()):
            meta_evs.append({"name": "thread_name", "ph": "M",
                             "pid": pid, "tid": tid,
                             "args": {"name": name}})
        for w in wevents:
            ts = max(0.0, (float(w["t0"]) - om) * 1e6)
            e = {"name": w["name"], "cat": w.get("cat", "span"),
                 "ph": w.get("ph", "X"), "pid": pid,
                 "tid": w.get("tid", 0), "ts": ts}
            if e["ph"] == "X":
                t1 = float(w.get("t1", w["t0"]))
                e["dur"] = max(0.0, (t1 - float(w["t0"])) * 1e6)
            else:
                e["s"] = "t"
            if w.get("args"):
                e["args"] = w["args"]
            x_evs.append(e)
    x_evs.sort(key=lambda e: e["ts"])
    return meta_evs + x_evs


# ---------------------------------------------------------------------------
# Cross-HOST trace merge (analyze-store --mesh): per-shard exports.
#
# The worker-spool fabric above merges per-PROCESS spools on one host;
# a mesh sweep spans hosts, whose processes cannot share a spool
# directory's lifecycle (concurrent shards must not clean each other's
# live spools) and whose pids collide. Each shard therefore exports
# its own ALREADY-MERGED Chrome event list (parent + its workers) as
# `<store>/trace-shard<k>.json`, stamped with the tracer's
# CLOCK_MONOTONIC origin; the coordinator folds the shard files into
# one cross-host trace.json, offsetting each shard's timestamps to the
# earliest origin and remapping pids into per-shard strides so tracks
# never collide. On one machine (the simulated-mesh harness) monotonic
# is system-wide, so the merged timeline is exact; across real hosts
# the residual error is clock skew between their monotonic clocks —
# fine for attribution (per-shard shares use each shard's own events)
# and for eyeballing, not for cross-host causality.
# ---------------------------------------------------------------------------

#: Per-shard merged-trace artifact naming — owned here like the spool
#: convention (note: `.json`, not a `.jsonl` spool).
SHARD_TRACE_PREFIX = "trace-shard"

#: pid stride separating shard tracks in the merged trace: real pids
#: stay readable modulo the stride, and two hosts' identical pids
#: can't fold into one track.
_SHARD_PID_STRIDE = 1 << 24


def shard_trace_path(store_base, shard: int) -> Path:
    return Path(store_base) / f"{SHARD_TRACE_PREFIX}{shard}.json"


def shard_spool_dir(store_base, shard: int) -> Path:
    """Worker-spool subdirectory for ONE mesh shard. Spool files are
    keyed by pid, and two HOSTS' pool workers can share a pid (small
    container pid namespaces), so concurrent shards spooling into the
    store root would truncate each other's live files — each shard
    spools into (and cleans, at its own sweep start) its own
    subdirectory instead; the coordinator removes the dirs after a
    fully-covered merge."""
    return Path(store_base) / f"spool-shard{shard}"


def export_shard_trace(tracer, store_base, shard: int, n_shards: int,
                       events: list | None = None) -> Path:
    """Write one shard's merged Chrome events (its own spans + its
    worker spools) as `trace-shard<k>.json`, carrying the shard
    geometry and the tracer's monotonic origin for the cross-host
    merge."""
    if events is None:
        events = merge_traces(tracer, store_base)
    return atomic_write_text(
        shard_trace_path(store_base, shard),
        json.dumps({"traceEvents": events, "displayTimeUnit": "ms",
                    "shard": shard, "shards": n_shards,
                    "origin_mono": tracer.origin_mono()}))


def load_shard_trace(path) -> dict | None:
    """One shard trace file -> its dict, or None on any miss/parse
    failure (a lost shard's file simply never landed)."""
    try:
        v = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError):
        return None
    return v if isinstance(v, dict) and "traceEvents" in v else None


def merge_shard_traces(store_base, shards):
    """Fold every present `trace-shard<k>.json` under `store_base`
    into one cross-host Chrome event list. Returns (merged events,
    {shard: that shard's own UNSHIFTED events}) — the per-shard map
    feeds the attribution report's per-shard stage shares, which must
    be computed on each shard's own timeline."""
    per_shard: dict[int, list] = {}
    loads = []
    for k in shards:
        d = load_shard_trace(shard_trace_path(store_base, k))
        if d is None:
            continue
        per_shard[k] = d["traceEvents"]
        loads.append((k, d))
    if not loads:
        return [], per_shard
    origins = [d["origin_mono"] for _k, d in loads
               if isinstance(d.get("origin_mono"), (int, float))]
    o0 = min(origins) if origins else 0.0
    meta_evs: list[dict] = []
    x_evs: list[dict] = []
    for k, d in loads:
        om = d.get("origin_mono")
        shift_us = (om - o0) * 1e6 \
            if isinstance(om, (int, float)) else 0.0
        for e in d["traceEvents"]:
            if not isinstance(e, dict):
                continue
            e = dict(e)
            try:
                e["pid"] = k * _SHARD_PID_STRIDE + int(e.get("pid", 0))
            except (TypeError, ValueError):
                e["pid"] = k * _SHARD_PID_STRIDE
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    args = dict(e.get("args") or {})
                    # the host id rides the track name: every process
                    # track of shard k reads "shard<k>:<name>"
                    args["name"] = f"shard{k}:{args.get('name', '')}"
                    e["args"] = args
                meta_evs.append(e)
            else:
                e["ts"] = float(e.get("ts", 0.0)) + shift_us
                x_evs.append(e)
    x_evs.sort(key=lambda e: e["ts"])
    return meta_evs + x_evs, per_shard


# ---------------------------------------------------------------------------
# Optional jax.profiler capture (JEPSEN_TPU_JAX_PROFILE=1)
# ---------------------------------------------------------------------------

def jax_profile_enabled() -> bool:
    return gates.get("JEPSEN_TPU_JAX_PROFILE")


class jax_profile_session:
    """Wrap a region in a `jax.profiler` trace when
    JEPSEN_TPU_JAX_PROFILE=1 (e.g. `--jax-profile`); otherwise a pure
    no-op that never imports jax. Profiler failures degrade to a
    warning — observability must never sink the run."""

    def __init__(self, out_dir):
        self.out_dir = Path(out_dir)
        self._active = False

    def __enter__(self):
        if jax_profile_enabled():
            try:
                import jax
                self.out_dir.mkdir(parents=True, exist_ok=True)
                jax.profiler.start_trace(str(self.out_dir))
                self._active = True
                log.info("jax.profiler capture -> %s", self.out_dir)
            except Exception:
                log.warning("jax.profiler capture failed to start",
                            exc_info=True)
        return self

    def __exit__(self, *exc):
        if self._active:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                log.warning("jax.profiler capture failed to stop",
                            exc_info=True)
        return False
