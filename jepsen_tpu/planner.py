"""The cost-aware dispatch planner (JEPSEN_TPU_PLANNER, default off).

Every sweep and every `serve` fold used to run ONE global
configuration — one bucket pad multiple, one python/native tier
choice, one fused-vs-two-pass setting, and a `T_pad²` admission
proxy — even though the costdb (PR-11) records measured device seconds
per (kernel flags, formulation, geometry) and the analytics ledger
(PR-15) records per-history edge density and closure rounds. This
module closes ROADMAP item 4's loop: the analytical complexity model
of arxiv 1908.04509 (closure cost grows with T_pad² × closure rounds,
modulated by edge density) parameterized EMPIRICALLY from this
machine's own measurements, steering four placement levers:

  * **bucket geometry** — `check_bucketed_async` asks `plan_buckets`
    which pad multiple (128/256/512) minimizes predicted device
    seconds + per-dispatch overhead for THIS batch (coarser multiples
    trade padding waste for fewer distinct executables);
  * **fused vs two-pass** — `check_bucketed` asks `fused_choice`
    which classify strategy the model prices cheaper, when the costdb
    has measured BOTH;
  * **split tier** — `independent.subhistories_path` asks
    `split_native` whether a history is big enough for the native
    per-key splitter to beat the pure-Python one;
  * **admission pricing** — the serve daemon prices each request with
    `admission_cost`: the model's predicted device seconds normalized
    back to the `fold_cost` cell unit (a history predicted as
    expensive as a T_pad=512 one costs 512² cells), so
    `plan_fold`'s DRR budgets and fairness semantics are unchanged.

THE INVARIANT: planner decisions never change verdicts, only
placement. Every lever routes between strategies the repo already
pins as verdict-identical (bucket composition, fused/two-pass,
native/python split, admission order), and the cold-start fallback —
costdb empty, device kind unseen, plan corrupt, model degenerate —
reproduces the exact current heuristics (`bucket_by_length` at
multiple 128, the fused gate, the native-split gate, `fold_cost`).

The fitted model persists as `<store>/plan.json` (snapshot protocol,
declared in the JT-DUR registry; `JEPSEN_TPU_PLANNER_PATH` overrides
the location) so warm sweeps and the daemon load it instead of
refitting. Every routing decision lands on the trace fabric
(`planner.*` counters: decisions, fallbacks, per-lever counts,
predicted-vs-measured error) and in `analyze-store --report`'s
"planner" section.

Stdlib-only module imports (gates/trace/store), like the device
observatory: the admission path must price a request without loading
jax.
"""

from __future__ import annotations

import json
import logging
import math
import threading

from . import gates, trace

log = logging.getLogger(__name__)

PLAN_VERSION = 1

#: Candidate bucket pad multiples `plan_buckets` races. 128 (the MXU
#: tile — the global default) is always first: the fallback and the
#: tie-break both land there, so an uninformative model reproduces
#: current behavior exactly.
GEOMETRY_CANDIDATES = (128, 256, 512)

#: The admission cost unit's reference T_pad: a history padding to 128
#: txns costs 128² cells under the model, exactly `fold_cost`'s floor,
#: so modeled and proxy costs share one scale and `budget_cells`
#: semantics are preserved.
_REF_TPAD = 128

#: Ridge regularizer for the log-space fit: keeps the tiny normal
#: system solvable on degenerate training sets (one geometry, one
#: mode) without changing a well-conditioned fit measurably.
_RIDGE = 1e-6

_LOCK = threading.Lock()
_active: "Planner | None" = None


def enabled() -> bool:
    """The JEPSEN_TPU_PLANNER gate (default off)."""
    return gates.get("JEPSEN_TPU_PLANNER")


# ---------------------------------------------------------------------------
# The model: costdb × analytics join, log-space ridge fit, prediction.
# ---------------------------------------------------------------------------

def _mode_key(rec: dict) -> str | None:
    """The model's stratification key for one costdb record: the
    kernel flags + formulation that pick an executable family. Two
    records with the same mode are the same cost curve sampled at
    different geometries."""
    k = rec.get("kernel")
    if not isinstance(k, dict):
        return None
    return "|".join((
        "classify" if k.get("classify", True) else "detect",
        "rt" if k.get("realtime") else "nort",
        "fused" if k.get("fused") else "twopass",
        str(rec.get("formulation") or "xla-bf16"),
    ))


def _analytics_by_tpad(search_records) -> dict[int, dict]:
    """Per-t_pad means of the analytics ledger's closure rounds and
    edge density (edges per txn) — the model's two non-geometric
    features. Register (WGL) records carry no t_pad and are skipped."""
    acc: dict[int, list] = {}
    for r in search_records or []:
        if not isinstance(r, dict):
            continue
        t = r.get("t_pad")
        if not isinstance(t, int) or t <= 0:
            continue
        n = max(int(r.get("n_txns") or 1), 1)
        edges = sum(int(r.get(f) or 0) for f in
                    ("ww_edges", "wr_edges", "rw_edges", "rt_edges",
                     "proc_edges"))
        rounds = r.get("closure_rounds")
        a = acc.setdefault(t, [0, 0.0, 0.0])
        a[0] += 1
        a[1] += float(rounds) if isinstance(rounds, (int, float)) else 1.0
        a[2] += edges / n
    return {t: {"rounds": a[1] / a[0],
                "edges_per_txn": a[2] / a[0],
                "histories": a[0]}
            for t, a in acc.items()}


def _features(t_pad: int, analytics: dict | None) -> list[float]:
    """The fit/predict feature row at one geometry: intercept, log
    T_pad, log1p closure rounds, log1p edge density — rounds/density
    taken from the NEAREST analytics t_pad bucket (the join is by
    geometry, and an unseen geometry borrows its closest neighbor's
    graph shape rather than inventing one)."""
    rounds, density = 1.0, 0.0
    if analytics:
        keys = [int(k) for k in analytics]
        near = min(keys, key=lambda k: abs(k - t_pad))
        row = analytics[near] if near in analytics \
            else analytics[str(near)]
        rounds = float(row.get("rounds", 1.0))
        density = float(row.get("edges_per_txn", 0.0))
    return [1.0, math.log(max(t_pad, 1)),
            math.log1p(max(rounds, 0.0)),
            math.log1p(max(density, 0.0))]


def _solve_ridge(rows: list[list[float]], ys: list[float]) -> list[float]:
    """Least squares with a ridge term, by Gaussian elimination on the
    normal equations — pure python, deterministic, fine at 4×4."""
    k = len(rows[0])
    ata = [[_RIDGE if i == j else 0.0 for j in range(k)]
           for i in range(k)]
    atb = [0.0] * k
    for x, y in zip(rows, ys):
        for i in range(k):
            atb[i] += x[i] * y
            for j in range(k):
                ata[i][j] += x[i] * x[j]
    # elimination with partial pivoting
    for col in range(k):
        piv = max(range(col, k), key=lambda r: abs(ata[r][col]))
        if abs(ata[piv][col]) < 1e-30:
            return [0.0] * k
        ata[col], ata[piv] = ata[piv], ata[col]
        atb[col], atb[piv] = atb[piv], atb[col]
        for r in range(k):
            if r == col:
                continue
            f = ata[r][col] / ata[col][col]
            atb[r] -= f * atb[col]
            for c in range(col, k):
                ata[r][c] -= f * ata[col][c]
    return [atb[i] / ata[i][i] for i in range(k)]


def training_points(cost_records, search_records) -> dict[str, list]:
    """The costdb × analytics join: per mode key, (t_pad, features,
    measured device seconds per history) for every costdb record that
    carries a real measured window. This is the `search_section`
    by-geometry join promoted to training data."""
    analytics = _analytics_by_tpad(search_records)
    by_mode: dict[str, list] = {}
    for rec in cost_records or []:
        if not isinstance(rec, dict):
            continue
        mode = _mode_key(rec)
        w = rec.get("windows") or {}
        g = rec.get("geometry") or {}
        t_pad = g.get("n_txns")
        hist = w.get("histories") or 0
        secs = w.get("device_secs") or 0.0
        if mode is None or not isinstance(t_pad, int) or t_pad <= 0 \
                or hist <= 0 or secs <= 0:
            continue
        y = secs / hist
        by_mode.setdefault(mode, []).append(
            (t_pad, _features(t_pad, analytics), y))
    return by_mode


def fit_plan(cost_records, search_records, *,
             device_kind: str | None = None,
             backend: str | None = None) -> dict | None:
    """Fit the plan from raw costdb/analytics records. None when the
    tables hold no usable measurement (the cold-start predicate) —
    never a degenerate all-zeros model."""
    by_mode = training_points(cost_records, search_records)
    if not by_mode:
        return None
    modes: dict[str, dict] = {}
    for mode, pts in sorted(by_mode.items()):
        coeffs = _solve_ridge([f for _t, f, _y in pts],
                              [math.log(max(y, 1e-12))
                               for _t, _f, y in pts])
        modes[mode] = {
            "coeffs": [round(c, 9) for c in coeffs],
            "points": len(pts),
            "t_pad_min": min(t for t, _f, _y in pts),
            "t_pad_max": max(t for t, _f, _y in pts),
        }
    overheads = []
    provenance = "estimated"
    for rec in cost_records or []:
        w = (rec or {}).get("windows") or {}
        if isinstance(w.get("min_secs"), (int, float)):
            overheads.append(float(w["min_secs"]))
        if isinstance(rec, dict) and rec.get("provenance") == "measured":
            provenance = "measured"
        if device_kind is None and isinstance(rec, dict) \
                and rec.get("device_kind"):
            device_kind = rec["device_kind"]
        if backend is None and isinstance(rec, dict) \
                and rec.get("backend"):
            backend = rec["backend"]
    analytics = _analytics_by_tpad(search_records)
    return {
        "v": PLAN_VERSION,
        "device_kind": device_kind or "unknown",
        "backend": backend or "unknown",
        "provenance": provenance,
        "trained_records": sum(len(p) for p in by_mode.values()),
        "modes": modes,
        "analytics": {str(t): {"rounds": round(r["rounds"], 4),
                               "edges_per_txn":
                                   round(r["edges_per_txn"], 4)}
                      for t, r in sorted(analytics.items())},
        # per-dispatch fixed overhead for the geometry race: the
        # fastest window ever measured approximates enqueue+launch
        "overhead_secs": round(min(overheads), 6) if overheads
        else 0.002,
        # histories smaller than this run the python splitter under
        # the planner; 0 (the default fit) keeps the native gate's
        # behavior — there is no split-cost table to fit yet
        "split_min_ops": 0,
    }


def _pick_mode(plan: dict, *, classify: bool = True,
               fused: bool | None = None) -> str | None:
    """The best-sampled mode key matching the requested strategy (the
    caller may not care about fused-ness: fused=None matches either)."""
    best, best_pts = None, -1
    for mode, row in (plan.get("modes") or {}).items():
        parts = mode.split("|")
        if classify != (parts[0] == "classify"):
            continue
        if fused is not None and (parts[2] == "fused") != fused:
            continue
        pts = int(row.get("points") or 0)
        if pts > best_pts:
            best, best_pts = mode, pts
    return best


def predict_secs(plan: dict, t_pad: int, *, mode: str | None = None,
                 classify: bool = True,
                 fused: bool | None = None) -> float | None:
    """Predicted device seconds per history at one padded geometry,
    or None when the plan holds no matching mode — the caller then
    falls back to the heuristic, it never guesses."""
    if not isinstance(plan, dict):
        return None
    if mode is None or mode not in (plan.get("modes") or {}):
        mode = _pick_mode(plan, classify=classify, fused=fused)
    row = (plan.get("modes") or {}).get(mode)
    if not row:
        return None
    coeffs = row.get("coeffs")
    if not isinstance(coeffs, list) or len(coeffs) != 4:
        return None
    x = _features(int(t_pad), plan.get("analytics") or {})
    ln = sum(c * f for c, f in zip(coeffs, x))
    # clamp the exponent: a wild extrapolation must stay a finite,
    # orderable number, not an inf that poisons the DRR arithmetic
    return math.exp(max(-25.0, min(ln, 5.0)))


# ---------------------------------------------------------------------------
# plan.json persistence — snapshot protocol (JT-DUR "dispatch plan").
# ---------------------------------------------------------------------------

def save_plan(path, plan: dict) -> bool:
    """Publish the fitted plan atomically (temp + os.replace via
    trace.atomic_write_text). Best-effort: a read-only store logs and
    returns False, never fails the sweep."""
    try:
        trace.atomic_write_text(path,
                                json.dumps(plan, indent=2) + "\n")
        return True
    except OSError:
        log.debug("plan save failed for %s", path, exc_info=True)
        return False


def load_plan(path) -> dict | None:
    """The persisted plan, or None for missing/corrupt/alien files —
    the AOT-cache degrade rule: a bad snapshot means a fresh cold
    start (heuristic fallback), never a failed sweep."""
    from pathlib import Path
    p = Path(path)
    if not p.is_file():
        return None
    try:
        plan = json.loads(p.read_text())
    except (OSError, ValueError):
        log.debug("plan load failed for %s (degrading to the "
                  "heuristic fallback)", p, exc_info=True)
        return None
    if not isinstance(plan, dict) or plan.get("v") != PLAN_VERSION \
            or not isinstance(plan.get("modes"), dict):
        log.debug("plan %s has alien shape; degrading to the "
                  "heuristic fallback", p)
        return None
    return plan


# ---------------------------------------------------------------------------
# The router.
# ---------------------------------------------------------------------------

class Planner:
    """One sweep's (or daemon's) routing brain: a fitted plan — or
    None, in which case EVERY decision is the deterministic heuristic
    fallback, counted as such. Decisions only ever choose placement
    among verdict-identical strategies (module docstring)."""

    def __init__(self, plan: dict | None, source: str):
        self.plan = plan
        #: "plan" (loaded snapshot), "fit" (fresh fit), "cold" (gate
        #: on, no model — pure fallback).
        self.source = source if plan is not None else "cold"

    @property
    def modeled(self) -> bool:
        return self.plan is not None

    # -- decision bookkeeping ---------------------------------------------

    def _decide(self, lever: str, fallback: bool) -> None:
        trace.counter("planner.decisions").inc()
        trace.counter(f"planner.{lever}").inc()
        if fallback:
            trace.counter("planner.fallbacks").inc()

    # -- lever: serve admission pricing -----------------------------------

    def admission_cost(self, n_txns: int, checker: str = "append") -> int:
        """One request's admission price in `fold_cost`'s cell unit:
        the model's predicted device seconds normalized so a T_pad=128
        history costs exactly 128² cells. DRR semantics survive by
        construction — any positive integer cost does. Fallback (and
        any degenerate prediction): `fold_cost` itself, bit-exact."""
        from .parallel import folding
        proxy = folding.fold_cost(int(n_txns or 1))
        if self.plan is None:
            self._decide("admission", fallback=True)
            return proxy
        t = max(int(n_txns or 1), 1)
        t_pad = max(_REF_TPAD,
                    ((t + _REF_TPAD - 1) // _REF_TPAD) * _REF_TPAD)
        pred = predict_secs(self.plan, t_pad, classify=True)
        unit = predict_secs(self.plan, _REF_TPAD, classify=True)
        if not pred or not unit or unit <= 0:
            self._decide("admission", fallback=True)
            return proxy
        self._decide("admission", fallback=False)
        return max(1, int(round(_REF_TPAD * _REF_TPAD * pred / unit)))

    # -- lever: bucket geometry -------------------------------------------

    def plan_buckets(self, encs, *, budget_cells: int,
                     dp: int = 1) -> list[list[int]]:
        """Bucket composition for one dispatch pipeline: race the
        candidate pad multiples on predicted total device seconds
        (per-history model cost + per-dispatch overhead) and keep the
        winner's buckets. Every candidate satisfies the same
        B_pad·T_pad² ≤ budget envelope, and composition only moves
        histories between dispatches — verdicts cannot change.
        Fallback: `bucket_by_length` at multiple 128, bit-exact."""
        from .parallel import bucket_by_length
        base = bucket_by_length(encs, budget_cells=budget_cells, dp=dp)
        if self.plan is None:
            self._decide("geometry", fallback=True)
            return base
        overhead = float(self.plan.get("overhead_secs") or 0.002)

        def predicted_total(buckets) -> float | None:
            total = 0.0
            for b in buckets:
                t_pad = max(_size_pad(encs, b), 1)
                per = predict_secs(self.plan, t_pad, classify=True)
                if per is None:
                    return None
                total += overhead + per * len(b)
            return total

        best, best_cost, fell_back = base, predicted_total(base), False
        if best_cost is None:
            self._decide("geometry", fallback=True)
            return base
        for m in GEOMETRY_CANDIDATES[1:]:
            cand = bucket_by_length(encs, multiple=m,
                                    budget_cells=budget_cells, dp=dp)
            cost = predicted_total(cand)
            if cost is not None and cost < best_cost:
                best, best_cost = cand, cost
        self._decide("geometry", fallback=fell_back)
        return best

    # -- lever: fused vs two-pass classify --------------------------------

    def fused_choice(self, default: bool, *, classify: bool = True,
                     t_pad: int = _REF_TPAD) -> bool:
        """The classify strategy the model prices cheaper at this
        geometry — only when the costdb has MEASURED both strategies
        (the verdicts are pinned identical either way); one-sided or
        absent evidence keeps the gate's default."""
        if not classify or self.plan is None:
            self._decide("fused", fallback=True)
            return default
        fused = predict_secs(self.plan, t_pad, classify=True,
                             fused=True)
        two = predict_secs(self.plan, t_pad, classify=True,
                           fused=False)
        has_both = (
            _pick_mode(self.plan, classify=True, fused=True) is not None
            and _pick_mode(self.plan, classify=True,
                           fused=False) is not None)
        if not has_both or fused is None or two is None:
            self._decide("fused", fallback=True)
            return default
        self._decide("fused", fallback=False)
        return fused <= two

    # -- lever: split tier (python vs native) -----------------------------

    def split_native(self, n_ops: int) -> bool:
        """Whether the native per-key splitter should run for a
        history of `n_ops` ops (the caller has already checked the
        gate — the planner can only DECLINE native, never force it on
        past the user's pin). The threshold rides the plan so an
        operator (or a future split-cost fit) can raise it; the
        fitted default 0 reproduces current behavior."""
        if self.plan is None:
            self._decide("split", fallback=True)
            return True
        thresh = int(self.plan.get("split_min_ops") or 0)
        self._decide("split", fallback=False)
        return int(n_ops) >= thresh

    # -- predicted-vs-measured accounting ---------------------------------

    def score_against(self, cost_records) -> dict | None:
        """Mean relative predicted-vs-measured error of this plan
        over freshly measured costdb records — the honesty loop: the
        report (and the `planner.pred_err_permille` gauge) always
        shows how wrong the model was THIS sweep."""
        if self.plan is None:
            return None
        errs = []
        for rec in cost_records or []:
            if not isinstance(rec, dict):
                continue
            w = rec.get("windows") or {}
            g = rec.get("geometry") or {}
            hist = w.get("histories") or 0
            secs = w.get("device_secs") or 0.0
            t_pad = g.get("n_txns")
            if hist <= 0 or secs <= 0 or not isinstance(t_pad, int):
                continue
            pred = predict_secs(self.plan, t_pad, mode=_mode_key(rec))
            if pred is None:
                continue
            measured = secs / hist
            errs.append(abs(pred - measured) / max(measured, 1e-12))
            trace.counter("planner.pred_checked").inc()
        if not errs:
            return None
        mean_err = sum(errs) / len(errs)
        trace.gauge("planner.pred_err_permille").set(
            int(round(mean_err * 1000)))
        return {"records": len(errs),
                "mean_rel_err": round(mean_err, 4),
                "max_rel_err": round(max(errs), 4)}


def _size_pad(encs, bucket: list[int], multiple: int = _REF_TPAD) -> int:
    """A bucket's dispatch T_pad: its max history size rounded to the
    MXU tile — the geometry `BatchShape.plan` will actually pad to,
    whatever multiple composed the bucket."""
    n = max(_enc_size(encs[i]) for i in bucket)
    return max(multiple,
               ((n + multiple - 1) // multiple) * multiple)


def _enc_size(e) -> int:
    n = getattr(e, "n", None)
    if n is None and isinstance(e, dict):
        n = e.get("n")
    return max(int(n or 1), 1)


# ---------------------------------------------------------------------------
# Lifecycle: one active planner per process, like the observatories.
# ---------------------------------------------------------------------------

def get() -> "Planner | None":
    """The active planner, or None when the gate is off (the dispatch
    layers' one-gate-read fast path). Gate on with nothing activated
    yet yields a cold planner: pure fallback until someone fits or
    loads a plan."""
    if not enabled():
        return None
    global _active
    with _LOCK:
        if _active is None:
            _active = Planner(None, "cold")
        return _active


def activate(store_base=None) -> "Planner | None":
    """Install the planner for a sweep/daemon: load the persisted
    plan.json if one exists (warm start), else run cold. No-op
    (returns None) when the gate is off."""
    global _active
    if not enabled():
        with _LOCK:
            _active = None
        return None
    plan = None
    with trace.span("planner.activate"):
        if store_base is not None \
                or gates.get("JEPSEN_TPU_PLANNER_PATH"):
            from .store import plan_path
            plan = load_plan(plan_path(store_base or "."))
    pl = Planner(plan, "plan")
    if plan is None:
        trace.counter("planner.cold_starts").inc()
    with _LOCK:
        _active = pl
    return pl


def deactivate() -> None:
    """Drop the active planner (sweep end, tests)."""
    global _active
    with _LOCK:
        _active = None


def current_plan() -> dict | None:
    """The active planner's fitted plan, or None (cold / gate off) —
    the report section's input."""
    with _LOCK:
        return _active.plan if _active is not None else None


def refresh(store_base, cost_records, search_records) -> dict | None:
    """Sweep-end refit: fit a fresh plan from this sweep's records
    (joined with whatever the store already held) and persist it, so
    the NEXT sweep and the daemon warm-start. Returns the plan, or
    None when there was nothing to fit; never raises."""
    if not enabled():
        return None
    try:
        from .store import plan_path
        with trace.span("planner.fit"):
            plan = fit_plan(cost_records, search_records)
        if plan is None:
            return None
        save_plan(plan_path(store_base), plan)
        with _LOCK:
            global _active
            _active = Planner(plan, "fit")
        return plan
    except Exception:
        log.debug("planner refresh failed", exc_info=True)
        return None


# ---------------------------------------------------------------------------
# Report section — the device/search sections' pattern.
# ---------------------------------------------------------------------------

def planner_section(plan: dict | None, cost_records=None,
                    metrics: dict | None = None) -> dict:
    """The report's "planner" section: model provenance, per-mode fit
    shape, decision/fallback counts from the sweep's metrics, and the
    predicted-vs-measured error over this sweep's fresh records."""
    counters = (metrics or {}).get("counters") or {}
    sec: dict = {
        "enabled": enabled(),
        "modeled": plan is not None,
        "decisions": int(counters.get("planner.decisions") or 0),
        "fallbacks": int(counters.get("planner.fallbacks") or 0),
        "levers": {lv: int(counters.get(f"planner.{lv}") or 0)
                   for lv in ("geometry", "fused", "split", "admission")
                   if counters.get(f"planner.{lv}")},
    }
    if plan is not None:
        sec["device_kind"] = plan.get("device_kind")
        sec["provenance"] = plan.get("provenance")
        sec["trained_records"] = plan.get("trained_records")
        sec["modes"] = {m: {"points": r.get("points"),
                            "t_pad_range": [r.get("t_pad_min"),
                                            r.get("t_pad_max")]}
                        for m, r in (plan.get("modes") or {}).items()}
        err = Planner(plan, "plan").score_against(cost_records)
        if err is not None:
            sec["predicted_vs_measured"] = err
    return sec


def render_planner_md(sec: dict) -> list[str]:
    """The report.md rendering of one planner section."""
    out = ["", "## Cost-aware planner", ""]
    if not sec.get("modeled"):
        out.append("cold start: no fitted model — every decision "
                   "took the deterministic heuristic fallback "
                   f"({sec.get('fallbacks', 0)} of "
                   f"{sec.get('decisions', 0)} decisions).")
        return out
    out.append(f"model: {sec.get('trained_records', 0)} training "
               f"record(s) on `{sec.get('device_kind')}` "
               f"({sec.get('provenance')}); "
               f"{sec.get('decisions', 0)} decision(s), "
               f"{sec.get('fallbacks', 0)} fallback(s)")
    pv = sec.get("predicted_vs_measured")
    if pv:
        out.append(f"predicted-vs-measured: mean "
                   f"{pv['mean_rel_err']:.1%} / max "
                   f"{pv['max_rel_err']:.1%} relative error over "
                   f"{pv['records']} record(s)")
    modes = sec.get("modes") or {}
    if modes:
        out += ["", "| mode | points | t_pad range |", "|---|---|---|"]
        for m, r in sorted(modes.items()):
            lo, hi = (r.get("t_pad_range") or [None, None])[:2]
            # mode keys embed literal pipes — escape for the table
            out.append(f"| `{m.replace('|', chr(92) + '|')}` | "
                       f"{r.get('points')} | {lo}–{hi} |")
    return out
