"""Wrap DB binaries so their clocks run at an offset/rate.

Counterpart of jepsen.faketime (jepsen/src/jepsen/faketime.clj). Where
the reference clones and installs a libfaketime fork on each node
(faketime.clj:8-22), this ships our own LD_PRELOAD shim
(native/faketime_shim.cc) and compiles it on the node — no network
fetch, same fault: the wrapped process sees
``t0 + offset + (t - t0) * rate``.
"""

from __future__ import annotations

import os.path
import random

from . import control

SHIM_DIR = "/opt/jepsen"
SHIM_SO = f"{SHIM_DIR}/libfaketime_shim.so"
NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")


def install(test: dict | None = None, node: str | None = None) -> None:
    """Upload + build the shim on the current session's node
    (counterpart of install-0.9.6-jepsen1!, faketime.clj:8-22)."""
    sess = control.current_session()
    su = sess.su()
    su.exec("mkdir", "-p", SHIM_DIR)
    src = os.path.join(NATIVE_DIR, "faketime_shim.cc")
    sess.upload(src, "/tmp/faketime_shim.cc")
    su.exec("mv", "/tmp/faketime_shim.cc", f"{SHIM_DIR}/faketime_shim.cc")
    # -pthread: the shim calls pthread_once, and a preloaded .so that
    # leaves the reference undefined breaks any host binary that does
    # not itself link libpthread (glibc's `date` on current distros:
    # "symbol lookup error: undefined symbol: pthread_once")
    su.exec(control.Lit(
        f"g++ -O2 -fPIC -shared -pthread -o {SHIM_SO} "
        f"{SHIM_DIR}/faketime_shim.cc -ldl"))


def script(cmd: str, init_offset: float, rate: float) -> str:
    """A sh script invoking cmd under the clock shim (faketime.clj:24-34)."""
    return ("#!/bin/bash\n"
            f"export LD_PRELOAD={SHIM_SO}\n"
            f"export JEPSEN_FAKETIME_OFFSET_S={float(init_offset)}\n"
            f"export JEPSEN_FAKETIME_RATE={float(rate)}\n"
            f"exec {cmd} \"$@\"\n")


def wrap(cmd: str, init_offset: float, rate: float) -> None:
    """Replace executable `cmd` with a skewed wrapper, keeping the
    original at cmd.no-faketime. Idempotent (faketime.clj:36-47)."""
    from .control import util as cutil
    sess = control.current_session()
    moved = f"{cmd}.no-faketime"
    wrapper = script(moved, init_offset, rate)
    if not cutil.exists(sess, moved):
        sess.su().exec("mv", cmd, moved)
    write = (f"cat > {control.escape(cmd)} <<'JEPSEN_EOF'\n"
             f"{wrapper}JEPSEN_EOF")
    res = sess.su().exec_raw(write)
    if res.exit != 0:
        # The original is already moved aside — fail loudly rather than
        # leave a broken wrapper in its place.
        raise control.CommandError(write, res.exit, res.out, res.err,
                                   sess.node)
    sess.su().exec("chmod", "a+x", cmd)


def unwrap(cmd: str) -> None:
    """Restore the original binary if wrapped (faketime.clj:49-55)."""
    from .control import util as cutil
    sess = control.current_session()
    moved = f"{cmd}.no-faketime"
    if cutil.exists(sess, moved):
        sess.su().exec("mv", moved, cmd)


def rand_factor(factor: float, rng: random.Random | None = None) -> float:
    """A clock rate near 1 such that max-rate = factor * min-rate across
    draws (faketime.clj:57-65)."""
    hi = 2 / (1 + 1 / factor)
    lo = hi / factor
    r = (rng or random).random()
    return lo + r * (hi - lo)
