"""jepsen_tpu: a TPU-native distributed-systems testing framework.

A ground-up rebuild of the capabilities of Jepsen (reference:
neuroradiology/jepsen): provision a cluster over SSH, drive concurrent client
operations from a pure generator DSL, inject faults with a nemesis layer,
record invocation/completion histories, and check those histories against
consistency models.

The differentiator is the analysis phase: histories are encoded as
HBM-resident integer tensors and checked by JAX/Pallas kernels sharded across
a TPU mesh (Elle-style transactional anomaly search via MXU boolean
transitive closure; Knossos-style linearizability via batched frontier
search), so thousands of recorded runs can be verified in one batch.

Layer map (mirrors SURVEY.md section 1):
  control/    L0 remote control (SSH / dummy backends)
  os_setup    L1 environment provisioning + db.py DB lifecycle
  nemesis/    L2 fault injection
  client      L3 client protocol
  generator/  L4 pure generator DSL + interpreter
  core        L5 runner / orchestration
  checker/    L6 analysis (CPU oracles + TPU kernels)
  store       L7 persistence
  cli         L8 command line
  workloads/  L9 workload library
"""

__version__ = "0.1.0"
