"""Checkers: analysis of histories.

The `Checker` interface mirrors the reference protocol
(jepsen/src/jepsen/checker.clj:49-69): `check(test, history, opts) ->
{"valid?": True | False | "unknown", ...}`. `compose` runs a map of named
checkers in parallel and merges validity with invalid < unknown < valid
precedence (checker.clj:20-47,90-102).

The built-in checkers here are the CPU oracles: pure data-in/data-out
functions, golden-tested, that also serve as the differential references for
the TPU kernel checkers in `checker.elle` and `checker.knossos`.
"""

from __future__ import annotations

import traceback
from collections import Counter
from typing import Any, Callable

from .. import history as h
from ..util import integer_interval_set_str, real_pmap
from . import models as model

VALID_PRIORITIES = {True: 2, "unknown": 1, False: 0}


def merge_valid(valids: list) -> Any:
    """Merge validity values: false wins over unknown wins over true."""
    out: Any = True
    for v in valids:
        if v not in VALID_PRIORITIES:
            raise ValueError(f"{v!r} is not a known valid? value")
        if VALID_PRIORITIES[v] < VALID_PRIORITIES[out]:
            out = v
    return out


class Checker:
    def check(self, test: dict, history: list, opts: dict) -> dict | None:
        raise NotImplementedError


class FnChecker(Checker):
    """Wrap a function (test, history, opts) -> result as a Checker."""

    def __init__(self, f: Callable[[dict, list, dict], dict | None]):
        self.f = f

    def check(self, test, history, opts):
        return self.f(test, history, opts)


def check_safe(checker: Checker, test: dict, history: list,
               opts: dict | None = None) -> dict:
    """Like check, but returns exceptions as {"valid?": "unknown"} results
    (checker.clj:77-88). Every check runs inside a trace span named
    after the checker class, so composed checkers show up as one track
    row each in the run's trace.json."""
    from .. import trace
    try:
        with trace.span(f"check:{type(checker).__name__}",
                        ops=len(history)):
            r = checker.check(test, history, opts or {})
        return r if r is not None else {"valid?": True}
    except Exception:
        return {"valid?": "unknown", "error": traceback.format_exc()}


class Noop(Checker):
    def check(self, test, history, opts):
        return None


def noop() -> Checker:
    return Noop()


class UnbridledOptimism(Checker):
    """Everything is awesome."""

    def check(self, test, history, opts):
        return {"valid?": True}


def unbridled_optimism() -> Checker:
    return UnbridledOptimism()


class Compose(Checker):
    def __init__(self, checker_map: dict[str, Checker]):
        self.checker_map = checker_map

    def check(self, test, history, opts):
        items = list(self.checker_map.items())
        results = real_pmap(
            lambda kv: (kv[0], check_safe(kv[1], test, history, opts)), items)
        out: dict = dict(results)
        out["valid?"] = merge_valid([r.get("valid?", True) for _, r in results])
        return out


def compose(checker_map: dict[str, Checker]) -> Checker:
    return Compose(checker_map)


class ConcurrencyLimit(Checker):
    """Bound concurrent executions of a memory-hungry checker
    (checker.clj:104-119)."""

    def __init__(self, limit: int, checker: Checker):
        import threading
        self.sem = threading.Semaphore(limit)
        self.checker = checker

    def check(self, test, history, opts):
        with self.sem:
            return self.checker.check(test, history, opts)


def concurrency_limit(limit: int, checker: Checker) -> Checker:
    return ConcurrencyLimit(limit, checker)


class UnhandledExceptions(Checker):
    """Aggregate crashed (:info) ops carrying errors, by error class,
    in descending frequency (checker.clj:127-154)."""

    def check(self, test, history, opts):
        crashed = [o for o in history
                   if h.is_info(o) and (o.get("exception") or o.get("error"))]
        groups: dict[Any, list] = {}
        for o in crashed:
            exc = o.get("exception")
            cls = (exc.get("class") if isinstance(exc, dict)
                   else type(exc).__name__ if isinstance(exc, BaseException)
                   else str(o.get("error", exc)))
            groups.setdefault(cls, []).append(o)
        exes = sorted(groups.items(), key=lambda kv: len(kv[1]), reverse=True)
        if not exes:
            return {"valid?": True}
        return {"valid?": True,
                "exceptions": [{"class": cls, "count": len(ops),
                                "example": ops[0]} for cls, ops in exes]}


def unhandled_exceptions() -> Checker:
    return UnhandledExceptions()


def _stats_of(ops: list) -> dict:
    ok = sum(1 for o in ops if h.is_ok(o))
    fail = sum(1 for o in ops if h.is_fail(o))
    info = sum(1 for o in ops if h.is_info(o))
    return {"valid?": ok > 0, "count": ok + fail + info,
            "ok-count": ok, "fail-count": fail, "info-count": info}


class Stats(Checker):
    """Success/failure counts, overall and by :f. Valid only when every :f
    saw at least one :ok (checker.clj:169-186)."""

    def check(self, test, history, opts):
        hist = [o for o in history
                if not h.is_invoke(o) and o.get("process") != h.NEMESIS]
        by_f: dict = {}
        for o in hist:
            by_f.setdefault(o.get("f"), []).append(o)
        groups = {f: _stats_of(ops) for f, ops in sorted(
            by_f.items(), key=lambda kv: str(kv[0]))}
        out = _stats_of(hist)
        out["by-f"] = groups
        out["valid?"] = merge_valid([g["valid?"] for g in groups.values()])
        return out


def stats() -> Checker:
    return Stats()


class QueueChecker(Checker):
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded and only ok dequeues happened, then fold through the
    model (checker.clj:221-240). O(n); use an unordered queue model."""

    def __init__(self, m: model.Model):
        self.model = m

    def check(self, test, history, opts):
        state = self.model
        for o in history:
            f = o.get("f")
            take = (h.is_invoke(o) if f == "enqueue"
                    else h.is_ok(o) if f == "dequeue" else False)
            if not take:
                continue
            state = state.step(o)
            if model.is_inconsistent(state):
                return {"valid?": False, "error": state.msg}
        return {"valid?": True, "final-queue": repr(state)}


def queue(m: model.Model | None = None) -> Checker:
    return QueueChecker(m or model.unordered_queue())


class SetChecker(Checker):
    """:add ops followed by a final :read of the whole set
    (checker.clj:243-302): every acknowledged add must be present; nothing
    unexpected may appear."""

    def check(self, test, history, opts):
        attempts = {o.get("value") for o in history
                    if h.is_invoke(o) and o.get("f") == "add"}
        adds = {o.get("value") for o in history
                if h.is_ok(o) and o.get("f") == "add"}
        final_read = None
        for o in history:
            if h.is_ok(o) and o.get("f") == "read":
                final_read = o.get("value")
        if final_read is None:
            return {"valid?": "unknown", "error": "Set was never read"}
        final = {v for v in final_read} if not isinstance(final_read, (set, frozenset)) else set(final_read)
        ok = final & attempts
        unexpected = final - attempts
        lost = adds - final
        recovered = ok - adds
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(ok),
            "lost-count": len(lost),
            "recovered-count": len(recovered),
            "unexpected-count": len(unexpected),
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
        }


def set_checker() -> Checker:
    return SetChecker()


class _SetFullElement:
    """Per-element timeline state for set-full (checker.clj:305-341)."""

    __slots__ = ("element", "known", "last_present", "last_absent")

    def __init__(self, element):
        self.element = element
        self.known = None          # completion op that proved existence
        self.last_present = None   # latest read invocation that observed it
        self.last_absent = None    # latest read invocation that missed it

    def add_ok(self, op):
        if self.known is None:
            self.known = op

    def read_present(self, iop, op):
        if self.known is None:
            self.known = op
        if self.last_present is None or \
                self.last_present["index"] < iop["index"]:
            self.last_present = iop

    def read_absent(self, iop, op):
        if self.last_absent is None or \
                self.last_absent["index"] < iop["index"]:
            self.last_absent = iop


def _idx(op, default=-1):
    return op["index"] if op is not None else default


def _set_full_element_results(e: _SetFullElement) -> dict:
    known_time = e.known.get("time") if e.known else None
    stable = bool(e.last_present is not None and
                  _idx(e.last_absent) < _idx(e.last_present))
    # An absent read concurrent with the add could have linearized before it;
    # require the miss to begin after the add was known complete
    # (checker.clj:368-383).
    lost = bool(e.known is not None and e.last_absent is not None and
                _idx(e.last_present) < _idx(e.last_absent) and
                _idx(e.known) < _idx(e.last_absent))
    stable_time = ((e.last_absent["time"] + 1 if e.last_absent else 0)
                   if stable else None)
    lost_time = ((e.last_present["time"] + 1 if e.last_present else 0)
                 if lost else None)
    stable_latency = (max(0, stable_time - known_time) // 1_000_000
                      if stable else None)
    lost_latency = (max(0, lost_time - known_time) // 1_000_000
                    if lost else None)
    return {"element": e.element,
            "outcome": ("stable" if stable else
                        "lost" if lost else "never-read"),
            "stable-latency": stable_latency,
            "lost-latency": lost_latency,
            "known": e.known,
            "last-absent": e.last_absent}


def frequency_distribution(points: list[float], xs: list) -> dict | None:
    xs = sorted(xs)
    if not xs:
        return None
    n = len(xs)
    return {p: xs[min(n - 1, int(n * p))] for p in points}


class SetFull(Checker):
    """Rigorous set analysis: per-element stable/lost/never-read outcomes
    with latency distributions (checker.clj:464-595). With
    linearizable=True, stale reads (nonzero stable latency) are invalid.

    Note: the reference's duplicate filter compares multiplicity < 1
    (checker.clj:571), which can never fire; we implement the evident
    intent — elements appearing more than once in a single read."""

    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, history, opts):
        elements: dict[Any, _SetFullElement] = {}
        reads: dict[Any, dict] = {}   # process -> read invocation
        dups: dict[Any, int] = {}     # element -> max multiplicity > 1
        for o in history:
            if not h.is_client_op(o):
                continue
            f, p = o.get("f"), o.get("process")
            if f == "add":
                v = o.get("value")
                if h.is_invoke(o):
                    elements.setdefault(v, _SetFullElement(v))
                elif h.is_ok(o) and v in elements:
                    elements[v].add_ok(o)
            elif f == "read":
                if h.is_invoke(o):
                    reads[p] = o
                elif h.is_fail(o):
                    reads.pop(p, None)
                elif h.is_ok(o):
                    iop = reads.pop(p, o)
                    vals = o.get("value") or []
                    for el, n in Counter(vals).items():
                        if n > 1:
                            dups[el] = max(dups.get(el, 0), n)
                    vset = set(vals)
                    for el, state in elements.items():
                        if el in vset:
                            state.read_present(iop, o)
                        else:
                            state.read_absent(iop, o)
        rs = [_set_full_element_results(e) for _, e in sorted(
            elements.items(), key=lambda kv: repr(kv[0]))]
        outcomes: dict[str, list] = {}
        for r in rs:
            outcomes.setdefault(r["outcome"], []).append(r)
        stable = outcomes.get("stable", [])
        lost = outcomes.get("lost", [])
        never_read = outcomes.get("never-read", [])
        stale = [r for r in stable if r["stable-latency"] > 0]
        stable_lat = [r["stable-latency"] for r in rs
                      if r["stable-latency"] is not None]
        lost_lat = [r["lost-latency"] for r in rs
                    if r["lost-latency"] is not None]
        valid: Any = (False if lost else
                      "unknown" if not stable else
                      False if self.linearizable and stale else
                      True)
        out = {
            "valid?": False if dups else valid,
            "attempt-count": len(rs),
            "stable-count": len(stable),
            "lost-count": len(lost),
            "lost": sorted((r["element"] for r in lost), key=repr),
            "never-read-count": len(never_read),
            "never-read": sorted((r["element"] for r in never_read), key=repr),
            "stale-count": len(stale),
            "stale": sorted((r["element"] for r in stale), key=repr),
            "worst-stale": sorted(stale, key=lambda r: r["stable-latency"],
                                  reverse=True)[:8],
            "duplicated-count": len(dups),
            "duplicated": dict(sorted(dups.items(), key=lambda kv: repr(kv[0]))),
        }
        points = [0, 0.5, 0.95, 0.99, 1]
        fd = frequency_distribution(points, stable_lat)
        if fd:
            out["stable-latencies"] = fd
        fd = frequency_distribution(points, lost_lat)
        if fd:
            out["lost-latencies"] = fd
        return out


def set_full(linearizable: bool = False) -> Checker:
    return SetFull(linearizable)


def expand_queue_drain_ops(history: list) -> list:
    """Expand ok :drain ops (value = list of elements) into dequeue
    invoke/ok pairs (checker.clj:598-628)."""
    out = []
    for o in history:
        if o.get("f") != "drain":
            out.append(o)
        elif h.is_invoke(o) or h.is_fail(o):
            continue
        elif h.is_ok(o):
            for el in o.get("value") or []:
                out.append({**o, "type": "invoke", "f": "dequeue", "value": None})
                out.append({**o, "type": "ok", "f": "dequeue", "value": el})
        else:
            raise ValueError(f"can't handle a crashed drain operation: {o!r}")
    return out


class TotalQueue(Checker):
    """What goes in must come out — multiset accounting over enqueues and
    dequeues, with drains expanded (checker.clj:631-690)."""

    def check(self, test, history, opts):
        hist = expand_queue_drain_ops(history)
        attempts = Counter(o.get("value") for o in hist
                           if h.is_invoke(o) and o.get("f") == "enqueue")
        enqueues = Counter(o.get("value") for o in hist
                           if h.is_ok(o) and o.get("f") == "enqueue")
        dequeues = Counter(o.get("value") for o in hist
                           if h.is_ok(o) and o.get("f") == "dequeue")
        ok = dequeues & attempts
        unexpected = Counter({v: n for v, n in dequeues.items()
                              if v not in attempts})
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": sum(attempts.values()),
            "acknowledged-count": sum(enqueues.values()),
            "ok-count": sum(ok.values()),
            "unexpected-count": sum(unexpected.values()),
            "duplicated-count": sum(duplicated.values()),
            "lost-count": sum(lost.values()),
            "recovered-count": sum(recovered.values()),
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
        }


def total_queue() -> Checker:
    return TotalQueue()


class UniqueIds(Checker):
    """A unique-id generator must emit distinct values
    (checker.clj:692-737)."""

    def check(self, test, history, opts):
        attempted = sum(1 for o in history
                        if h.is_invoke(o) and o.get("f") == "generate")
        acks = [o.get("value") for o in history
                if h.is_ok(o) and o.get("f") == "generate"]
        counts = Counter(acks)
        dups = {v: n for v, n in counts.items() if n > 1}
        rng = [min(acks), max(acks)] if acks else [None, None]
        worst = dict(sorted(dups.items(), key=lambda kv: kv[1],
                            reverse=True)[:48])
        return {"valid?": not dups,
                "attempted-count": attempted,
                "acknowledged-count": len(acks),
                "duplicated-count": len(dups),
                "duplicated": worst,
                "range": rng}


def unique_ids() -> Checker:
    return UniqueIds()


class CounterChecker(Checker):
    """A counter incremented by :add ops and observed by :read ops: each read
    must lie within [sum of ok increments + attempted decrements, sum of
    attempted increments + ok decrements] at its window (checker.clj:740-795).
    """

    def check(self, test, history, opts):
        # Apply completion values to invocations; drop definite failures.
        hist = h.remove_failures(h.complete(h.index(history)))
        lower = upper = 0
        pending_reads: dict = {}
        reads: list = []
        for o in hist:
            key = (o.get("type"), o.get("f"))
            p = o.get("process")
            v = o.get("value")
            if key == ("invoke", "read"):
                pending_reads[p] = [lower, v]
            elif key == ("ok", "read"):
                r = pending_reads.pop(p, [lower, v])
                reads.append([r[0], r[1], upper])
            elif key == ("invoke", "add"):
                if v >= 0:
                    upper += v
                else:
                    lower += v
            elif key == ("ok", "add"):
                if v >= 0:
                    lower += v
                else:
                    upper += v
        errors = [r for r in reads
                  if r[1] is None or not (r[0] <= r[1] <= r[2])]
        return {"valid?": not errors, "reads": reads, "errors": errors}


def counter() -> Checker:
    return CounterChecker()


class Linearizable(Checker):
    """Linearizability checker over a data-type model — the reference's
    `checker/linearizable` (jepsen/src/jepsen/checker.clj:188-219),
    rebuilt on the native knossos engine.

    `model` is a `models.Model` (immutable; step returns a successor).
    `algorithm` mirrors knossos: "wgl" | "linear" | "competition"; on
    this build all CPU routes share the WGL engine (C++ for CAS
    registers, Python otherwise) and the `linear` config-space search
    is the TPU dense-bitset kernel, selected with backend="tpu".
    backend="race" is the knossos-competition analogue across ENGINES:
    the device pipeline and the CPU engine run concurrently and the
    first full-batch finisher wins (multi-core hosts only — racing
    doubles host work while both run). The device route is taken only
    for the model it implements (a fresh CAS register) on histories
    that fit its slot/value grid; everything else falls back to the
    CPU engine, so verdicts only ever degrade to the oracle, never
    diverge from it."""

    def __init__(self, m: model.Model | None = None,
                 algorithm: str = "competition", backend: str = "auto",
                 frontier: int | None = None):
        self.model = m if m is not None else model.cas_register()
        self.algorithm = algorithm
        self.backend = backend
        # bounded-frontier arena size; None = JEPSEN_TPU_FRONTIER or 512
        if frontier is None:
            from .. import gates
            frontier = gates.get("JEPSEN_TPU_FRONTIER")
        self.frontier = frontier

    def _cpu(self, history: list, search_stats: dict | None = None
             ) -> dict:
        from . import knossos
        return knossos.analysis(self.model, history,
                                algorithm=self.algorithm,
                                search_stats=search_stats)

    def check(self, test, history, opts):
        res = self.check_batch(test, [history], opts)[0]
        if res.get("valid?") is False:
            self.render_failure(test, history, res, opts)
        return res

    def render_failure(self, test, history, res, opts) -> None:
        """Render linear.svg for an invalid analysis (checker.clj:209-213,
        knossos.linear.report). Called directly from check(), and by
        independent.checker per failing key with that key's
        subdirectory opts."""
        if test.get("store") is None:
            return
        try:
            from . import linear_svg
            linear_svg.render_analysis(test, res, history, opts)
        except Exception:  # rendering must never mask the verdict
            import logging
            logging.getLogger(__name__).warning(
                "linear.svg render failed", exc_info=True)

    def check_batch(self, test, histories: list[list], opts,
                    stats_out: list | None = None) -> list[dict]:
        """Check many histories at once — the TPU batch path used by
        `independent.checker` to shard per-key subhistories across the
        device mesh instead of pmapping JVM threads.

        `stats_out` (a list, JEPSEN_TPU_KERNEL_STATS) is extended with
        one per-history search-telemetry dict — WGL configs/backtracks
        on the CPU engine, frontier/grid occupancy on the device
        kernels; None per history on the race backend (whichever
        engine wins owns the wall clock, so neither's counters are
        authoritative).

        Device routing is tiered: (1) the dense-bitset config-grid
        kernel (`.knossos.dense`) — exact verdicts, no frontier
        overflow — for histories inside its slot/value grid budget;
        (2) histories past the grid (e.g. >14 concurrently-pending
        ops) route to the bounded sorted-frontier kernel
        (`.knossos.kernels`), whose cost scales with the frontier
        arena, not 2^slots; its rare ":frontier-overflow" unknowns
        (3) re-run on the CPU WGL oracle, as does anything not
        register-shaped at all. The kernels implement CAS-register
        semantics from a nil initial state, so any other model routes
        to CPU wholesale. Verdicts only ever degrade toward the
        oracle, never diverge from it."""
        # Model eligibility first: resolving an auto backend may probe
        # the hardware (bounded, but up to JEPSEN_TPU_PROBE_TIMEOUT on a
        # dead transport) — pointless when only the CPU path can apply.
        def cpu_all():
            out = []
            for hs in histories:
                sd: dict | None = {} if stats_out is not None else None
                out.append(self._cpu(hs, search_stats=sd))
                if stats_out is not None:
                    stats_out.append(sd or None)
            return out

        if not (type(self.model) is model.CASRegister
                and self.model.value is None):
            return cpu_all()
        from ..devices import resolve_backend
        backend = self.backend
        if backend == "auto":
            # the CLI communicates --backend via JEPSEN_TPU_BACKEND and
            # constructs checkers with "auto": honor an env-requested
            # race here, where the race is implemented
            from .. import gates
            backend = gates.get("JEPSEN_TPU_BACKEND") or "auto"
        if backend == "race":
            if resolve_backend("auto") != "tpu":
                return cpu_all()
            if stats_out is not None:
                stats_out.extend(None for _ in histories)
            return self._race(histories)
        if resolve_backend(self.backend) != "tpu":
            return cpu_all()
        return self._device_batch(histories, stats_out=stats_out)

    #: losing race dispatches still draining in background threads;
    #: joined at interpreter exit so teardown can't kill a thread
    #: mid-XLA-dispatch (pthread aborts with "exception not rethrown")
    _race_threads: set = set()
    _race_atexit = [False]

    def _race(self, histories: list[list]) -> list[dict]:
        """knossos.competition's racing rule, engine-scaled: run the
        tiered device pipeline and the CPU engine concurrently and
        return whichever finishes the WHOLE batch first (verdicts are
        identical by the parity contract, so the race only decides
        wall-clock). The reference races wgl against linear the same
        way and takes the first future (knossos competition.clj via
        jepsen checker.clj:188-219); like there, the loser can't be
        interrupted mid-flight — the CPU side stops at the next
        history boundary, a losing device dispatch runs its course in
        the background. Racing doubles host work while both run, so
        it's an explicit backend choice for multi-core hosts, not the
        auto default."""
        import threading

        n = len(histories)
        cpu_res: list = [None] * n
        stop = threading.Event()
        cpu_done = threading.Event()
        dev_out: list = []
        dev_done = threading.Event()
        turn = threading.Event()

        cpu_exc: list = []

        def cpu_side():
            try:
                for i, hs in enumerate(histories):
                    if stop.is_set():
                        return
                    cpu_res[i] = self._cpu(hs)
            except Exception as e:   # propagate via the main thread
                cpu_exc.append(e)
            finally:
                Linearizable._race_threads.discard(
                    threading.current_thread())
            cpu_done.set()
            turn.set()

        def dev_side():
            try:
                dev_out.append(self._device_batch(histories))
            except Exception as e:   # device failure: CPU decides
                dev_out.append(e)
            finally:
                Linearizable._race_threads.discard(
                    threading.current_thread())
            dev_done.set()
            turn.set()

        tc = threading.Thread(target=cpu_side, daemon=True,
                              name="linearizable-race-cpu")
        td = threading.Thread(target=dev_side, daemon=True,
                              name="linearizable-race-dev")
        if not Linearizable._race_atexit[0]:
            Linearizable._race_atexit[0] = True
            import atexit

            def _drain():
                for t in list(Linearizable._race_threads):
                    t.join(timeout=120)
            atexit.register(_drain)
        tc.start()
        Linearizable._race_threads.add(tc)
        td.start()
        Linearizable._race_threads.add(td)
        while True:
            turn.wait()
            turn.clear()
            dev_ok = (dev_done.is_set() and dev_out
                      and not isinstance(dev_out[0], Exception))
            if dev_ok:
                stop.set()
                return dev_out[0]
            if cpu_done.is_set():
                if cpu_exc:
                    # CPU side failed; the device result decides, or
                    # the failure propagates as it would un-raced
                    dev_done.wait()
                    if dev_out and not isinstance(dev_out[0], Exception):
                        return dev_out[0]
                    raise cpu_exc[0]
                return list(cpu_res)
            # device errored first: wait for the CPU side to finish

    def _device_batch(self, histories: list[list],
                      stats_out: list | None = None) -> list[dict]:
        """The tiered device pipeline (see check_batch's docstring);
        callers have already checked model eligibility. With
        `stats_out`, each tier reports its own search telemetry
        (grid/frontier occupancy, rounds; the CPU oracle's WGL
        counters for fallbacks)."""
        from .knossos import dense, kernels
        from .knossos import encode as kenc
        with_stats = stats_out is not None
        stats: list = [None] * len(histories)
        dense_encs, dense_idx = [], []
        front_encs, front_idx = [], []
        cpu_idx = []
        for i, hs in enumerate(histories):
            try:
                dense_encs.append(dense.encode_dense_history(hs))
                dense_idx.append(i)
            except kenc.EncodingError:
                try:
                    enc = kenc.encode_register_history(hs)
                    # Feasibility gate: every simultaneously-open
                    # write or unknown-value read doubles the frontier
                    # (they apply in any order); open cas ops and
                    # known-value reads prune on state mismatch —
                    # empirically contributing about half a doubling
                    # each. If the estimated closure can't fit the
                    # arena, the kernel would burn a full device pass
                    # only to report overflow (round 4's
                    # tiers={"wgl": 8}); predictably-infeasible
                    # histories go straight to the oracle. The
                    # kernel's own overflow fallback still catches the
                    # ones the estimate admits.
                    budget = 2 * (max(self.frontier, 1).bit_length() - 1)
                    if enc.half_doublings_peak > budget:
                        cpu_idx.append(i)
                    else:
                        front_encs.append(enc)
                        front_idx.append(i)
                except kenc.EncodingError:
                    cpu_idx.append(i)
        results: list[dict | None] = [None] * len(histories)
        if dense_encs:
            ds: list | None = [] if with_stats else None
            for j, (i, r) in enumerate(zip(
                    dense_idx,
                    dense.check_encoded_dense_batch(dense_encs,
                                                    stats_out=ds))):
                results[i] = r
                if ds is not None:
                    stats[i] = ds[j]
        if front_encs:
            fs: list | None = [] if with_stats else None
            for j, (i, r) in enumerate(zip(
                    front_idx,
                    kernels.check_encoded_batch(
                        front_encs, frontier=self.frontier,
                        stats_out=fs))):
                if r.get("valid?") == "unknown":
                    cpu_idx.append(i)  # overflow: exact answer from CPU
                else:
                    results[i] = r
                    if fs is not None:
                        stats[i] = fs[j]
        for i in cpu_idx:
            sd: dict | None = {} if with_stats else None
            results[i] = self._cpu(histories[i], search_stats=sd)
            if with_stats:
                stats[i] = sd or None
        if with_stats:
            stats_out.extend(stats)
        return results  # type: ignore[return-value]


def linearizable(m: model.Model | None = None,
                 algorithm: str = "competition",
                 backend: str = "auto", **kw) -> Checker:
    return Linearizable(m, algorithm=algorithm, backend=backend, **kw)


# ---------------------------------------------------------------------------
# Plot/report checkers live in submodules (perf, clock, timeline) but are
# part of the reference's jepsen.checker namespace (checker.clj:797-837) —
# re-export the constructors here. Imported lazily to keep matplotlib off
# the fast path.
# ---------------------------------------------------------------------------

def _submodule(name: str):
    import importlib
    return importlib.import_module(f"{__name__}.{name}")


def latency_graph(nemeses=None) -> Checker:
    return _submodule("perf").latency_graph(nemeses)


def rate_graph(nemeses=None) -> Checker:
    return _submodule("perf").rate_graph_checker(nemeses)


def perf_checker(opts: dict | None = None) -> Checker:
    """Composite latency+rate plots (checker.clj:822-829). Named
    perf_checker because `checker.perf` is the helper submodule, as in the
    reference's jepsen.checker.perf namespace."""
    return _submodule("perf").perf(opts)


def clock_plot() -> Checker:
    return _submodule("clock").clock_plot()


def timeline_checker() -> Checker:
    """Timeline HTML checker; the submodule `checker.timeline` mirrors
    jepsen.checker.timeline (whose constructor is `html`)."""
    return _submodule("timeline").html()
