"""History → tensor encoder for list-append analysis.

This is the TPU build's replacement for the reference's `txn/` micro-op
parser (txn/src/jepsen/txn.clj) plus the version-order inference inside
Elle's list-append checker: the host-side "tokenizer" that digests ragged
mop lists once, detects every anomaly that needs raw list data
(G1a/G1b/internal/duplicates/incompatible-order/dirty-update), and emits
compact integer tensors from which the device kernels build ww/wr/rw
dependency edges and run cycle detection.

Key design fact (why the tensors are small): in list-append, every
successful read of key k returns a *prefix* of k's final append order. So
once version orders are inferred, a read is fully described by the
*length* of the list it saw (= the version position of its last element),
and an append by the *position* of its value. Edge construction then needs
only (txn, key, pos) triples — no ragged data on device.

Versions are 1-based; position 0 is the initial empty list. Position -1
marks appends never observed by any read (unordered; they generate no
edges).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ... import history as h
from . import txn as t

OK, INFO, FAIL = 0, 1, 2  # txn status codes

# Completion index base for indeterminate txns in realtime ordering: they
# never completed, so nothing can be realtime-after them. Each info row
# gets NEVER_COMPLETED + row so completion keys stay *distinct* (the
# device kernel's successor-by-min formulation and the CPU oracle's stable
# sort must agree on process order between two crashed txns). Base + row
# fits in int32 so values survive JAX's int64->int32 cast without x64.
NEVER_COMPLETED = np.int64(2**30)


def effective_complete_index(status: np.ndarray,
                             complete_index: np.ndarray) -> np.ndarray:
    """Completion keys for ordering: real index for committed txns, a
    distinct beyond-everything key for indeterminate ones."""
    rows = np.arange(len(status), dtype=np.int64)
    return np.where(status == INFO, NEVER_COMPLETED + rows, complete_index)


@dataclass
class EncodedHistory:
    """One history's worth of device-ready facts + host-detected anomalies."""

    n: int = 0                      # graph txns (committed + indeterminate)
    n_keys: int = 0
    max_pos: int = 0                # longest version chain over all keys
    # (txn_row, key, pos) triples; pos semantics per module docstring.
    appends: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 3), np.int32))
    # (txn_row, key, pos-of-last-element) triples for external reads.
    reads: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 3), np.int32))
    status: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int32))      # OK | INFO
    process: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int32))
    invoke_index: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int64))
    complete_index: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int64))
    op_index: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int64))      # witness mapping
    # Host-detected anomalies: name -> list of witness dicts.
    anomalies: dict = field(default_factory=dict)
    # key id -> original key, row -> completion op (for witnesses/debug)
    key_names: list = field(default_factory=list)
    txn_ops: list = field(default_factory=list)


def _note(anomalies: dict, name: str, witness: dict) -> None:
    anomalies.setdefault(name, []).append(witness)


def lean_anomalies(enc: EncodedHistory) -> dict:
    """Witnesses reduced to the environment-independent lean shape the
    native ingest (native/hist_encode.cc) emits — ints and key names
    only, no op dicts. Same anomaly names, counts, and order either
    way, so persisted batch-sweep artifacts don't depend on which
    encoder ran (the Python path's full witnesses embed op dicts the
    native path never materializes). Call BEFORE dropping txn_ops:
    rows are recovered from witness-op identity."""
    if not enc.anomalies:       # clean history: skip the row-map build
        return {}
    row_of = {id(op): r for r, op in enumerate(enc.txn_ops)}

    def row(w, k="op"):
        return row_of.get(id(w.get(k)), -1)

    out: dict = {}
    for name, wits in enc.anomalies.items():
        lw = []
        for w in wits:
            if name == "duplicate-appends":
                lw.append({"key": w["key"], "value": w["value"],
                           "row": row(w)})
            elif name == "internal":
                lw.append({"row": row(w), "key": w["mop"][1]})
            elif name == "duplicate-elements":
                lw.append({"key": w["key"], "row": row(w)})
            elif name == "incompatible-order":
                lw.append({"key": w["key"], "row": row(w, "b-op")})
            elif name in ("G1a", "dirty-update"):
                writer = w.get("writer") or {}
                lw.append({"key": w["key"], "value": w["value"],
                           "writer-index": writer.get("index", -1)})
            elif name == "G1b":
                lw.append({"key": w["key"], "row": row(w)})
            elif name == "phantom-read":
                lw.append({"key": w["key"], "value": w["value"]})
            else:  # unknown anomaly class: pass through untouched
                lw.append(w)
        out[name] = lw
    return out


def _check_internal(txn: list, op: dict, anomalies: dict) -> None:
    """Within-txn consistency: a read must reflect the txn's own prior
    reads and appends on that key (Elle's :internal anomaly)."""
    known: dict = {}     # key -> exact list the txn must now observe
    appended: dict = {}  # key -> own appends before the first read of key
    for mf, k, v in txn:
        if mf == "r":
            if v is None:
                continue
            v = list(v)
            if k in known:
                if v != known[k]:
                    _note(anomalies, "internal",
                          {"op": op, "mop": ["r", k, v],
                           "expected": known[k]})
            elif k in appended:
                suffix = appended[k]
                if v[len(v) - len(suffix):] != suffix:
                    _note(anomalies, "internal",
                          {"op": op, "mop": ["r", k, v],
                           "expected": ["..."] + suffix})
            known[k] = v
            appended.pop(k, None)
        else:
            if k in known:
                known[k] = known[k] + [v]
            else:
                appended.setdefault(k, []).append(v)


def _longest_prefix_order(reads: list[tuple], anomalies: dict, key: Any) -> list:
    """Infer the version order for one key from its observed read lists.
    All reads must be prefixes of the longest; mismatches flag
    :incompatible-order (we keep the longest list as best-effort order)."""
    longest: list = []
    longest_op = None
    for op, v in reads:
        if len(v) > len(longest):
            longest, longest_op = list(v), op
    for op, v in reads:
        if list(v) != longest[: len(v)]:
            _note(anomalies, "incompatible-order",
                  {"key": key, "a": longest, "b": list(v),
                   "a-op": longest_op, "b-op": op})
    return longest


def encode_history(history: list[dict]) -> EncodedHistory:
    """Digest a list-append history into an EncodedHistory."""
    history = h.index(history)
    enc = EncodedHistory()
    anomalies = enc.anomalies

    # --- pair invocations with completions; bucket txns by fate ----------
    # (fused single-pass pairing + filtering, shared with the wr
    # encoder — t.bucket_txn_pairs)
    committed, indeterminate, failed = t.bucket_txn_pairs(history)

    # --- key interning ----------------------------------------------------
    key_ids: dict = {}

    def kid(k: Any) -> int:
        i = key_ids.get(k)
        if i is None:
            i = len(key_ids)
            key_ids[k] = i
            enc.key_names.append(k)
        return i

    # --- graph txn rows: committed first, then indeterminate -------------
    rows: list[dict] = []   # row facts
    for inv, comp in committed:
        txn = t.mops(comp)
        rows.append({"txn": txn, "status": OK, "inv": inv,
                     "op": comp, "wbk": t.writes_by_key(txn)})
    for inv in indeterminate:
        txn = t.mops(inv)
        rows.append({"txn": txn, "status": INFO, "inv": inv,
                     "op": inv, "wbk": t.writes_by_key(txn)})
    enc.n = len(rows)

    # --- writer index: (key, value) -> row --------------------------------
    writer_of: dict = {}
    multi_append: set = set()
    for r_i, row in enumerate(rows):
        for k, vals in row["wbk"].items():
            for v in vals:
                if (k, v) in writer_of:
                    _note(anomalies, "duplicate-appends",
                          {"key": k, "value": v, "op": row["op"]})
                    multi_append.add((k, v))
                else:
                    writer_of[(k, v)] = r_i
    failed_writes: dict = {}
    for inv in failed:
        for k, vals in t.writes_by_key(t.mops(inv)).items():
            for v in vals:
                failed_writes[(k, v)] = inv

    # --- internal consistency + read collection --------------------------
    reads_by_key: dict = {}
    for row in rows:
        if row["status"] != OK:
            continue
        _check_internal(row["txn"], row["op"], anomalies)
        for mf, k, v in row["txn"]:
            if mf == "r" and v is not None:
                reads_by_key.setdefault(k, []).append((row["op"], v))
                # duplicate elements inside one read. The C-speed
                # set(vals) screen is exact for the non-dup case; a
                # suspected dup re-checks with (type, v) pairs so
                # Python's cross-type equality (1 == True == 1.0)
                # can't flag a legitimate [1, True] read. repr stays
                # the fallback for unhashables.
                vals = list(v)
                try:
                    dup = len(vals) != len(set(vals)) and \
                        len(vals) != len({(type(x), x) for x in vals})
                except TypeError:
                    dup = len(vals) != len(set(map(repr, vals)))
                if dup:
                    _note(anomalies, "duplicate-elements",
                          {"key": k, "value": vals, "op": row["op"]})

    # --- version orders ---------------------------------------------------
    version_pos: dict = {}       # (key, value) -> 1-based position
    version_chain: dict = {}     # key -> longest list
    for k, rds in reads_by_key.items():
        order = _longest_prefix_order(rds, anomalies, k)
        version_chain[k] = order
        for i, v in enumerate(order):
            version_pos[(k, v)] = i + 1
        enc.max_pos = max(enc.max_pos, len(order))

    # --- aborted / phantom / dirty observations --------------------------
    for k, order in version_chain.items():
        for i, v in enumerate(order):
            if (k, v) in writer_of:
                continue
            if (k, v) in failed_writes:
                _note(anomalies, "G1a",
                      {"key": k, "value": v, "writer": failed_writes[(k, v)]})
                if i + 1 < len(order):
                    # Committed appends built on top of an aborted write.
                    _note(anomalies, "dirty-update",
                          {"key": k, "value": v,
                           "writer": failed_writes[(k, v)]})
            else:
                _note(anomalies, "phantom-read",
                      {"key": k, "value": v})

    # --- G1b: external reads of intermediate versions ---------------------
    # A txn's non-final append to a key is an intermediate state; any other
    # txn's read ending there observed a state that "never existed".
    intermediate: set = set()
    for row_i, row in enumerate(rows):
        for k, vals in row["wbk"].items():
            for v in vals[:-1]:
                intermediate.add((k, v, row_i))

    # --- emit tensors -----------------------------------------------------
    appends: list[tuple] = []
    reads: list[tuple] = []
    for r_i, row in enumerate(rows):
        for k, vals in row["wbk"].items():
            for v in vals:
                pos = version_pos.get((k, v), -1)
                if (k, v) in multi_append:
                    pos = -1  # ambiguous writer: generates no edges
                appends.append((r_i, kid(k), pos))
        if row["status"] != OK:
            continue
        for k, v in t.ext_reads(row["txn"]).items():
            if v is None:
                continue
            vals = list(v)
            pos = len(vals)
            if vals:
                last = vals[-1]
                if version_pos.get((k, last)) != pos:
                    pos = -1  # incompatible read: no edges from it
                w = writer_of.get((k, last))
                if w is not None and (k, last, w) in intermediate \
                        and w != r_i:
                    _note(anomalies, "G1b",
                          {"key": k, "value": vals, "op": row["op"]})
            reads.append((r_i, kid(k), pos))

    enc.n_keys = len(key_ids)
    enc.appends = np.asarray(appends or np.zeros((0, 3)), np.int32).reshape(-1, 3)
    enc.reads = np.asarray(reads or np.zeros((0, 3)), np.int32).reshape(-1, 3)
    enc.status = np.asarray([r["status"] for r in rows], np.int32)
    enc.process = np.asarray(
        [r["inv"].get("process", -1) if isinstance(r["inv"].get("process"), int)
         else -1 for r in rows], np.int32)
    enc.invoke_index = np.asarray(
        [r["inv"].get("index", -1) for r in rows], np.int64)
    enc.complete_index = np.asarray(
        [r["op"].get("index", -1) for r in rows], np.int64)
    enc.op_index = enc.complete_index
    enc.txn_ops = [r["op"] for r in rows]
    return enc
