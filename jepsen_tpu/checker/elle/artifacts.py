"""Elle-style anomaly artifacts: an `elle/` directory under the run
dir with one file per anomaly class plus an anomalies.edn summary.

The reference's checker emits explained anomalies into an elle/
subdirectory of the store (jepsen/src/jepsen/tests/cycle/append.clj:
17-22, elle's :directory option); re-checking a stored run must leave
the same breadcrumbs here. Each <anomaly>.txt renders the witness
cycles txn-by-txn; flag-only anomalies (host-detected, e.g. internal)
render their op evidence from the encoded history's notes.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Callable

from ... import edn

log = logging.getLogger(__name__)


def _render_txn(op: Any) -> str:
    if isinstance(op, dict):
        return edn.dumps({k: op.get(k) for k in
                          ("process", "type", "f", "value", "index")
                          if k in op}, keywordize=True)
    return repr(op)


def render_anomaly(name: str, witness: Any) -> str:
    """One anomaly class -> human-readable explanation text."""
    lines = [f"Anomaly: {name}", ""]
    if witness is True:
        lines.append("Present (flag-only: no witness cycle recorded).")
    elif isinstance(witness, list):
        for i, w in enumerate(witness):
            if isinstance(w, dict) and "cycle-txns" in w:
                lines.append(f"Cycle {i + 1}:")
                cycle = w["cycle-txns"]
                closed = len(cycle) > 1 and cycle[0] == cycle[-1]
                for op in (cycle[:-1] if closed else cycle):
                    lines.append(f"  {_render_txn(op)}")
                if cycle:
                    lines.append(f"  ... and back to "
                                 f"{_render_txn(cycle[0])}")
            else:
                lines.append(f"Witness {i + 1}: {w!r}")
            lines.append("")
    else:
        lines.append(repr(witness))
    lines.append("")
    return "\n".join(lines)


def write_artifacts(anomalies: dict, directory: Path) -> Path:
    """Write elle/-style artifacts for a verdict's anomalies map.
    Returns the directory."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    summary = {}
    for name, witness in sorted(anomalies.items()):
        (directory / f"{name}.txt").write_text(
            render_anomaly(name, witness))
        summary[name] = (True if witness is True
                         else f"{len(witness)} witness(es)"
                         if isinstance(witness, list) else repr(witness))
    (directory / "anomalies.edn").write_text(
        edn.dumps(summary, keywordize=True) + "\n")
    return directory


def store_dir(test: dict, opts: dict | None) -> Path | None:
    """The elle/ directory for this (possibly independent-keyed) check,
    or None when the test has no store. Shares perf's
    subdirectory-resolution rule so per-key layouts can't drift."""
    from ..perf import store_path
    return store_path(test, opts or {}, "elle")


def device_host_refine(device_cycles: dict,
                       host_fn: Callable[[], dict]) -> tuple[dict, dict]:
    """Turn device anomaly FLAGS into host witness cycles. Parity runs
    both ways (SURVEY.md §4.3): a device flag the host can't reproduce
    stays in the result (flag-only), and an anomaly the host finds that
    the device missed is equally a divergence — both are reported,
    since either direction means one of the two paths is wrong."""
    host = host_fn()
    device_only = sorted(set(device_cycles) - set(host))
    host_only = sorted(set(host) - set(device_cycles))
    merged = dict(host)
    for name in device_only:
        log.warning("device flagged %s but host pass found no witness "
                    "— keeping the flag (kernel/host divergence?)", name)
        merged[name] = True
    for name in host_only:
        log.warning("host pass found %s the device did not flag "
                    "(kernel false negative?)", name)
    divergence = {}
    if device_only:
        divergence["device-only"] = device_only
    if host_only:
        divergence["host-only"] = host_only
    return merged, divergence


def attach(verdict: dict, divergent: dict | list, test: dict,
           opts: dict | None) -> dict:
    """Record divergences and write the elle/ artifacts for any
    anomalies in the verdict."""
    if divergent:
        verdict["device-host-divergence"] = divergent
    if verdict.get("anomalies"):
        d = store_dir(test, opts)
        if d is not None:
            write_artifacts(verdict["anomalies"], d)
            verdict["elle-dir"] = str(d)
    return verdict
