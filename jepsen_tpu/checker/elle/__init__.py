"""Elle-style transactional anomaly checking (list-append and friends).

Public API mirrors the reference's jepsen.tests.cycle.append checker
(jepsen/src/jepsen/tests/cycle/append.clj:11-22, backed by the external
elle 0.1.0 dependency): a Checker over histories whose op values are
transactions of [f k v] micro-ops.

Two interchangeable backends produce cycle verdicts:

  backend="cpu"  hash-join edges + Tarjan SCC + BFS witnesses (the oracle)
  backend="tpu"  dense scatter + MXU transitive closure, batched on device

Verdict parity between them is the acceptance criterion (SURVEY.md §4.3);
`checker.elle.kernels.check_encoded_batch` is the batch entry point the
CLI's analyze-store path uses to sweep thousands of stored histories.
"""

from __future__ import annotations

from typing import Any, Iterable

from .. import Checker
from . import graph as g
from .encode import EncodedHistory, encode_history

# NOTE: `kernels` (the jax/device backend) is imported lazily where
# used: this package init is on every ingest pool WORKER's bootstrap
# path (spawn re-imports it per process to encode histories into
# numpy tensors), and an eager jax import costs each worker ~2s of
# pure interpreter startup it never uses — across a sweep's pool
# that is more wall clock than the encoding itself.

# Anomalies that invalidate a history regardless of requested level —
# they indicate corrupted data structures, not isolation-level choices.
ALWAYS_INVALID = frozenset({
    "internal", "incompatible-order", "duplicate-elements", "dirty-update",
    "phantom-read", "duplicate-appends", "G0",
})

ANOMALY_EXPANSION = {
    "G0": {"G0"},
    "G1": {"G0", "G1a", "G1b", "G1c"},
    "G1a": {"G1a"},
    "G1b": {"G1b"},
    "G1c": {"G1c"},
    "G2": {"G-single", "G2-item"},
    "G-single": {"G-single"},
    "G2-item": {"G2-item"},
}


def expand_anomalies(wanted: Iterable[str]) -> frozenset:
    out: set = set()
    for a in wanted:
        out |= ANOMALY_EXPANSION.get(a, {a})
    return frozenset(out)


def cycle_anomalies_cpu(enc: EncodedHistory, realtime: bool = False,
                        process_order: bool = False) -> dict:
    edges = g.build_edges(enc, process_order=process_order, realtime=realtime)
    return g.classify_cycles(enc.n, edges)


def cycle_anomalies_tpu(enc: EncodedHistory, realtime: bool = False,
                        process_order: bool = False) -> dict:
    from . import kernels
    return kernels.check_encoded_batch(
        [enc], realtime=realtime, process_order=process_order)[0]


def render_verdict(enc: EncodedHistory, cycles: dict,
                   prohibited: frozenset) -> dict:
    """Combine host-detected and cycle anomalies into a checker verdict."""
    anomalies: dict = dict(enc.anomalies)
    for name, witness in cycles.items():
        if witness is True:
            anomalies[name] = True
        else:
            anomalies[name] = [
                {"cycle-txns": [_witness_op(enc, r) for r in witness]}]
    bad = {a for a in anomalies
           if a in prohibited or a in ALWAYS_INVALID}
    if enc.n == 0:
        return {"valid?": "unknown",
                "anomaly-types": ["empty-transaction-graph"],
                "anomalies": {}, "txn-count": 0}
    return {
        "valid?": not bad,
        "anomaly-types": sorted(anomalies),
        "anomalies": anomalies,
        "txn-count": enc.n,
        "key-count": enc.n_keys,
    }


def _witness_op(enc: EncodedHistory, row: int) -> Any:
    if 0 <= row < len(enc.txn_ops):
        return enc.txn_ops[row]
    return row


class AppendChecker(Checker):
    """Checker for list-append histories.

    Options:
      anomalies:      which anomaly classes to prohibit (default G1+G2,
                      like the reference wrapper append.clj:14-16)
      backend:        "auto" (device kernels when an accelerator is
                      reachable, else the CPU oracle) | "cpu" | "tpu"
      realtime:       add realtime (strict-serializability) edges
      process_order:  add per-process order edges
    """

    def __init__(self, anomalies: Iterable[str] = ("G1", "G2"),
                 backend: str = "auto", realtime: bool = False,
                 process_order: bool = False):
        self.prohibited = expand_anomalies(anomalies)
        self.backend = backend
        self.realtime = realtime
        self.process_order = process_order

    def check(self, test, history, opts):
        from ...devices import resolve_backend
        backend = resolve_backend(self.backend)
        enc = encode_history(history)
        find = (cycle_anomalies_tpu if backend == "tpu"
                else cycle_anomalies_cpu)
        cycles = find(enc, realtime=self.realtime,
                      process_order=self.process_order)
        from . import artifacts
        divergent: dict = {}
        if backend == "tpu" and cycles:
            # Device path returns anomaly FLAGS; flagged histories run
            # the host pass for witness cycles (rare positives — the
            # fast path stays on device).
            cycles, divergent = artifacts.device_host_refine(
                cycles, lambda: cycle_anomalies_cpu(
                    enc, realtime=self.realtime,
                    process_order=self.process_order))
        verdict = render_verdict(enc, cycles, self.prohibited)
        return artifacts.attach(verdict, divergent, test, opts)

    def render_failure(self, test, history, res, opts) -> None:
        """Per-key artifact hook: `independent.checker` calls this with
        the key's subdirectory opts for each invalid batch result, so
        batched dispatch still leaves elle/ witness artifacts for the
        keys that failed."""
        from . import artifacts
        artifacts.attach(res, res.get("device-host-divergence", {}),
                         test, opts)

    def check_batch(self, test, histories: list, opts) -> list[dict]:
        """Check MANY histories in one bucketed device sweep — the
        route `independent.checker` takes so per-key subhistories
        share dispatches (and the detect-then-classify two-pass)
        instead of fanning out over host threads. Flagged histories
        re-run the host oracle for witness cycles; verdicts match
        check() minus store artifacts (per-key artifact dirs are the
        independent layer's concern)."""
        from ...devices import resolve_backend
        backend = resolve_backend(self.backend)
        encs = [encode_history(h) for h in histories]
        kw = dict(realtime=self.realtime,
                  process_order=self.process_order)
        if backend != "tpu":
            return [render_verdict(e, cycle_anomalies_cpu(e, **kw),
                                   self.prohibited) for e in encs]
        from ... import parallel
        mesh = None
        try:
            mesh = parallel.make_mesh()
        except Exception:
            pass
        cycles_list = parallel.check_bucketed(encs, mesh, **kw)
        from . import artifacts
        out = []
        for enc, cycles in zip(encs, cycles_list):
            if hasattr(cycles, "verdict"):
                # a supervisor.Quarantined sentinel: the device sweep
                # abandoned this history (OOM backdown exhausted /
                # watchdog) — its validity is unknown, not a judgment
                out.append(cycles.verdict())
                continue
            divergent: dict = {}
            if cycles:
                cycles, divergent = artifacts.device_host_refine(
                    cycles,
                    lambda enc=enc: cycle_anomalies_cpu(enc, **kw))
            verdict = render_verdict(enc, cycles, self.prohibited)
            if divergent:  # either direction means a path is wrong
                verdict["device-host-divergence"] = divergent
            out.append(verdict)
        return out


def append_checker(anomalies: Iterable[str] = ("G1", "G2"),
                   backend: str = "auto", realtime: bool = False,
                   process_order: bool = False) -> Checker:
    return AppendChecker(anomalies, backend, realtime, process_order)
