"""Transaction micro-op (mop) helpers.

Counterpart of the reference's `txn/` subproject (txn/src/jepsen/txn.clj):
transactions are op :values of the form [[f k v] ...] where f is "append"
or "r" for list-append workloads, "w"/"r" for rw-register workloads.

This module is the seam the TPU build changes: `encode.py` builds on these
to translate ragged mop lists into fixed-width integer tensors.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable


def mops(op: dict) -> list:
    """The micro-ops of a txn op (empty list for nil values)."""
    v = op.get("value")
    return v if isinstance(v, (list, tuple)) else []


def is_txn_op(op: dict) -> bool:
    """Does this op's value look like a transaction (a list of [f k v]
    micro-ops)?"""
    v = op.get("value")
    if not isinstance(v, (list, tuple)):
        return False
    return all(isinstance(m, (list, tuple)) and len(m) == 3 for m in v)


def bucket_txn_pairs(history: Iterable[dict]
                     ) -> tuple[list, list, list]:
    """Pair txn invocations with their completions in ONE pass and
    bucket them by fate: -> (committed [(inv, ok-comp)...],
    indeterminate [inv...], failed [inv...]), each in invocation
    order. The fused equivalent of h.pairs() + is_invoke/is_client_op/
    is_txn_op filtering — this touches every op of a history and sits
    on the analyze-store/north-star ingest critical path, so both elle
    encoders share it. Expects an indexed history (h.index) so the
    order-restoring sorts have keys."""
    committed: list = []
    indeterminate: list = []
    failed: list = []
    pending: dict = {}                          # process -> txn invoke
    for o in history:
        ty = o.get("type")
        p = o.get("process")
        if ty == "invoke":
            # a new invoke by p supersedes a still-open one (malformed
            # histories only) — the old invoke never completed, so it
            # stays visible as indeterminate, as h.pairs() has it
            stale = pending.pop(p, None)
            if stale is not None:
                indeterminate.append(stale)
            if isinstance(p, int) and is_txn_op(o):
                pending[p] = o
            continue
        inv = pending.pop(p, None)
        if inv is None:
            continue
        if ty == "ok":
            committed.append((inv, o))
        elif ty == "fail":
            failed.append(inv)
        elif ty == "info":                      # crashed
            indeterminate.append(inv)
        # any other completion type: malformed — the invocation is
        # consumed but bucketed nowhere, exactly as the h.pairs()
        # formulation had it
    indeterminate.extend(pending.values())      # open at history end
    # strict ["index"]: an unindexed history would otherwise sort into
    # silent completion-order row numbering — fail loudly instead
    _inv_idx = lambda o: o["index"]
    committed.sort(key=lambda pair: _inv_idx(pair[0]))
    indeterminate.sort(key=_inv_idx)
    failed.sort(key=_inv_idx)
    return committed, indeterminate, failed


def reduce_mops(f: Callable, init: Any, history: Iterable[dict]) -> Any:
    """Fold f(state, op, [mf, k, v]) over every micro-op of every op
    (txn.clj:5-17)."""
    state = init
    for op in history:
        for mop in mops(op):
            state = f(state, op, mop)
    return state


def ext_reads(txn: list) -> dict:
    """Keys to values for a txn's external reads: values observed that the
    txn did not itself write first (txn.clj:19-34). Only the first access
    to a key counts; later reads see the txn's own effects."""
    ext: dict = {}
    seen: set = set()
    for mf, k, v in txn:
        if mf == "r" and k not in seen:
            ext[k] = v
        seen.add(k)
    return ext


def ext_writes(txn: list) -> dict:
    """Keys to final written values for a txn's external writes
    (txn.clj:36-47). For append txns the 'write' is the last appended
    element."""
    ext: dict = {}
    for mf, k, v in txn:
        if mf != "r":
            ext[k] = v
    return ext


def writes_by_key(txn: list) -> dict:
    """Key -> list of values written/appended by this txn, in order."""
    out: dict = {}
    for mf, k, v in txn:
        if mf != "r":
            out.setdefault(k, []).append(v)
    return out
