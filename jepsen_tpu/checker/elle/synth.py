"""Vectorized synthetic list-append batches for benchmarks and dry runs.

Builds packed, device-ready batches (the same layout `kernels.pack_batch`
produces) straight from numpy arithmetic — no per-op Python objects — so
benchmarks can exercise the device checking phase at sizes where building
50M op dicts on the host would dominate. The generated executions are
serial (one append + one external read per txn), hence anomaly-free;
`inject_g1c` corrupts chosen histories with a ww/wr cycle so the classify
path has positives to find.
"""

from __future__ import annotations

import numpy as np

from .kernels import BatchShape, pad_to


def synth_valid_batch(B: int, T: int, K: int, concurrency: int = 5,
                      seed: int = 0) -> dict:
    """A packed batch of B serial histories, T txns each over K keys.

    Txn i does [r k_r v][append k_a v]: the read is external (first
    access), observing exactly the appends committed by earlier txns.
    """
    rng = np.random.default_rng(seed)
    i = np.arange(T)
    rot = rng.integers(0, K, size=(B, 1))

    a_key = (i[None, :] + rot) % K                # [B,T]
    a_pos = i[None, :] // K + 1
    appends = np.stack(
        [np.broadcast_to(i, (B, T)), a_key, np.broadcast_to(a_pos, (B, T))],
        axis=-1).astype(np.int32)

    r_key = (i[None, :] * 7 + 3 + rot) % K
    # First txn appending r_key is row ((r_key - rot) mod K); appends to it
    # land every K txns. Number committed strictly before txn i:
    first = (r_key - rot) % K
    r_pos = np.where(i[None, :] > first, (i[None, :] - 1 - first) // K + 1, 0)
    reads = np.stack(
        [np.broadcast_to(i, (B, T)), r_key, r_pos], axis=-1).astype(np.int32)

    invoke_index = np.broadcast_to(2 * i, (B, T)).astype(np.int64)
    complete_index = np.broadcast_to(2 * i + 1, (B, T)).astype(np.int64)
    process = np.broadcast_to(i % concurrency, (B, T)).astype(np.int32)
    shape = BatchShape(n_txns=pad_to(T, 128), n_appends=pad_to(T, 8),
                       n_reads=pad_to(T, 8), n_keys=pad_to(K, 8),
                       max_pos=pad_to((T - 1) // K + 1, 8))
    return {
        "appends": _pad_triples(appends, shape.n_appends),
        "reads": _pad_triples(reads, shape.n_reads),
        "invoke_index": _pad_axis(invoke_index, shape.n_txns),
        "complete_index": _pad_axis(complete_index, shape.n_txns),
        "process": _pad_axis(process, shape.n_txns, fill=-1),
        "n_txns": np.full(B, T, np.int32),
        "shape": shape,
    }


def inject_g1c(batch: dict, which: np.ndarray, K: int) -> dict:
    """Corrupt selected histories with a ww+wr cycle: txn a appends (k,p),
    txn b = a+K appends (k,p+1); rewriting a's read to observe (k,p+1)
    adds wr b→a against the existing ww a→b."""
    reads = batch["reads"].copy()
    appends = batch["appends"]
    for h in np.atleast_1d(which):
        T = int(batch["n_txns"][h])
        a = T // 2
        b = a + K
        if b >= T:
            raise ValueError("history too short to inject a cycle")
        k = appends[h, a, 1]
        p = appends[h, a, 2]
        reads[h, a, 1] = k
        reads[h, a, 2] = p + 1
    return {**batch, "reads": reads}


def _pad_triples(a: np.ndarray, n: int) -> np.ndarray:
    B, t, _ = a.shape
    out = np.full((B, n, 3), -1, np.int32)
    out[:, :t] = a
    return out


def _pad_axis(a: np.ndarray, n: int, fill: int = 0) -> np.ndarray:
    B, t = a.shape
    out = np.full((B, n), fill, a.dtype)
    out[:, :t] = a
    return out


def synth_encoded_history(T: int, K: int = 64, concurrency: int = 10,
                          inject_cycle: bool = False):
    """A T-txn serial EncodedHistory straight from numpy — the
    100k-op-scale sibling of synth_append_history (no per-op dicts):
    txn i appends (key i%K, pos i//K+1) and externally reads a key it
    has seen. With ``inject_cycle``, one read observes its key one
    position ahead, creating a ww/wr (G1c) cycle."""
    from .encode import EncodedHistory

    i = np.arange(T, dtype=np.int32)
    appends = np.stack([i, i % K, i // K + 1], axis=-1)
    r_key = (i * 7 + 3) % K
    first = r_key.astype(np.int64)
    r_pos = np.where(i.astype(np.int64) > first,
                     (i - 1 - first) // K + 1, 0).astype(np.int32)
    reads = np.stack([i, r_key, r_pos], axis=-1)
    if inject_cycle:
        a = T // 2
        reads[a, 1] = appends[a, 1]
        reads[a, 2] = appends[a, 2] + 1
    return EncodedHistory(
        n=T, n_keys=K, max_pos=int(appends[:, 2].max()) + 1,
        appends=appends.astype(np.int32), reads=reads.astype(np.int32),
        status=np.zeros(T, np.int32),
        process=(i % concurrency).astype(np.int32),
        invoke_index=(2 * i).astype(np.int64),
        complete_index=(2 * i + 1).astype(np.int64))


def synth_append_history(T: int, K: int, seed: int = 0,
                         g1c: bool = False,
                         concurrency: int = 5) -> list[dict]:
    """A serial (anomaly-free) list-append history as op DICTS — the
    dict-level sibling of synth_valid_batch, for paths that start from
    encode_history (long-history checking, dry runs, tests). With
    ``g1c``, two mutually-observing txns on fresh keys are appended,
    forming a wr/wr cycle."""
    import random

    rng = random.Random(seed)
    hist: list[dict] = []
    state: dict[int, list[int]] = {}
    for i in range(T):
        k = rng.randrange(K)
        if rng.random() < 0.5:
            v = len(state.setdefault(k, [])) + 1
            state[k].append(v)
            val = [["append", k, v]]
        else:
            val = [["r", k, list(state.get(k, []))]]
        hist.append({"type": "invoke", "process": i % concurrency,
                     "f": "txn",
                     "value": [[m[0], m[1], None] for m in val],
                     "time": i * 1000, "index": 2 * i})
        hist.append({"type": "ok", "process": i % concurrency, "f": "txn",
                     "value": val, "time": i * 1000 + 500,
                     "index": 2 * i + 1})
    if g1c:
        t = T * 1000 + 1000
        ka, kb = K, K + 1
        hist += [
            {"type": "invoke", "process": 0, "f": "txn",
             "value": [["append", ka, None], ["r", kb, None]],
             "time": t, "index": len(hist)},
            {"type": "ok", "process": 0, "f": "txn",
             "value": [["append", ka, 1], ["r", kb, [1]]],
             "time": t + 2, "index": len(hist) + 1},
            {"type": "invoke", "process": 1, "f": "txn",
             "value": [["append", kb, None], ["r", ka, None]],
             "time": t + 1, "index": len(hist) + 2},
            {"type": "ok", "process": 1, "f": "txn",
             "value": [["append", kb, 1], ["r", ka, [1]]],
             "time": t + 3, "index": len(hist) + 3},
        ]
    return hist


def write_synth_store(root, B: int, T: int, K: int,
                      bad_every: int) -> list:
    """Materialize B serial list-append runs as history.jsonl dirs —
    the same execution shape as synth_encoded_history (txn i appends
    (key (i+rot)%K, pos i//K+1) and externally reads a key it has
    seen), written as raw JSON lines without per-op dict churn. Every
    `bad_every`-th history gets two adjacent txns reading EACH OTHER's
    appends (one of them a future observation): mutual wr edges — a
    G1c cycle for the classify pass to find, with no same-txn read
    that would trip the encoder's `internal` check instead. The ONE
    synthetic-store generator, shared by bench.py's north-star block
    and the `make bench-warm` gate so the two can't drift."""
    from pathlib import Path
    root = Path(root)
    dirs = []
    for h in range(B):
        rot = h % K
        corrupt = bad_every and h % bad_every == bad_every - 1
        a = T // 2
        lines = []
        for i in range(T):
            ak = (i + rot) % K
            ap = i // K + 1
            rk = (i * 7 + 3 + rot) % K
            first = (rk - rot) % K
            rp = (i - 1 - first) // K + 1 if i > first else 0
            if corrupt and i == a:          # reads txn a+1's append
                rk, rp = (a + 1 + rot) % K, (a + 1) // K + 1
            elif corrupt and i == a + 1:    # reads txn a's append
                rk, rp = (a + rot) % K, a // K + 1
            obs = list(range(1, rp + 1))
            p = i % 5
            lines.append(
                f'{{"type":"invoke","process":{p},"f":"txn",'
                f'"value":[["append",{ak},{ap}],["r",{rk},null]],'
                f'"time":{2 * i * 1000},"index":{2 * i}}}')
            lines.append(
                f'{{"type":"ok","process":{p},"f":"txn",'
                f'"value":[["append",{ak},{ap}],["r",{rk},{obs}]],'
                f'"time":{(2 * i + 1) * 1000},"index":{2 * i + 1}}}')
        d = root / f"run-{h:05d}"
        d.mkdir()
        (d / "history.jsonl").write_text("\n".join(lines) + "\n")
        dirs.append(d)
    return dirs
