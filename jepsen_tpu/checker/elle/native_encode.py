"""ctypes bridge to the native list-append ingest (native/hist_encode.cc).

`encode_history_file` parses + encodes a history.jsonl straight to an
EncodedHistory in C++, skipping json.loads and the Python dict walk —
the analyze-store sweep's dominant host cost (SURVEY.md §5.7). The
native side enforces a strict parity contract (see hist_encode.cc's
header): anything it can't reproduce byte-identically returns None and
the caller falls back to `store.load_history_dir` + `encode_history`.

Witnesses on this path are LEAN — plain-int dicts (key/value/row), no
op dicts — matching the batch sweep's lean=True contract where
`txn_ops` is dropped anyway. Anomaly names, counts, and note order are
identical to the Python encoder's (differentially fuzzed in
tests/test_native_encode.py).
"""

from __future__ import annotations

import ctypes
import json
import os
from pathlib import Path

import numpy as np

from ... import native_lib
from .encode import EncodedHistory

# anomaly row codes, per hist_encode.cc's ABI comment
_CODES = {
    1: "duplicate-appends",
    2: "internal",
    3: "duplicate-elements",
    4: "incompatible-order",
    5: "G1a",
    6: "dirty-update",
    7: "phantom-read",
    8: "G1b",
    9: "duplicate-writes",
}


def _np(ptr, n, dtype):
    """Copy n elements out of a ctypes pointer into a fresh array (the
    handle is freed right after, so views would dangle)."""
    if n == 0:
        return np.zeros(0, dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


def _witness(code: int, f0: int, f1: int, f2: int, f3: int,
             pre_names: list, wr: bool) -> dict:
    key = pre_names[f0] if 0 <= f0 < len(pre_names) else f0
    if code == 2:                       # internal (f0=row, f1=pre_key)
        k2 = pre_names[f1] if 0 <= f1 < len(pre_names) else f1
        return {"row": f0, "key": k2}
    if wr:
        if code == 5:                   # G1a: reader row + failed writer
            return {"key": key, "value": f1, "writer-index": f2,
                    "row": f3}
        if code == 8:                   # G1b (f1=row, f2=value)
            return {"key": key, "value": f2, "row": f1}
        # duplicate-writes / phantom-read: (key, value, row)
        return {"key": key, "value": f1, "row": f2}
    if code == 1:                       # duplicate-appends
        return {"key": key, "value": f1, "row": f2}
    if code in (3, 4, 8):               # dup-elements / incompat / G1b
        return {"key": key, "row": f1}
    if code in (5, 6):                  # G1a / dirty-update
        return {"key": key, "value": f1, "writer-index": f2}
    return {"key": key, "value": f1}    # phantom-read


def _write_sidecar(L, h, hist_path: Path, sidecar_path) -> None:
    """Persist the encoded sidecar straight from the native handle's
    buffers (store.py's flat layout, no Python round-trip). The layout
    version rides the target filename (`.v2.bin` = dispatch-shaped),
    which store.encoded_cache_path already resolved from the gate.
    Best-effort: a 0 return just leaves the run uncached."""
    if sidecar_path is None:
        return
    version = 2 if str(sidecar_path).endswith(".v2.bin") else 1
    try:
        L.jt_ha_write_sidecar(h, os.fsencode(str(hist_path)),
                              os.fsencode(str(sidecar_path)), version)
    except Exception:
        pass


def encode_history_file(path: str | os.PathLike,
                        sidecar_path: str | os.PathLike | None = None
                        ) -> EncodedHistory | None:
    """Encode one history.jsonl natively; None means "use the Python
    path" (lib unavailable, file absent, or unrepresentable content).
    `sidecar_path`, when given, also writes the encoded.v1 cache
    sidecar from the native buffers."""
    L = native_lib.hist_lib()
    if L is None:
        return None
    p = Path(path)
    if not p.is_file():
        return None
    h = L.jt_ha_encode_file(str(p).encode())
    if not h:
        return None
    try:
        _write_sidecar(L, h, p, sidecar_path)
        dims = (ctypes.c_int64 * 8)()
        L.jt_ha_dims(h, dims)
        n, n_keys, max_pos, n_app, n_rd, n_anom, json_len, _n_pre = dims
        enc = EncodedHistory()
        enc.n = int(n)
        enc.n_keys = int(n_keys)
        enc.max_pos = int(max_pos)
        enc.appends = _np(L.jt_ha_appends(h), n_app * 3,
                          np.int32).reshape(-1, 3)
        enc.reads = _np(L.jt_ha_reads(h), n_rd * 3,
                        np.int32).reshape(-1, 3)
        enc.status = _np(L.jt_ha_status(h), n, np.int32)
        enc.process = _np(L.jt_ha_process(h), n, np.int32)
        enc.invoke_index = _np(L.jt_ha_invoke_index(h), n, np.int64)
        enc.complete_index = _np(L.jt_ha_complete_index(h), n, np.int64)
        enc.op_index = enc.complete_index
        pre_names = json.loads(
            L.jt_ha_pre_key_names_json(h).decode("utf-8")) if json_len \
            else []
        kid_to_pre = _np(L.jt_ha_kid_to_pre(h), n_keys, np.int32)
        enc.key_names = [pre_names[i] for i in kid_to_pre]
        anom = _np(L.jt_ha_anomalies(h), n_anom * 5, np.int64).reshape(-1, 5)
        for code, f0, f1, f2, f3 in anom.tolist():
            name = _CODES.get(code)
            if name is None:            # ABI drift: don't guess
                return None
            enc.anomalies.setdefault(name, []).append(
                _witness(code, f0, f1, f2, f3, pre_names, wr=False))
        enc.txn_ops = []
        return enc
    finally:
        L.jt_ha_free(h)


def encode_wr_history_file(path: str | os.PathLike,
                           sidecar_path: str | os.PathLike | None = None):
    """Native sibling of wr.encode_wr_history with DEFAULT version-order
    flags (the analyze-store wr sweep's configuration); None means "use
    the Python path". `sidecar_path` as in encode_history_file."""
    from .wr import WrEncoded
    L = native_lib.hist_lib()
    if L is None:
        return None
    p = Path(path)
    if not p.is_file():
        return None
    h = L.jt_wr_encode_file(str(p).encode())
    if not h:
        return None
    try:
        _write_sidecar(L, h, p, sidecar_path)
        dims = (ctypes.c_int64 * 8)()
        L.jt_ha_dims(h, dims)
        n, key_count, _mp, _n_app, _n_rd, n_anom, json_len, n_edges = dims
        enc = WrEncoded()
        enc.n = int(n)
        enc.key_count = int(key_count)
        edges = _np(L.jt_ha_edges(h), n_edges * 3, np.int32).reshape(-1, 3)
        enc.edges = [(int(a), int(b), int(c)) for a, b, c in edges]
        enc.status = _np(L.jt_ha_status(h), n, np.int32)
        enc.process = _np(L.jt_ha_process(h), n, np.int32)
        enc.invoke_index = _np(L.jt_ha_invoke_index(h), n, np.int64)
        enc.complete_index = _np(L.jt_ha_complete_index(h), n, np.int64)
        pre_names = json.loads(
            L.jt_ha_pre_key_names_json(h).decode("utf-8")) if json_len \
            else []
        anom = _np(L.jt_ha_anomalies(h), n_anom * 5, np.int64).reshape(-1, 5)
        for code, f0, f1, f2, f3 in anom.tolist():
            name = _CODES.get(code)
            if name is None:
                return None
            enc.anomalies.setdefault(name, []).append(
                _witness(code, f0, f1, f2, f3, pre_names, wr=True))
        enc.txn_ops = []
        return enc
    finally:
        L.jt_ha_free(h)
