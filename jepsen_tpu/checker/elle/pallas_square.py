"""Pallas TPU kernel for the closure-squaring step — THE hot op.

The transitive-closure fixpoint (kernels._closure_batched) squares a
[B, T, T] boolean reachability matrix each round:

    m2 = (bf16(m) @ bf16(m)) > 0

On the XLA path that is three HBM passes per round: cast bool->bf16
(materialized), the matmul, and the f32->bool compare. This kernel
fuses all three: bool tiles are DMA'd to VMEM once, cast on the VPU,
accumulated on the MXU in an f32 VMEM scratch over the k-tiles, and
thresholded back to bool as they leave — one HBM read of m per operand
tile and one bool write, no bf16/f32 intermediates in HBM.

Grid is (B, i, j, k) with k innermost (sequential — "arbitrary"
semantics) so the accumulator scratch carries across the k loop of one
output tile; b/i/j are parallel. T must be a multiple of the tile (the
encoders pad T to 128 already).

Used by kernels._closure_batched on unsharded TPU dispatches;
mesh-sharded closures keep the XLA matmul so the compiler can insert
the dp/mp collectives. Correctness is pinned CPU-side via
interpret=True differential tests (tests/test_pallas_square.py) and on
hardware by the `-m tpu` tier.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on every platform; only lowering needs a TPU
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# Tests flip this to run the kernel through the Pallas interpreter on
# CPU (full-verdict parity without hardware); production leaves it off.
INTERPRET = False


def _square_kernel(a_ref, b_ref, out_ref, acc_ref, *, dot_dtype):
    @pl.when(pl.program_id(3) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    a = a_ref[0].astype(dot_dtype)
    b = b_ref[0].astype(dot_dtype)
    acc_ref[...] += jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        preferred_element_type=acc_ref.dtype)

    @pl.when(pl.program_id(3) == pl.num_programs(3) - 1)
    def _emit():
        out_ref[0] = acc_ref[...] > 0


@functools.partial(jax.jit, static_argnames=("tile", "interpret",
                                             "int8"))
def closure_square(m: jnp.ndarray, *, tile: int = 256,
                   interpret: bool = False,
                   int8: bool = False) -> jnp.ndarray:
    """One closure round: (cast(m) @ cast(m)) > 0 for m [B, T, T] bool —
    bf16 dots accumulated in f32 by default, or int8 dots accumulated
    in int32 (exact for boolean operands, ~2× MXU throughput on v5e):
    the fusion (VMEM residency) and the arithmetic (int8) are
    orthogonal levers, and this kernel stacks them.

    `tile` shrinks to T when T < tile; T must divide evenly by the
    effective tile (guaranteed by the 128-padding in the encoders)."""
    B, T, T2 = m.shape
    assert T == T2, m.shape
    t = tile if T % tile == 0 else 128  # encoders pad T to 128
    t = min(t, T)
    assert T % t == 0, (T, t)
    grid = (B, T // t, T // t, T // t)

    kwargs = {}
    if pltpu is not None and not interpret:
        try:
            kwargs["compiler_params"] = pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary"))
        except Exception:  # older API spellings: let the compiler infer
            pass
    dot_dtype = jnp.int8 if int8 else jnp.bfloat16
    acc_dtype = jnp.int32 if int8 else jnp.float32
    return pl.pallas_call(
        functools.partial(_square_kernel, dot_dtype=dot_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, t, t), lambda b, i, j, k: (b, i, k)),
            pl.BlockSpec((1, t, t), lambda b, i, j, k: (b, k, j)),
        ],
        out_specs=pl.BlockSpec((1, t, t), lambda b, i, j, k: (b, i, j)),
        out_shape=jax.ShapeDtypeStruct((B, T, T), jnp.bool_),
        scratch_shapes=[
            (pltpu.VMEM((t, t), acc_dtype) if pltpu is not None
             else pl.pallas_core.MemorySpace.ANY)  # pragma: no cover
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * B * T * T * T,
            bytes_accessed=m.size * 2 + m.size,
            transcendentals=0),
        interpret=interpret,
        **kwargs,
    )(m, m)


_works: dict[bool, bool] = {}


def pallas_available(int8: bool = False) -> bool:
    """True when the current default device is a real TPU AND the
    requested kernel variant actually compiles on it (verified once
    per process per variant with a tiny probe input, so a lowering
    regression — bf16 OR int8-specific — degrades the analysis path to
    the XLA matmul instead of breaking it). Interpret mode is for
    tests; running it in production on CPU would be slower than the
    XLA matmul."""
    cached = _works.get(int8)
    if cached is not None:
        return cached
    try:
        from ...devices import default_devices
        d = default_devices()[0]
        if getattr(d, "platform", "") not in ("tpu", "axon"):
            _works[int8] = False
            return False
        import numpy as np
        # 256 is divisible by both effective tiles, so this lowers the
        # same tile=256 configuration the production shapes use
        m = jnp.asarray(np.eye(256, dtype=bool)[None])
        out = np.asarray(closure_square(m, int8=int8))
        ok = bool((out == np.eye(256, dtype=bool)[None]).all())
        _works[int8] = ok
        if not ok:
            import logging
            logging.getLogger(__name__).warning(
                "pallas closure kernel (int8=%s) MISCOMPUTED its "
                "probe; using the XLA matmul path", int8)
    except Exception:  # pragma: no cover - hardware-specific
        import logging
        logging.getLogger(__name__).warning(
            "pallas closure kernel (int8=%s) failed its probe; using "
            "the XLA matmul path", int8, exc_info=True)
        _works[int8] = False
    return _works[int8]
