"""Dependency-graph construction and CPU cycle analysis.

Builds ww/wr/rw (and optional process/realtime) edges from an
EncodedHistory, then classifies cycles the way Elle does (reference dep:
elle 0.1.0, used at jepsen/src/jepsen/tests/cycle/append.clj:17-22; paper
arXiv:2003.10554):

  G0        cycle of only ww edges
  G1c       cycle of ww∪wr edges containing at least one wr
  G-single  cycle with exactly one rw (anti-dependency) edge
  G2-item   cycle with two or more rw edges

This CPU implementation (hash joins + iterative Tarjan + per-edge BFS) is
deliberately algorithm-independent from the TPU kernel (dense scatter +
MXU transitive closure) so the two serve as differential oracles for each
other. It also extracts witness cycles, which the device path does not.
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

from .encode import EncodedHistory, effective_complete_index

WW, WR, RW, PROC, RT = 0, 1, 2, 3, 4
EDGE_NAMES = {WW: "ww", WR: "wr", RW: "rw", PROC: "process", RT: "realtime"}


def build_edges(enc: EncodedHistory, process_order: bool = False,
                realtime: bool = False) -> list[tuple[int, int, int]]:
    """(src, dst, type) dependency edges between txn rows.

    ww: t1 appended version p, t2 appended p+1 (same key)
    wr: t1 appended version p, t2's external read observed p last
    rw: t1's external read ended at p, t2 appended p+1 (t1 "missed" t2)
    """
    edges: list[tuple[int, int, int]] = []
    writer: dict = {}  # (key, pos) -> row
    for r, k, p in enc.appends:
        if p > 0:
            writer[(int(k), int(p))] = int(r)
    for (k, p), r in writer.items():
        prev = writer.get((k, p - 1))
        if p > 1 and prev is not None and prev != r:
            edges.append((prev, r, WW))
    for r, k, p in enc.reads:
        r, k, p = int(r), int(k), int(p)
        if p < 0:
            continue  # incompatible read; no edge facts
        if p > 0:
            w = writer.get((k, p))
            if w is not None and w != r:
                edges.append((w, r, WR))
        nxt = writer.get((k, p + 1))
        if nxt is not None and nxt != r:
            edges.append((r, nxt, RW))
    # Indeterminate txns never completed: nothing is realtime-after them,
    # and they sort last (in row order) in their process's order.
    complete = effective_complete_index(enc.status, enc.complete_index)
    edges += order_edges(enc.n, enc.process, enc.invoke_index, complete,
                         process_order=process_order, realtime=realtime)
    return edges


def order_edges(n: int, process: np.ndarray, invoke_index: np.ndarray,
                effective_complete: np.ndarray, process_order: bool = False,
                realtime: bool = False) -> list[tuple[int, int, int]]:
    """Process-order / realtime edges from txn-row timing arrays — the
    single host-side implementation shared by every CPU oracle
    (list-append, rw-register). `effective_complete` must come from
    encode.effective_complete_index so indeterminate txns sort last with
    distinct keys, matching the device kernel's formulation."""
    edges: list[tuple[int, int, int]] = []
    if process_order:
        last_by_proc: dict = {}
        for row in np.argsort(effective_complete, kind="stable"):
            row = int(row)
            p = int(process[row])
            if p < 0:
                continue
            if p in last_by_proc:
                edges.append((last_by_proc[p], row, PROC))
            last_by_proc[p] = row
    if realtime:
        # t1 completed before t2 invoked. Already transitively closed, so
        # emit the full relation (CPU oracle scale only; the device builds
        # this densely via a broadcast compare).
        for i in range(n):
            for j in range(n):
                if j != i and effective_complete[j] < invoke_index[i]:
                    edges.append((j, i, RT))
    return edges


def adjacency(n: int, edges: Iterable[tuple[int, int, int]],
              types: set[int] | None = None) -> list[list[int]]:
    adj: list[list[int]] = [[] for _ in range(n)]
    for s, d, ty in edges:
        if types is None or ty in types:
            adj[s].append(d)
    return adj


# Above this node count, SCC dispatches to the C++ kernel when built
# (native/graph_algo.cc via native_lib); below it, ctypes/CSR setup costs
# more than the pure-Python walk.
NATIVE_SCC_THRESHOLD = 256


def tarjan_scc(n: int, adj: list[list[int]]) -> list[int]:
    """SCC id per node (ids arbitrary). Large graphs go to the native
    kernel; the pure-Python fallback handles the rest (and everything,
    when no compiler is around)."""
    if n >= NATIVE_SCC_THRESHOLD:
        from ... import native_lib
        out = native_lib.tarjan_scc(n, adj)
        if out is not None:
            return out
    return _tarjan_scc_py(n, adj)


def _tarjan_scc_py(n: int, adj: list[list[int]]) -> list[int]:
    """Iterative Tarjan: returns scc id per node (ids arbitrary)."""
    index = [-1] * n
    low = [0] * n
    on_stack = [False] * n
    stack: list[int] = []
    scc = [-1] * n
    counter = [0]
    scc_count = [0]
    for root in range(n):
        if index[root] != -1:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack[v] = True
            recurse = False
            for i in range(pi, len(adj[v])):
                w = adj[v][i]
                if index[w] == -1:
                    work[-1] = (v, i + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                elif on_stack[w]:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    scc[w] = scc_count[0]
                    if w == v:
                        break
                scc_count[0] += 1
            work.pop()
            if work:
                u, _ = work[-1]
                low[u] = min(low[u], low[v])
    return scc


def _bfs_path(adj: list[list[int]], src: int, dst: int) -> list[int] | None:
    """Shortest path src..dst (inclusive) or None."""
    if src == dst:
        return [src]
    prev = {src: None}
    frontier = [src]
    while frontier:
        nxt = []
        for v in frontier:
            for w in adj[v]:
                if w not in prev:
                    prev[w] = v
                    if w == dst:
                        path = [w]
                        while path[-1] != src:
                            path.append(prev[path[-1]])
                        return path[::-1]
                    nxt.append(w)
        frontier = nxt
    return None


def classify_cycles(n: int, edges: list[tuple[int, int, int]],
                    want_witnesses: bool = True) -> dict:
    """Find which cycle anomalies exist. Returns
    {name: witness-cycle-node-list | True} for each anomaly present.
    Realtime/process edges, when present, participate like ww edges do in
    the "no-antidependency" classes (they strengthen cycles)."""
    out: dict = {}
    base = {WW, PROC, RT}
    ww_adj = adjacency(n, edges, base)
    wwr_adj = adjacency(n, edges, base | {WR})
    full_adj = adjacency(n, edges, None)

    # G0: nontrivial SCC in the write-order graph.
    scc = tarjan_scc(n, ww_adj)
    counts = np.bincount(np.asarray(scc, np.int64), minlength=0) \
        if n else np.zeros(0, np.int64)
    g0_scc = {i for i, c in enumerate(counts) if c > 1}
    if g0_scc:
        if want_witnesses:
            s, d = next((s, d) for s, d, ty in edges
                        if ty in base and scc[s] == scc[d] and s != d
                        and scc[s] in g0_scc)
            path = _bfs_path(ww_adj, d, s)
            out["G0"] = path + [d] if path else True
        else:
            out["G0"] = True

    # G1c: wr edge inside an SCC of the ww∪wr graph.
    scc2 = tarjan_scc(n, wwr_adj)
    for s, d, ty in edges:
        if ty == WR and scc2[s] == scc2[d]:
            if want_witnesses:
                path = _bfs_path(wwr_adj, d, s)
                out["G1c"] = (path + [d]) if path else True
            else:
                out["G1c"] = True
            break

    # G-single / G2-item: per rw edge, can we get back without / only-with
    # further rw edges? One wwr BFS per edge; full-graph BFS only on miss.
    rw_edges = [(s, d) for s, d, ty in edges if ty == RW]
    if not want_witnesses and len(rw_edges) >= 64:
        # Batch the probes through the native BFS kernel when we only
        # need flags, not witness paths.
        from ... import native_lib
        back = native_lib.reach(n, wwr_adj, [(d, s) for s, d in rw_edges])
        if back is not None:
            if any(back):
                out["G-single"] = True
            misses = [(d, s) for (s, d), hit in zip(rw_edges, back)
                      if not hit]
            if misses:
                full_back = native_lib.reach(n, full_adj, misses) or ()
                if any(full_back):
                    out["G2-item"] = True
            return out
    for s, d in rw_edges:
        path = _bfs_path(wwr_adj, d, s)
        if path is not None:
            if "G-single" not in out:
                out["G-single"] = path + [d] if want_witnesses else True
        elif "G2-item" not in out:
            path = _bfs_path(full_adj, d, s)
            if path is not None:
                out["G2-item"] = path + [d] if want_witnesses else True
        if "G-single" in out and "G2-item" in out:
            break
    return out
