"""TPU device kernels for Elle-style cycle detection.

The north-star compute path (SURVEY.md §3.3, BASELINE.json): encoded
histories live in HBM as padded int32 tensors; dependency edges are built
with dense scatters; cycle detection runs as boolean transitive closure by
repeated matrix squaring — log2(T) bfloat16 matmuls that map straight onto
the MXU — and anomaly classes fall out of closure/edge intersections:

  G0        some ww edge (u,v) with v→u in closure(ww)
  G1c       some wr edge (u,v) with v→u in closure(ww|wr)
  G-single  some rw edge (u,v) with v→u in closure(ww|wr)
  G2-item   some rw edge (u,v) with v→u only in closure(ww|wr|rw)

There is exactly one implementation of the math, written batched over
[B,T,T] tensors with a `constrain` hook: `jepsen_tpu.parallel` passes a
sharding constraint (dp over histories × mp over closure-matmul columns)
and jit shardings; the single-device path passes identity. Realtime and
process-order edges fold into the ww class (they strengthen cycles without
adding anti-dependencies), masked to each history's live rows.

All matmuls accumulate in float32 (`preferred_element_type`) from bf16
operands: entries are 0/1 so any nonzero dot-product term keeps the
closure sound; magnitudes are re-thresholded to booleans every step.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ...devices import default_devices, ensure_platform_pin

ensure_platform_pin()
from ...util import pad_to_multiple
from .encode import EncodedHistory, effective_complete_index

# Flag bit positions in the kernel's output word.
G0, G1C, G_SINGLE, G2_ITEM, CYCLE = 0, 1, 2, 3, 4
FLAG_NAMES = {G0: "G0", G1C: "G1c", G_SINGLE: "G-single", G2_ITEM: "G2-item"}

#: Per-history search-stat row layout ([B, N_STATS] int32) the kernels
#: return alongside the verdict flags under JEPSEN_TPU_KERNEL_STATS —
#: the structural evidence behind a verdict (ISSUE 15):
#:
#:   ww/wr/rw_edges   distinct dependency edges per class, BEFORE the
#:                    power-of-two writer-chain shortcuts (so counts
#:                    match the CPU oracle's graph exactly);
#:   rt/proc_edges    realtime / process-order edges the kernel built
#:                    from the timing tensors;
#:   closure_rounds   squaring rounds the (final) closure actually ran
#:                    to its fixpoint for THIS history (vs the static
#:                    `closure_steps` bound the caller reports);
#:   cycle_round      first round at which a cycle became visible
#:                    (0 = present in the raw edge set; -1 = acyclic);
#:   scc_count/max/min  nontrivial SCCs of the full closure, their
#:                    largest and smallest member counts (0 = none);
#:   cycle_txns       txn rows participating in any cycle;
#:   margin           the decision-boundary margin: rounds of closure
#:                    work sustained before a cycle appeared
#:                    (= cycle_round for cyclic histories — high means
#:                    the cycle needs long paths, i.e. near-miss from
#:                    inside; = closure_rounds for valid ones — high
#:                    means deep dependency chains, near-miss from
#:                    outside). Together with the cyclic bit it orders
#:                    histories by distance to the decision boundary,
#:                    the signal the adversarial mutation search
#:                    (ROADMAP item 3) seeds from.
#:
#: The JEPSEN_TPU_KERNEL_STATS gate itself has ONE reader —
#: `obs.search.enabled()` — and the kernels never self-gate: callers
#: decide by passing `with_stats`/`stats_out`, so the off path stays
#: byte-identical (executables, dispatch keys, verdicts) with zero
#: gate reads on the dispatch hot path.
STAT_FIELDS = ("ww_edges", "wr_edges", "rw_edges", "rt_edges",
               "proc_edges", "closure_rounds", "cycle_round",
               "scc_count", "scc_max", "scc_min", "cycle_txns",
               "margin")
N_STATS = len(STAT_FIELDS)


def stats_row(row, *, n_txns: int, t_pad: int) -> dict:
    """One device stats row -> the per-history dict the analytics
    journal records: the named device fields plus the host-side
    geometry facts (bucket pad, the static closure bound, per-history
    pad waste in closure cells)."""
    out = {f: int(v) for f, v in zip(STAT_FIELDS, row)}
    out["n_txns"] = int(n_txns)
    out["t_pad"] = int(t_pad)
    out["closure_bound"] = closure_steps(t_pad)
    out["pad_waste_cells"] = int(t_pad) ** 2 - int(n_txns) ** 2
    return out

#: Per-chip peak throughput, keyed by a normalized `device_kind`. The
#: MFU/roofline numbers used to assume v5e (394 int8 TOPS hard-coded in
#: bench.py) whatever chip actually ran; now the peak resolves from
#: `jax.devices()[0].device_kind` with the v5e row as the DOCUMENTED
#: fallback — and every consumer (bench artifact, costdb record, report
#: device section) surfaces WHICH peak it used (`source: table` vs
#: `fallback`), so an assumed number can never read as a measured one.
#: Values are the published per-chip peaks: dense bf16 TFLOPS, int8
#: TOPS (chips without an int8 fast path reuse the bf16 number — the
#: closure is exact in either arithmetic, see _closure_batched), HBM
#: bandwidth GB/s and capacity GiB.
DEVICE_PEAKS: dict[str, dict] = {
    "tpu v2": {"bf16_tflops": 45.0, "int8_tops": 45.0,
               "hbm_gbps": 700.0, "hbm_gib": 16.0},
    "tpu v3": {"bf16_tflops": 123.0, "int8_tops": 123.0,
               "hbm_gbps": 900.0, "hbm_gib": 32.0},
    "tpu v4": {"bf16_tflops": 275.0, "int8_tops": 275.0,
               "hbm_gbps": 1228.0, "hbm_gib": 32.0},
    "tpu v5 lite": {"bf16_tflops": 197.0, "int8_tops": 394.0,
                    "hbm_gbps": 819.0, "hbm_gib": 16.0},
    "tpu v5p": {"bf16_tflops": 459.0, "int8_tops": 918.0,
                "hbm_gbps": 2765.0, "hbm_gib": 95.0},
    "tpu v6 lite": {"bf16_tflops": 918.0, "int8_tops": 1836.0,
                    "hbm_gbps": 1640.0, "hbm_gib": 32.0},
}

#: Spelling aliases libtpu has shipped for the same chips.
_PEAK_ALIASES = {"tpu v5e": "tpu v5 lite", "tpu v5": "tpu v5p",
                 "tpu v6e": "tpu v6 lite", "tpu v6": "tpu v6 lite"}

#: The documented fallback row for unknown/CPU device kinds — the v5e
#: values every pre-peak-table number assumed.
_PEAK_FALLBACK = "tpu v5 lite"


def device_peak(device_kind: str | None = None) -> dict:
    """The peak-throughput row for `device_kind` (default: the first
    jax device's), plus `device_kind` (as reported) and `source`:
    `"table"` for a known chip, `"fallback"` when the kind is unknown
    (CPU hosts, new chips) and the v5e row is assumed — consumers must
    surface that instead of publishing an assumed peak as measured."""
    if device_kind is None:
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = "unknown"
    norm = str(device_kind).strip().lower()
    norm = _PEAK_ALIASES.get(norm, norm)
    row = DEVICE_PEAKS.get(norm)
    if row is not None:
        return {"device_kind": str(device_kind), "source": "table",
                **row}
    return {"device_kind": str(device_kind),
            "source": f"fallback (assumed {_PEAK_FALLBACK})",
            **DEVICE_PEAKS[_PEAK_FALLBACK]}


def pad_to(x: int, multiple: int) -> int:
    """Round x up to a positive multiple."""
    return max(multiple, ((x + multiple - 1) // multiple) * multiple)


@dataclass(frozen=True)
class BatchShape:
    """Static padding plan for a batch of encoded histories."""

    n_txns: int      # T: txn rows per history (padded)
    n_appends: int   # A: append triples per history
    n_reads: int     # R: read triples per history
    n_keys: int      # K: interned keys per history
    max_pos: int     # P: longest version chain

    @staticmethod
    def plan(encs: list[EncodedHistory], multiple: int = 128) -> "BatchShape":
        return BatchShape(
            n_txns=pad_to(max((e.n for e in encs), default=1), multiple),
            n_appends=pad_to(max((len(e.appends) for e in encs), default=1), 8),
            n_reads=pad_to(max((len(e.reads) for e in encs), default=1), 8),
            n_keys=pad_to(max((e.n_keys for e in encs), default=1), 8),
            max_pos=pad_to(max((e.max_pos for e in encs), default=1), 8),
        )


def pack_batch(encs: list[EncodedHistory],
               shape: BatchShape | None = None) -> dict:
    """Pack EncodedHistories into padded stacked arrays (host-side).

    Padding convention: append/read triples beyond their count have
    txn = -1; txn rows beyond a history's n are dead (no triples reference
    them, and the kernel masks them out of realtime edges via n_txns)."""
    shape = shape or BatchShape.plan(encs)
    B = len(encs)
    appends = np.full((B, shape.n_appends, 3), -1, np.int32)
    reads = np.full((B, shape.n_reads, 3), -1, np.int32)
    invoke_idx = np.zeros((B, shape.n_txns), np.int64)
    complete_idx = np.zeros((B, shape.n_txns), np.int64)
    process = np.full((B, shape.n_txns), -1, np.int32)
    n_txns = np.zeros((B,), np.int32)
    for i, e in enumerate(encs):
        a = np.asarray(e.appends, np.int32)
        r = np.asarray(e.reads, np.int32)
        if len(a) > shape.n_appends or len(r) > shape.n_reads or \
                e.n > shape.n_txns:
            raise ValueError(f"history {i} exceeds batch shape {shape}")
        appends[i, : len(a)] = a
        reads[i, : len(r)] = r
        invoke_idx[i, : e.n] = e.invoke_index
        complete_idx[i, : e.n] = effective_complete_index(
            e.status, e.complete_index)
        process[i, : e.n] = e.process
        n_txns[i] = e.n
    return {"appends": appends, "reads": reads, "n_txns": n_txns,
            "invoke_index": invoke_idx, "complete_index": complete_idx,
            "process": process, "shape": shape}


def dispatch_shape(enc) -> "BatchShape | None":
    """The BatchShape a v2 (dispatch-shaped) sidecar pre-padded this
    encoding to, or None when it carries no dispatch views. The pad
    plan lives in store.dispatch_pad_plan (jax-free, for pool
    workers); this is the one place it re-enters the kernel type."""
    p = getattr(enc, "dispatch_pad", None)
    if not p or getattr(enc, "dispatch", None) is None:
        return None
    try:
        return BatchShape(n_txns=p["n_txns"], n_appends=p["n_appends"],
                          n_reads=p["n_reads"], n_keys=p["n_keys"],
                          max_pos=p["max_pos"])
    except KeyError:
        return None


def pack_batch_views(encs: list, shape: BatchShape) -> dict | None:
    """Copy-free sibling of pack_batch: when EVERY encoding carries
    dispatch-shaped mmap views (v2 sidecar warm path), return
    per-field LISTS of those views instead of freshly-copied stacked
    arrays — the h2d stage then device_puts each view straight from
    the mapped pages, pads ragged ones ON DEVICE (a history's own pad
    geometry may be smaller than the bucket max — `pad_to` is
    monotone, so it is never larger), and stacks in HBM. The host
    copies zero bytes either way. None when any encoding carries no
    views (cold encodings, v1 cache) or claims a geometry beyond the
    bucket's: the caller falls back to pack_batch, whose copies the
    warm counters attribute."""
    for e in encs:
        ds = dispatch_shape(e)
        if ds is None or ds.n_txns > shape.n_txns \
                or ds.n_appends > shape.n_appends \
                or ds.n_reads > shape.n_reads:
            return None
    fields = ("appends", "reads", "invoke_index", "complete_index",
              "process")
    out: dict = {f: [e.dispatch[f] for e in encs] for f in fields}
    out["n_txns"] = np.asarray([e.n for e in encs], np.int32)
    out["shape"] = shape
    out["views"] = True
    return out


def fused_classify_enabled() -> bool:
    """One home for the JEPSEN_TPU_FUSED_CLASSIFY gate (default on):
    classify dispatches run the fused detect/classify kernel — one
    detect closure per history, with the classification closures behind
    a `lax.cond` that only fires when some history in the batch is
    cyclic. `=0` restores the separate detect-then-classify re-dispatch
    (the pre-fusion two-pass strategy) for A/B runs."""
    from ... import gates

    return gates.get("JEPSEN_TPU_FUSED_CLASSIFY")


def resolve_formulation(use_pallas: bool | None = None,
                        use_int8: bool | None = None, *,
                        single_device: bool) -> tuple[bool, bool]:
    """THE closure-formulation resolver, shared by every dispatch layer
    (parallel.sharded_check_fn, check_encoded_batch, check_edge_batch)
    so JEPSEN_TPU_CLOSURE reaches the production analyze-store paths,
    not just the bench. Explicit arguments win; the env picks the
    default: "bf16" / "int8" pin the XLA formulations, "pallas" /
    "pallas-int8" opt into the fused ones. The auto default is the
    XLA **int8** matmul pipeline — int8 won the four-way race on real
    v5e hardware AND on CPU (BENCH_r05_hw; the closure is exact in
    either arithmetic), and XLA beat the fused Pallas kernels at every
    production shape. Pallas needs a single-device dispatch (sharded closures
    stay XLA for the collectives) and a per-VARIANT lowering probe —
    an int8-specific Mosaic regression degrades to the XLA matmul
    instead of breaking production."""
    from ... import gates

    from . import pallas_square
    # the registry validates the choice set and warns once on an
    # unrecognized value, falling back to the auto default ("")
    env = gates.get("JEPSEN_TPU_CLOSURE")
    if use_int8 is None:
        # auto default is int8: the boolean closure is exact in either
        # arithmetic, and int8 won the race on BOTH measured backends —
        # real v5e (74.3 vs 68.6 hist/s at the 5k-txn headline,
        # BENCH_r05_hw) and CPU (1.5x at T=1024) — which the MXU's 2:1
        # int8:bf16 throughput predicts. JEPSEN_TPU_CLOSURE=bf16 pins
        # the old formulation.
        use_int8 = env in ("int8", "pallas-int8") if env else True
    if use_pallas is None:
        if env in ("pallas", "pallas-int8") and single_device:
            # explicit opt-in only: fuse when it lowers
            use_pallas = pallas_square.pallas_available(int8=use_int8)
        else:
            # auto default is the XLA matmul pipeline: on a real v5e
            # the fused Pallas squaring measured 23 hist/s vs XLA's
            # 65-74 at the 5000-txn headline shape (and lost at 1000,
            # tied at 300) — XLA's own tiling beats the hand kernel
            # at every production shape, so fusion stays an explicit
            # JEPSEN_TPU_CLOSURE=pallas[-int8] experiment
            use_pallas = False
    return bool(use_pallas), bool(use_int8)


def closure_steps(n_txns: int) -> int:
    """Squaring rounds needed for a T-node graph: path lengths double each
    round; (A|I)^(2^s) covers all simple paths once 2^s >= T."""
    return max(1, int(np.ceil(np.log2(max(2, n_txns)))))


def _edges_one(appends: jnp.ndarray, reads: jnp.ndarray, n_keys: int,
               max_pos: int, n_txns: int, with_counts: bool = False):
    """Build [T,T] boolean adjacency matrices for ww/wr/rw from triples.

    appends: [A,3] (txn,key,pos), pos>=1 observed, -1 unobserved/dead.
    reads:   [R,3] (txn,key,pos-of-last), 0 empty read, -1 dead.

    With `with_counts` (the kernel-stats path) a fourth output carries
    the [3] int32 distinct-edge counts — ww counted BEFORE the
    power-of-two shortcut edges below, so the number matches the CPU
    oracle's adjacent-version graph, not the shortcut-augmented one
    the closure runs on.
    """
    T = n_txns
    a_txn, a_key, a_pos = appends[:, 0], appends[:, 1], appends[:, 2]
    r_txn, r_key, r_pos = reads[:, 0], reads[:, 1], reads[:, 2]
    a_live = (a_txn >= 0) & (a_pos >= 1)
    r_live = (r_txn >= 0) & (r_pos >= 0)

    # Writer lookup table W[key, pos] -> txn row (or -1). pos axis is
    # 1-based; slot 0 unused; dead triples scatter to a trash slot that is
    # re-nulled afterwards.
    W = jnp.full((n_keys, max_pos + 2), -1, jnp.int32)
    k_idx = jnp.where(a_live, a_key, n_keys - 1)
    p_idx = jnp.where(a_live, a_pos, max_pos + 1)
    W = W.at[k_idx, p_idx].set(jnp.where(a_live, a_txn, -1), mode="drop")
    W = W.at[:, max_pos + 1].set(-1)

    def scatter_edges(src, dst, live):
        live = live & (src >= 0) & (dst >= 0) & (src != dst)
        s = jnp.where(live, src, 0)
        d = jnp.where(live, dst, 0)
        adj = jnp.zeros((T, T), bool)
        return adj.at[s, d].max(live, mode="drop")

    # ww: writer of pos-1 -> writer of pos
    prev_w = W[k_idx, jnp.maximum(p_idx - 1, 0)]
    ww = scatter_edges(prev_w, a_txn, a_live & (a_pos >= 2))
    ww_raw = ww if with_counts else None

    # Power-of-two shortcut edges along each key's writer chain: an
    # edge W[k,p] -> W[k,p+s] is implied by transitivity whenever every
    # position p..p+s is live, so every closure is unchanged — but the
    # effective graph diameter drops from the chain length to ~log of
    # it, cutting squaring rounds (measured 8 -> 4 on the 5k-txn bench
    # shape, chain length 80). Soundness needs the contiguity gate: a
    # gap in the chain means no implied path, and a shortcut across it
    # would invent reachability.
    liveW = (W >= 0).astype(jnp.int32)          # [K, P+2]
    C = jnp.cumsum(liveW, axis=1)
    P = max_pos
    s = 2
    while s <= P:
        src = W[:, 1:P + 1 - s]                 # pos p = 1..P-s
        dst = W[:, 1 + s:P + 1]                 # pos p+s
        run = (C[:, 1 + s:P + 1] - C[:, 0:P - s]) == s + 1
        ww = ww | scatter_edges(src.ravel(), dst.ravel(), run.ravel())
        s *= 2

    # wr: writer of pos -> reader (pos >= 1)
    rk = jnp.where(r_live, r_key, n_keys - 1)
    rp = jnp.where(r_live & (r_pos >= 1), r_pos, max_pos + 1)
    wr = scatter_edges(W[rk, rp], r_txn, r_live & (r_pos >= 1))

    # rw: reader -> writer of pos+1
    rp1 = jnp.where(r_live, jnp.minimum(r_pos + 1, max_pos + 1), max_pos + 1)
    rw = scatter_edges(r_txn, W[rk, rp1], r_live)
    if with_counts:
        counts = jnp.stack([jnp.sum(ww_raw), jnp.sum(wr), jnp.sum(rw)]
                           ).astype(jnp.int32)
        return ww, wr, rw, counts
    return ww, wr, rw


def _closure_batched(m: jnp.ndarray, steps: int, constrain,
                     use_pallas: bool = False,
                     use_int8: bool = False) -> jnp.ndarray:
    """Transitive closure of [B,T,T] boolean adjacencies via repeated
    squaring; each squaring is one batched matmul on the MXU — bf16 by
    default, or int8×int8→int32 with use_int8: the MXU's int8 path has
    ~2× the bf16 throughput on v5e (394 TOPS vs 197 TFLOPS) and the
    boolean closure is exact in either (non-negative terms, int32
    accumulation never overflows below T=2^31). use_pallas composes
    with use_int8 (fusion × arithmetic); the bench races all four
    formulations and JEPSEN_TPU_CLOSURE (via resolve_formulation)
    flips the dispatch default once hardware numbers justify it.

    Runs to the fixpoint, not a fixed count: path lengths double each
    round, so convergence takes ~log2(graph diameter) rounds — for real
    histories the diameter tracks ops-per-key, far below T, which makes
    the early exit worth ~1.5x on the 5k-txn benchmark (the any()
    reduction per round is noise next to the matmul). `steps` stays the
    adversarial upper bound.

    With use_pallas (unsharded TPU dispatches), the squaring runs as
    the fused Pallas kernel (pallas_square.closure_square): the
    cast/matmul/threshold pipeline stays in VMEM instead of making
    bf16/f32 round-trips through HBM. Sharded dispatches keep the XLA
    matmul so the compiler can insert the dp/mp collectives.

    Returns (closure, rounds): the round counter is the ACTUAL number
    of squarings executed before the fixpoint — closure_rounds_device
    reads it back so the bench's measured MFU can never drift from
    what this kernel really does."""
    eye = jnp.eye(m.shape[-1], dtype=bool)
    m = m | eye

    def cond(carry):
        _, changed, i = carry
        return changed & (i < steps)

    def body(carry):
        m, _, i = carry
        m2 = _square(m, constrain, use_pallas, use_int8)
        return m2, jnp.any(m2 != m), i + 1

    m, _, i = jax.lax.while_loop(
        cond, body, (m, jnp.bool_(True), jnp.int32(0)))
    return m, i


def _square(m, constrain, use_pallas: bool, use_int8: bool):
    """ONE boolean matrix squaring — the loop body shared by
    `_closure_batched` and `_closure_batched_stats`, so the stats
    closure is bit-identical to the production one by construction
    (the telemetry variant only adds bookkeeping around it)."""
    if use_pallas:
        from . import pallas_square
        return pallas_square.closure_square(
            m, interpret=pallas_square.INTERPRET, int8=use_int8)
    if use_int8:
        mb = constrain(m.astype(jnp.int8))
        m2 = jax.lax.dot_general(
            mb, mb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32) > 0
        return constrain(m2)
    mb = constrain(m.astype(jnp.bfloat16))
    m2 = jax.lax.dot_general(
        mb, mb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) > 0
    return constrain(m2)


def _closure_batched_stats(m: jnp.ndarray, steps: int, constrain,
                           use_pallas: bool = False,
                           use_int8: bool = False):
    """`_closure_batched` with per-HISTORY search telemetry: the same
    squaring loop (same `_square` body, same batch-level fixpoint
    exit, so the returned closure — and every flag derived from it —
    is bit-identical to the stats-off kernel), additionally tracking
    for each history the round its own matrix reached fixpoint and the
    first round at which a cycle (an off-diagonal mutual-reachability
    pair) became visible. Returns (closure, rounds [B], cycle_round
    [B]; -1 = no cycle; cycle_round 0 = a cycle already present in
    the raw edge set)."""
    T = m.shape[-1]
    eye = jnp.eye(T, dtype=bool)
    nI = ~eye
    m = m | eye
    B = m.shape[0]

    def has_cycle(mm):
        return jnp.any(mm & jnp.swapaxes(mm, 1, 2) & nI, axis=(1, 2))

    def cond(carry):
        return carry[1] & (carry[2] < steps)

    def body(carry):
        m, _, i, rounds, cyc_round = carry
        m2 = _square(m, constrain, use_pallas, use_int8)
        changed_h = jnp.any(m2 != m, axis=(1, 2))
        rounds = jnp.where(changed_h, i + 1, rounds)
        cyc_round = jnp.where((cyc_round < 0) & has_cycle(m2), i + 1,
                              cyc_round)
        return m2, jnp.any(changed_h), i + 1, rounds, cyc_round

    cyc0 = jnp.where(has_cycle(m), jnp.int32(0), jnp.int32(-1))
    m, _, _, rounds, cyc_round = jax.lax.while_loop(
        cond, body, (m, jnp.bool_(True), jnp.int32(0),
                     jnp.zeros((B,), jnp.int32), cyc0))
    return m, rounds, cyc_round


def _graph_stats(edge_counts, rt_cnt, proc_cnt, c_full, rounds,
                 cyc_round, nI) -> jnp.ndarray:
    """Assemble the [B, N_STATS] stat rows from the full closure: SCC
    shape via mutual reachability (i and j share an SCC iff each
    reaches the other — the closure is reflexive, so the diagonal is
    excluded with nI), plus the edge counts and round telemetry
    gathered along the way. The SCC representative trick: the
    first-True index of `mutual[i, :]` is the SCC's minimum member, so
    counting rows that are their own argmax counts distinct SCCs."""
    T = c_full.shape[-1]
    mutual = c_full & jnp.swapaxes(c_full, 1, 2)       # [B,T,T]
    on_cycle = jnp.any(mutual & nI, axis=2)            # [B,T]
    scc_size = jnp.sum(mutual, axis=2).astype(jnp.int32)
    cycle_txns = jnp.sum(on_cycle, axis=1).astype(jnp.int32)
    scc_max = jnp.max(jnp.where(on_cycle, scc_size, 0), axis=1)
    scc_min = jnp.min(jnp.where(on_cycle, scc_size, T + 1), axis=1)
    scc_min = jnp.where(cycle_txns > 0, scc_min, 0)
    rep = on_cycle & (jnp.argmax(mutual, axis=2)
                      == jnp.arange(T, dtype=jnp.int32)[None, :])
    scc_count = jnp.sum(rep, axis=1).astype(jnp.int32)
    margin = jnp.where(cyc_round >= 0, cyc_round, rounds)
    return jnp.stack(
        [edge_counts[:, 0], edge_counts[:, 1], edge_counts[:, 2],
         rt_cnt, proc_cnt, rounds, cyc_round, scc_count, scc_max,
         scc_min, cycle_txns, margin], axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n_keys", "max_pos",
                                             "n_txns", "steps"))
def closure_rounds_device(appends, reads, *, n_keys: int, max_pos: int,
                          n_txns: int, steps: int) -> jnp.ndarray:
    """How many squaring rounds the detect closure ACTUALLY executes on
    this batch before the fixpoint — the measured input to the bench's
    MFU number, replacing the old assumed-rounds model. Runs the SAME
    _closure_batched loop as production and reads back its round
    counter; one extra dispatch of the detect-mode work, bench-only."""
    edges = jax.vmap(functools.partial(
        _edges_one, n_keys=n_keys, max_pos=max_pos, n_txns=n_txns))
    ww, wr, rw = edges(appends, reads)
    _, i = _closure_batched(ww | wr | rw, steps, _identity)
    return i


# NOTE: an iterated-peeling cycle test (live = adj·live > 0 to fixpoint,
# O(diameter·T²) matvecs instead of O(log T) T³ matmuls) was prototyped
# for detect mode but showed no robust end-to-end win on 5k-txn
# histories: wr/rw edges chain across keys, so real dependency graphs
# have diameters in the hundreds, and peeling's linear dependence on
# diameter cancels its cheaper rounds against the closure's logarithmic
# round count. Keep the fixpoint closure for both modes.


def check_batched_impl(appends, reads, invoke_index, complete_index, process,
                       n_live, *, n_keys: int, max_pos: int, n_txns: int,
                       steps: int, classify: bool, realtime: bool,
                       process_order: bool, constrain,
                       use_pallas: bool = False,
                       use_int8: bool = False,
                       fused: bool = True,
                       with_stats: bool = False):
    """THE cycle-check kernel: packed [B,...] tensors -> [B] int32 flag
    words. `n_live` is the per-history real txn count ([B]); rows beyond
    it are excluded from realtime/process edges. With `with_stats`
    (JEPSEN_TPU_KERNEL_STATS) the return is `(flags, stats)` where
    stats is the [B, N_STATS] int32 search-telemetry matrix — the
    flags themselves are bit-identical either way."""
    edges = jax.vmap(functools.partial(
        _edges_one, n_keys=n_keys, max_pos=max_pos, n_txns=n_txns,
        with_counts=with_stats))
    if with_stats:
        ww, wr, rw, counts = edges(appends, reads)
    else:
        ww, wr, rw = edges(appends, reads)
        counts = None
    return classify_matrices_impl(
        ww, wr, rw, invoke_index, complete_index, process, n_live,
        steps=steps, classify=classify, realtime=realtime,
        process_order=process_order, constrain=constrain,
        use_pallas=use_pallas, use_int8=use_int8, fused=fused,
        with_stats=with_stats, edge_counts=counts)


def _flags_from_closures(ww, wr, rw, c_ww, c_wwr, c_full, cycle,
                         nI) -> jnp.ndarray:
    """Anomaly flag words from the three edge classes and their three
    (nested) closures — the one classification formula, shared by the
    fused and unfused classify paths so their verdicts can't drift."""
    cT_wwr = jnp.swapaxes(c_wwr, 1, 2)
    g0 = jnp.any(ww & jnp.swapaxes(c_ww, 1, 2) & nI, axis=(1, 2))
    g1c = jnp.any(wr & cT_wwr, axis=(1, 2))
    g_single = jnp.any(rw & cT_wwr, axis=(1, 2))
    g2 = jnp.any(rw & jnp.swapaxes(c_full, 1, 2) & ~cT_wwr, axis=(1, 2))
    cycle = cycle | g0 | g1c | g_single | g2
    return (g0.astype(jnp.int32) << G0) \
        | (g1c.astype(jnp.int32) << G1C) \
        | (g_single.astype(jnp.int32) << G_SINGLE) \
        | (g2.astype(jnp.int32) << G2_ITEM) \
        | (cycle.astype(jnp.int32) << CYCLE)


def classify_matrices_impl(ww, wr, rw, invoke_index, complete_index, process,
                           n_live, *, steps: int, classify: bool,
                           realtime: bool, process_order: bool,
                           constrain, use_pallas: bool = False,
                           use_int8: bool = False,
                           fused: bool = True,
                           with_stats: bool = False,
                           edge_counts=None):
    """Closure + anomaly classification over explicit [B,T,T] boolean edge
    matrices. Entry point for checkers (rw-register) whose edge
    construction happens host-side from inferred version graphs rather
    than from per-key position chains.

    With `with_stats`, returns `(flags, stats)` — see STAT_FIELDS. The
    WIDEST closure of whichever strategy runs (the from-scratch full
    closure in detect/fused mode; the final seeded stage of the
    unfused chain) supplies the round/margin telemetry, and
    `edge_counts` ([B,3], from `_edges_one(with_counts=True)`) the
    pre-shortcut ww/wr/rw counts; None (this host-built-matrix entry
    point) counts the RAW incoming matrices instead — host builders
    emit no shortcut edges, so the counts match their edge lists."""
    T = ww.shape[-1]
    nI = ~jnp.eye(T, dtype=bool)
    live = jnp.arange(T)[None, :] < n_live[:, None]          # [B,T]
    live2 = live[:, :, None] & live[:, None, :]              # [B,T,T]

    if with_stats and edge_counts is None:
        edge_counts = jnp.stack(
            [jnp.sum(ww, axis=(1, 2)), jnp.sum(wr, axis=(1, 2)),
             jnp.sum(rw, axis=(1, 2))], axis=-1).astype(jnp.int32)
    rt_cnt = proc_cnt = None
    if with_stats:
        B = ww.shape[0]
        rt_cnt = jnp.zeros((B,), jnp.int32)
        proc_cnt = jnp.zeros((B,), jnp.int32)

    if process_order:
        # Consecutive txns of one process in completion order: link row i
        # to the same-process row with the smallest completion index
        # greater than i's.
        same = (process[:, :, None] == process[:, None, :]) \
            & (process[:, :, None] >= 0)
        later = complete_index[:, None, :] > complete_index[:, :, None]
        cand = same & later & live2
        big = jnp.where(cand, complete_index[:, None, :],
                        jnp.iinfo(complete_index.dtype).max)
        nxt = jnp.min(big, axis=2, keepdims=True)
        proc_add = cand & (big == nxt)
        if with_stats:
            proc_cnt = jnp.sum(proc_add, axis=(1, 2)).astype(jnp.int32)
        ww = ww | proc_add
    if realtime:
        # j completed before i invoked => j precedes i in real time.
        # Indeterminate txns carry NEVER_COMPLETED and emit no rt edges.
        rt = complete_index[:, :, None] < invoke_index[:, None, :]
        rt_add = rt & live2 & nI
        if with_stats:
            rt_cnt = jnp.sum(rt_add, axis=(1, 2)).astype(jnp.int32)
        ww = ww | rt_add

    def closure(m):
        """The widest closure + its telemetry: the stats variant runs
        the SAME loop body, so the matrix (and every flag below) is
        bit-identical with the gate on or off."""
        if with_stats:
            return _closure_batched_stats(m, steps, constrain,
                                          use_pallas, use_int8)
        c, _ = _closure_batched(m, steps, constrain, use_pallas,
                                use_int8)
        return c, None, None

    def result(flags, c_full, rounds, cyc_round):
        if not with_stats:
            return flags
        return flags, _graph_stats(edge_counts, rt_cnt, proc_cnt,
                                   c_full, rounds, cyc_round, nI)

    wwr = ww | wr
    full = wwr | rw
    if not classify:
        c_full, rounds, cyc_round = closure(full)
        cycle = jnp.any(full & jnp.swapaxes(c_full, 1, 2) & nI,
                        axis=(1, 2))
        return result(cycle.astype(jnp.int32) << CYCLE, c_full,
                      rounds, cyc_round)
    if fused:
        # Fused detect/classify (Elle's own design point: classification
        # falls out of the same graph detection walks): run the detect
        # closure first, and gate the classification closures behind a
        # lax.cond on "any history in this batch is cyclic". The common
        # all-valid batch pays exactly the detect cost — one closure —
        # while a batch with positives runs the per-class closures
        # REUSING the already-computed full closure for the cycle and
        # G2-item tests. Exact, because every per-class witness edge
        # implies a cycle in the full graph (each edge class is a
        # subset of `full` and each per-class closure a subset of
        # c_full), so a batch where the detect test fires nowhere can
        # only classify to zero flags.
        c_full, rounds, cyc_round = closure(full)
        cycle = jnp.any(full & jnp.swapaxes(c_full, 1, 2) & nI,
                        axis=(1, 2))

        def _classify(ops):
            ww_, wr_, rw_, c_full_, cycle_ = ops
            c_ww, _ = _closure_batched(ww_, steps, constrain,
                                       use_pallas, use_int8)
            c_wwr, _ = _closure_batched(c_ww | wr_, steps, constrain,
                                        use_pallas, use_int8)
            return _flags_from_closures(ww_, wr_, rw_, c_ww, c_wwr,
                                        c_full_, cycle_, nI)

        def _clean(ops):
            return ops[4].astype(jnp.int32) << CYCLE

        flags = jax.lax.cond(jnp.any(cycle), _classify, _clean,
                             (ww, wr, rw, c_full, cycle))
        return result(flags, c_full, rounds, cyc_round)
    # Unfused baseline (JEPSEN_TPU_FUSED_CLASSIFY=0): chained warm
    # starts — closure(A|B) == closure(closure(A)|B), so seeding each
    # wider closure with the previous result is exact and each seeded
    # closure converges in the few rounds its NEW edge class adds,
    # instead of re-walking the whole graph three times.
    c_ww, _ = _closure_batched(ww, steps, constrain, use_pallas,
                               use_int8)
    c_wwr, _ = _closure_batched(c_ww | wr, steps, constrain, use_pallas,
                                use_int8)
    c_full, rounds, cyc_round = closure(c_wwr | rw)
    cycle = jnp.any(full & jnp.swapaxes(c_full, 1, 2) & nI, axis=(1, 2))
    return result(
        _flags_from_closures(ww, wr, rw, c_ww, c_wwr, c_full, cycle,
                             nI), c_full, rounds, cyc_round)


def _identity(x):
    return x


@functools.partial(jax.jit, static_argnames=(
    "n_keys", "max_pos", "n_txns", "steps", "classify", "realtime",
    "process_order", "use_pallas", "use_int8", "fused", "with_stats"))
def check_batch_device(appends, reads, invoke_index, complete_index, process,
                       n_live, *, n_keys: int, max_pos: int, n_txns: int,
                       steps: int, classify: bool = True,
                       realtime: bool = False,
                       process_order: bool = False,
                       use_pallas: bool = False,
                       use_int8: bool = False,
                       fused: bool = True,
                       with_stats: bool = False):
    """Single-device jitted entry over a packed batch: [B] int32 flags
    (plus the [B, N_STATS] stats matrix under with_stats)."""
    return check_batched_impl(
        appends, reads, invoke_index, complete_index, process, n_live,
        n_keys=n_keys, max_pos=max_pos, n_txns=n_txns, steps=steps,
        classify=classify, realtime=realtime, process_order=process_order,
        constrain=_identity, use_pallas=use_pallas, use_int8=use_int8,
        fused=fused, with_stats=with_stats)


@functools.partial(jax.jit, static_argnames=(
    "steps", "classify", "realtime", "process_order", "use_pallas",
    "use_int8", "fused", "with_stats"))
def classify_matrices_device(ww, wr, rw, invoke_index, complete_index,
                             process, n_live, *, steps: int,
                             classify: bool = True, realtime: bool = False,
                             process_order: bool = False,
                             use_pallas: bool = False,
                             use_int8: bool = False,
                             fused: bool = True,
                             with_stats: bool = False):
    """Jitted single-device entry over packed [B,T,T] edge matrices."""
    return classify_matrices_impl(
        ww, wr, rw, invoke_index, complete_index, process, n_live,
        steps=steps, classify=classify, realtime=realtime,
        process_order=process_order, constrain=_identity,
        use_pallas=use_pallas, use_int8=use_int8, fused=fused,
        with_stats=with_stats)


def pack_edge_matrices(per_history: list[dict], multiple: int = 128) -> dict:
    """Pack host-built sparse edges into stacked dense bool matrices.

    per_history: dicts with keys n (txn count), edges (list of
    (src, dst, cls) with cls in {graph.WW, WR, RW}), invoke_index,
    complete_index, process (np arrays of length n)."""
    from . import graph as g
    B = len(per_history)
    T = pad_to(max((h["n"] for h in per_history), default=1), multiple)
    ww = np.zeros((B, T, T), bool)
    wr = np.zeros((B, T, T), bool)
    rw = np.zeros((B, T, T), bool)
    invoke_idx = np.zeros((B, T), np.int64)
    complete_idx = np.zeros((B, T), np.int64)
    process = np.full((B, T), -1, np.int32)
    n_live = np.zeros((B,), np.int32)
    # Only the three dependency classes are accepted: realtime/process
    # edges are built in-kernel from the timing tensors (passing them
    # here would double-count them against the kernel's flags).
    mats = {g.WW: ww, g.WR: wr, g.RW: rw}
    for i, hist in enumerate(per_history):
        n = hist["n"]
        n_live[i] = n
        for s, d, cls in hist["edges"]:
            if s != d:
                mats[cls][i, s, d] = True
        invoke_idx[i, :n] = hist["invoke_index"]
        complete_idx[i, :n] = hist["complete_index"]
        process[i, :n] = hist["process"]
    return {"ww": ww, "wr": wr, "rw": rw, "invoke_index": invoke_idx,
            "complete_index": complete_idx, "process": process,
            "n_txns": n_live, "T": T}


def check_edge_batch(per_history: list[dict], realtime: bool = False,
                     process_order: bool = False,
                     classify: bool = True, devices=None,
                     fused: bool | None = None,
                     stats_out: list | None = None) -> list[dict]:
    """Device cycle check over host-built edge lists: per-history
    {anomaly-name: True} dicts (the rw-register device path, and the
    per-SCC classify stage of the condensed long-history path).

    With several devices the batch axis shards over a 1-D dp mesh,
    ragged batches padded by replicating the last entry.

    `stats_out` (a list) is EXTENDED with one `stats_row` dict per
    input history when given — the kernel then also computes the
    search-telemetry matrix (same flags either way)."""
    if not per_history:
        return []
    n = len(per_history)
    devices = devices if devices is not None else default_devices()
    per_history = pad_to_multiple(per_history, len(devices))
    p = pack_edge_matrices(per_history)
    names = ("ww", "wr", "rw", "invoke_index", "complete_index",
             "process", "n_txns")
    # device_put straight from numpy: going through jnp.asarray first
    # would commit each [B,T,T] matrix whole onto device 0 before the
    # dp sharding ever applied.
    if len(devices) > 1:
        mesh = jax.sharding.Mesh(np.asarray(devices), ("dp",))
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("dp"))
        args = [jax.device_put(p[k], sharding) for k in names]
    else:
        args = [jax.device_put(p[k], devices[0] if devices else None)
                for k in names]
    use_pallas, use_int8 = resolve_formulation(
        single_device=len(devices) == 1)
    if fused is None:
        fused = fused_classify_enabled()
    with_stats = stats_out is not None
    out = classify_matrices_device(
        *args, steps=closure_steps(p["T"]), classify=classify,
        realtime=realtime, process_order=process_order,
        use_pallas=use_pallas, use_int8=use_int8, fused=fused,
        with_stats=with_stats)
    flags, dev_stats = out if with_stats else (out, None)
    # the np.asarray below is an implicit device wait: bound it with
    # the dispatch watchdog so a wedged device can't hang the wr sweep
    # (JEPSEN_TPU_DISPATCH_TIMEOUT_S; no-op when the gate is off)
    from ...parallel import _block_flags
    from ... import trace as _trace
    flags = _block_flags(flags, _trace.get_current())
    if with_stats:
        rows = np.asarray(dev_stats)[:n]
        stats_out.extend(
            stats_row(rows[i], n_txns=per_history[i]["n"],
                      t_pad=p["T"]) for i in range(n))
    return [flags_to_names(int(w)) for w in np.asarray(flags)[:n]]


def check_edge_batch_bucketed(per_history: list[dict],
                              realtime: bool = False,
                              process_order: bool = False,
                              classify: bool = True, devices=None,
                              budget_cells: int = 1 << 27,
                              fused: bool | None = None,
                              stats_out: list | None = None) -> list[dict]:
    """check_edge_batch with device-memory-aware length bucketing: the
    packed matrices are B·T_pad² cells × 3 edge classes, so one
    unbucketed dispatch over a big store would blow HBM. Reuses
    parallel.bucket_by_length (including its dp-padding headroom —
    check_edge_batch replicates the last entry up to a device
    multiple); results return in input order, and `stats_out` (when
    given) is extended with per-history stats dicts in the SAME
    order."""
    if not per_history:
        return []
    from ...parallel import bucket_by_length
    dp = (len(devices) if devices is not None
          else len(default_devices()))
    out: list[dict | None] = [None] * len(per_history)
    sout: list = [None] * len(per_history)
    for bucket in bucket_by_length(per_history,
                                   budget_cells=budget_cells,
                                   dp=max(1, dp)):
        bstats: list | None = [] if stats_out is not None else None
        res = check_edge_batch([per_history[j] for j in bucket],
                               realtime=realtime,
                               process_order=process_order,
                               classify=classify, devices=devices,
                               fused=fused, stats_out=bstats)
        for i, (j, r) in enumerate(zip(bucket, res)):
            out[j] = r
            if bstats is not None:
                sout[j] = bstats[i]
    if stats_out is not None:
        stats_out.extend(sout)
    return out  # type: ignore[return-value]


def flags_to_names(word: int) -> dict:
    """Anomaly names for a flag word. In detect-only mode (classify=False)
    no classify bits exist, so a set CYCLE bit reports as a generic
    "cycle" anomaly rather than vanishing."""
    out = {name: True for bit, name in FLAG_NAMES.items()
           if word & (1 << bit)}
    if not out and word & (1 << CYCLE):
        out["cycle"] = True
    return out


def check_encoded_batch(encs: list[EncodedHistory],
                        realtime: bool = False,
                        process_order: bool = False,
                        classify: bool = True,
                        devices=None,
                        stats_out: list | None = None) -> list[dict]:
    """Check a batch of encoded histories on device; returns per-history
    dicts {anomaly-name: True} for the cycle anomalies.

    With several addressable devices the batch axis is sharded across a
    1-D mesh — the analysis data plane (SURVEY.md §5.8). Ragged batches
    are padded to a device multiple by replicating the last history (the
    extra results are dropped), so sharding never silently degrades to
    one device."""
    if not encs:
        return []
    n = len(encs)
    devices = devices if devices is not None else default_devices()
    encs = pad_to_multiple(encs, len(devices))
    batch = pack_batch(encs)
    shape: BatchShape = batch["shape"]
    names = ("appends", "reads", "invoke_index", "complete_index",
             "process", "n_txns")
    args = [jnp.asarray(batch[k]) for k in names]

    if len(devices) > 1:
        mesh = jax.sharding.Mesh(np.asarray(devices), ("dp",))
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("dp"))
        args = [jax.device_put(a, sharding) for a in args]

    use_pallas, use_int8 = resolve_formulation(
        single_device=len(devices) == 1)
    with_stats = stats_out is not None
    out = check_batch_device(
        *args, n_keys=shape.n_keys, max_pos=shape.max_pos,
        n_txns=shape.n_txns, steps=closure_steps(shape.n_txns),
        classify=classify, realtime=realtime, process_order=process_order,
        use_pallas=use_pallas, use_int8=use_int8,
        fused=fused_classify_enabled(), with_stats=with_stats)
    flags, dev_stats = out if with_stats else (out, None)
    if with_stats:
        rows = np.asarray(dev_stats)[:n]
        stats_out.extend(
            stats_row(rows[i], n_txns=encs[i].n, t_pad=shape.n_txns)
            for i in range(n))
    return [flags_to_names(int(w)) for w in np.asarray(flags)[:n]]
