"""Elle rw-register analysis: write/read register transactions.

Mirrors the reference's jepsen.tests.cycle.wr checker
(jepsen/src/jepsen/tests/cycle/wr.clj:16-56, backed by elle.rw-register;
paper arXiv:2003.10554 §5). Op values are transactions of [f k v]
micro-ops with f in {"r","w"}; writes are assumed unique per key.

Unlike list-append, a register read reveals only the *latest* value, so
version orders are not observable directly; they are inferred per key as
a constraint graph over values from configurable sources
(wr.clj:25-31):

  initial            None (unwritten) precedes every value
  wfr_keys           within a txn, writes follow reads: ext-read value
                     precedes values the same txn writes to that key
  sequential_keys    each key is sequentially consistent: one process's
                     successive ext-writes to a key are ordered
  linearizable_keys  each key is linearizable: realtime-ordered ext-writes
                     (w1's txn completed before w2's invoked) are ordered

A cyclic constraint graph is itself an anomaly ("cyclic-versions",
valid? false). From the (acyclic) version graph's transitive reduction we
derive dependency edges between txns:

  ww  writer(v1) -> writer(v2)        for v1 -> v2 adjacent versions
  wr  writer(v)  -> ext-reader of v   (exact: writes are unique)
  rw  ext-reader of v1 -> writer(v2)  for v1 -> v2 adjacent versions

Cycle search + classification then reuses the shared machinery: CPU
Tarjan oracle (graph.classify_cycles) or the MXU transitive-closure
kernel over explicit edge matrices (kernels.check_edge_batch).

Host-detected anomalies: internal (txn observes state inconsistent with
its own prior reads/writes), G1a (read of a failed txn's write), G1b
(read of an intermediate write), and cyclic-versions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable

import numpy as np

from ... import history as h
from .. import Checker
from . import graph as g
from . import txn as t
from .encode import INFO, OK, NEVER_COMPLETED, _note, \
    effective_complete_index

# Sentinel for the initial (unwritten) register state in version graphs.
INIT = object()


@dataclass
class WrEncoded:
    """One rw-register history digested to txn rows + dependency edges."""

    n: int = 0
    edges: list = field(default_factory=list)    # (src, dst, g.WW|WR|RW)
    status: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int32))
    process: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int32))
    invoke_index: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int64))
    complete_index: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int64))
    anomalies: dict = field(default_factory=dict)
    txn_ops: list = field(default_factory=list)
    key_count: int = 0


def to_edge_dict(enc: WrEncoded) -> dict:
    """The packed-edge form kernels.check_edge_batch consumes."""
    return {"n": enc.n, "edges": enc.edges,
            "invoke_index": enc.invoke_index,
            "complete_index": enc.complete_index,
            "process": enc.process}


def ext_reads(txn: list) -> dict:
    """key -> value for reads that observe *external* state: the first
    read of a key at a point where the txn has not yet written it."""
    written: set = set()
    out: dict = {}
    for f, k, v in txn:
        if f == "w":
            written.add(k)
        elif k not in written and k not in out:
            out[k] = v
    return out


def ext_writes(txn: list) -> dict:
    """key -> value of the txn's last write to each key (the state it
    leaves behind)."""
    out: dict = {}
    for f, k, v in txn:
        if f == "w":
            out[k] = v
    return out


def _check_internal(txn: list, op: dict, anomalies: dict) -> None:
    """Register semantics: a read of k must return the txn's latest prior
    write/read of k, if any."""
    state: dict = {}
    for f, k, v in txn:
        if f == "w":
            state[k] = v
        else:
            if k in state and state[k] != v:
                _note(anomalies, "internal",
                      {"op": op, "mop": ["r", k, v], "expected": state[k]})
            state[k] = v


def _toposort(nodes: list, adj: dict) -> list | None:
    """Kahn topological order, or None if cyclic."""
    indeg = {u: 0 for u in nodes}
    for u in nodes:
        for v in adj.get(u, ()):
            indeg[v] += 1
    queue = [u for u in nodes if indeg[u] == 0]
    out = []
    while queue:
        u = queue.pop()
        out.append(u)
        for v in adj.get(u, ()):
            indeg[v] -= 1
            if indeg[v] == 0:
                queue.append(v)
    return out if len(out) == len(nodes) else None


def _transitive_reduction(nodes: list, adj: dict) -> dict:
    """Adjacent-version edges of a small DAG: drop u->v when another
    path u->..->v exists. O(V*E) DFS per node; per-key version graphs
    are small (one node per written value)."""
    reach: dict = {}

    def dfs(u):
        if u in reach:
            return reach[u]
        acc = set()
        reach[u] = acc  # placeholder breaks accidental cycles defensively
        for v in adj.get(u, ()):
            acc.add(v)
            acc |= dfs(v)
        reach[u] = acc
        return acc

    out: dict = {}
    for u in nodes:
        direct = set(adj.get(u, ()))
        redundant = set()
        for v in direct:
            for w in direct:
                if w != v and v in dfs(w):
                    redundant.add(v)
        out[u] = direct - redundant
    return out


def lean_wr_anomalies(enc: WrEncoded) -> dict:
    """Witnesses reduced to the environment-independent lean shape the
    native wr ingest (native/hist_encode.cc) emits — the rw-register
    sibling of encode.lean_anomalies, same contract: same names,
    counts, and order, no op dicts, so persisted wr-sweep artifacts
    don't depend on which encoder ran. Call BEFORE dropping txn_ops."""
    if not enc.anomalies:
        return {}
    row_of = {id(op): r for r, op in enumerate(enc.txn_ops)}

    def row(w):
        return row_of.get(id(w.get("op")), -1)

    out: dict = {}
    for name, wits in enc.anomalies.items():
        lw = []
        for w in wits:
            if name == "internal":
                lw.append({"row": row(w), "key": w["mop"][1]})
            elif name == "G1a":
                writer = w.get("writer") or {}
                lw.append({"key": w["key"], "value": w["value"],
                           "writer-index": writer.get("index", -1),
                           "row": row(w)})
            elif name in ("duplicate-writes", "phantom-read", "G1b"):
                lw.append({"key": w["key"], "value": w["value"],
                           "row": row(w)})
            elif name == "cyclic-versions":
                lw.append({"key": w["key"]})
            else:  # unknown anomaly class: pass through untouched
                lw.append(w)
        out[name] = lw
    return out


def encode_wr_history(history: list[dict], *, sequential_keys: bool = False,
                      linearizable_keys: bool = False,
                      wfr_keys: bool = False) -> WrEncoded:
    """Digest an rw-register history into txn rows + dependency edges."""
    history = h.index(history)
    enc = WrEncoded()
    anomalies = enc.anomalies

    # fused single-pass pairing + filtering (t.bucket_txn_pairs, the
    # same ingest-critical-path hot loop the append encoder uses)
    committed, indeterminate, failed = t.bucket_txn_pairs(history)

    rows: list[dict] = []
    for inv, comp in committed:
        rows.append({"txn": t.mops(comp), "status": OK, "inv": inv,
                     "op": comp})
    for inv in indeterminate:
        rows.append({"txn": t.mops(inv), "status": INFO, "inv": inv,
                     "op": inv})
    enc.n = len(rows)

    # --- writer index + per-txn intermediate writes ----------------------
    writer_of: dict = {}           # (k, v) -> row
    writers_by_key: dict = {}      # k -> {v: row}
    intermediate: set = set()      # (k, v, row): non-final write of row
    for r_i, row in enumerate(rows):
        per_key: dict = {}
        for f, k, v in row["txn"]:
            if f == "w":
                per_key.setdefault(k, []).append(v)
        for k, vals in per_key.items():
            for v in vals:
                if (k, v) in writer_of:
                    _note(anomalies, "duplicate-writes",
                          {"key": k, "value": v, "op": row["op"]})
                writer_of[(k, v)] = r_i
                writers_by_key.setdefault(k, {})[v] = r_i
            for v in vals[:-1]:
                intermediate.add((k, v, r_i))
    failed_writes: dict = {}
    for inv in failed:
        # every write of a failed txn is aborted state, including
        # intermediate (non-final) ones — reading any of them is G1a
        for f, k, v in t.mops(inv):
            if f == "w":
                failed_writes[(k, v)] = inv

    # --- internal + read collection --------------------------------------
    readers_by_key: dict = {}      # k -> {v: [row, ...]} external readers
    for r_i, row in enumerate(rows):
        if row["status"] != OK:
            continue
        _check_internal(row["txn"], row["op"], anomalies)
        for k, v in ext_reads(row["txn"]).items():
            readers_by_key.setdefault(k, {}).setdefault(v, []).append(r_i)
            if v is None:
                continue
            w = writer_of.get((k, v))
            if w is None:
                if (k, v) in failed_writes:
                    _note(anomalies, "G1a",
                          {"key": k, "value": v, "op": row["op"],
                           "writer": failed_writes[(k, v)]})
                else:
                    _note(anomalies, "phantom-read",
                          {"key": k, "value": v, "op": row["op"]})
            elif (k, v, w) in intermediate and w != r_i:
                _note(anomalies, "G1b",
                      {"key": k, "value": v, "op": row["op"]})

    # --- version graphs per key ------------------------------------------
    complete_idx = effective_complete_index(
        np.asarray([r["status"] for r in rows], np.int32),
        np.asarray([r["op"].get("index", -1) for r in rows], np.int64))
    keys: set = set(writers_by_key) | set(readers_by_key)
    enc.key_count = len(keys)
    version_adj: dict = {}         # key -> {value-node: set(successors)}

    def add_version_edge(k, v1, v2):
        if v1 == v2:
            return
        version_adj.setdefault(k, {}).setdefault(v1, set()).add(v2)

    for k, vals in writers_by_key.items():
        # initial: None precedes every written value
        for v in vals:
            add_version_edge(k, INIT, v)
    for r_i, row in enumerate(rows):
        if wfr_keys:
            er = ext_reads(row["txn"])
            for k, v in ext_writes(row["txn"]).items():
                if k in er and er[k] is not None:
                    add_version_edge(k, er[k], v)
    if sequential_keys:
        by_proc_key: dict = {}
        for r_i, row in enumerate(rows):
            p = row["inv"].get("process")
            for k, v in ext_writes(row["txn"]).items():
                by_proc_key.setdefault((p, k), []).append(
                    (int(complete_idx[r_i]), v))
        for (p, k), writes in by_proc_key.items():
            writes.sort()
            for (_, v1), (_, v2) in zip(writes, writes[1:]):
                add_version_edge(k, v1, v2)
    if linearizable_keys:
        by_key: dict = {}
        for r_i, row in enumerate(rows):
            inv_i = row["inv"].get("index", -1)
            for k, v in ext_writes(row["txn"]).items():
                by_key.setdefault(k, []).append(
                    (int(complete_idx[r_i]), inv_i, v))
        for k, writes in by_key.items():
            writes.sort()
            for i, (c1, _, v1) in enumerate(writes):
                if c1 >= NEVER_COMPLETED:
                    continue
                # every write invoked after v1's txn completed is
                # realtime-after it; transitive reduction compacts chains
                for c2, inv2, v2 in writes[i + 1:]:
                    if inv2 > c1:
                        add_version_edge(k, v1, v2)

    # --- dependency edges from version graphs ----------------------------
    edges: list = []
    for k in sorted(keys, key=repr):
        adj = version_adj.get(k, {})
        key_writers = writers_by_key.get(k, {})
        key_readers = readers_by_key.get(k, {})
        nodes = list({INIT} | set(key_writers) | set(adj))
        if _toposort(nodes, adj) is None:
            _note(anomalies, "cyclic-versions", {"key": k})
            continue
        red = _transitive_reduction(nodes, adj)
        for v1, succs in red.items():
            w1 = key_writers.get(v1) if v1 is not INIT else None
            rds = key_readers.get(v1 if v1 is not INIT else None, [])
            for v2 in succs:
                w2 = key_writers.get(v2)
                if w2 is None:
                    continue
                if w1 is not None and w1 != w2:
                    edges.append((w1, w2, g.WW))
                for rd in rds:
                    if rd != w2:
                        edges.append((rd, w2, g.RW))
        for v, rds in key_readers.items():
            if v is None:
                continue
            w = key_writers.get(v)
            if w is None:
                continue
            for rd in rds:
                if rd != w:
                    edges.append((w, rd, g.WR))
    enc.edges = sorted(set(edges))

    enc.status = np.asarray([r["status"] for r in rows], np.int32)
    enc.process = np.asarray(
        [r["inv"].get("process", -1)
         if isinstance(r["inv"].get("process"), int) else -1
         for r in rows], np.int32)
    enc.invoke_index = np.asarray(
        [r["inv"].get("index", -1) for r in rows], np.int64)
    enc.complete_index = complete_idx
    enc.txn_ops = [r["op"] for r in rows]
    return enc


def cycle_anomalies_cpu(enc: WrEncoded, realtime: bool = False,
                        process_order: bool = False) -> dict:
    edges = enc.edges + g.order_edges(
        enc.n, enc.process, enc.invoke_index, enc.complete_index,
        process_order=process_order, realtime=realtime)
    return g.classify_cycles(enc.n, edges)


def cycle_anomalies_tpu(enc: WrEncoded, realtime: bool = False,
                        process_order: bool = False) -> dict:
    if enc.n == 0:
        return {}
    from . import kernels  # deferred: keeps jax out of encode-only
    # workers (ingest.parallel_encode forks encode_wr_history users)
    return kernels.check_edge_batch(
        [{"n": enc.n, "edges": enc.edges,
          "invoke_index": enc.invoke_index,
          "complete_index": enc.complete_index,
          "process": enc.process}],
        realtime=realtime, process_order=process_order)[0]


# Anomalies that always invalidate an rw-register history.
ALWAYS_INVALID = frozenset({
    "internal", "cyclic-versions", "dirty-update", "phantom-read",
    "duplicate-writes", "G0",
})

# Specifying an anomaly class prohibits the classes it implies
# (wr.clj:46: "G2 implies G-single and G1c. G1 implies G1a, G1b, and
# G1c. G1c implies G0.").
ANOMALY_EXPANSION = {
    "G0": {"G0"},
    "G1": {"G0", "G1a", "G1b", "G1c"},
    "G1a": {"G1a"},
    "G1b": {"G1b"},
    "G1c": {"G1c", "G0"},
    "G2": {"G2-item", "G-single", "G1c", "G0"},
    "G-single": {"G-single", "G1c", "G0"},
    "G2-item": {"G2-item"},
    "internal": {"internal"},
}


def render_wr_verdict(enc: WrEncoded, cycles: dict,
                      prohibited: frozenset) -> dict:
    """Combine host-detected and cycle anomalies into the rw-register
    verdict (shared by WrChecker and the batch analyze-store path)."""
    anomalies: dict = dict(enc.anomalies)
    for name, witness in cycles.items():
        if witness is True:
            anomalies[name] = True
        else:
            anomalies[name] = [{"cycle-txns": [
                enc.txn_ops[r] if 0 <= r < len(enc.txn_ops) else r
                for r in witness]}]
    bad = {a for a in anomalies
           if a in prohibited or a in ALWAYS_INVALID}
    if enc.n == 0:
        return {"valid?": "unknown",
                "anomaly-types": ["empty-transaction-graph"],
                "anomalies": {}, "txn-count": 0}
    return {"valid?": not bad,
            "anomaly-types": sorted(anomalies),
            "anomalies": anomalies,
            "txn-count": enc.n,
            "key-count": enc.key_count}


class WrChecker(Checker):
    """Checker for rw-register histories (wr.clj:16-56 equivalent).

    Options: anomalies to prohibit (default G2+G1a+G1b+internal, the
    reference default at wr.clj:47), backend cpu|tpu, version-order
    inference flags, realtime/process_order graph additions."""

    def __init__(self, anomalies: Iterable[str] = ("G2", "G1a", "G1b",
                                                   "internal"),
                 backend: str = "auto", sequential_keys: bool = False,
                 linearizable_keys: bool = False, wfr_keys: bool = False,
                 realtime: bool = False, process_order: bool = False):
        self.prohibited = frozenset().union(
            *(ANOMALY_EXPANSION.get(a, {a}) for a in anomalies)) \
            if anomalies else frozenset()
        self.backend = backend
        self.opts = dict(sequential_keys=sequential_keys,
                         linearizable_keys=linearizable_keys,
                         wfr_keys=wfr_keys)
        self.realtime = realtime
        self.process_order = process_order

    def check(self, test, history, opts):
        from ...devices import resolve_backend
        backend = resolve_backend(self.backend)
        enc = encode_wr_history(history, **self.opts)
        find = (cycle_anomalies_tpu if backend == "tpu"
                else cycle_anomalies_cpu)
        cycles = find(enc, realtime=self.realtime,
                      process_order=self.process_order)
        from . import artifacts
        divergent: dict = {}
        if backend == "tpu" and cycles:
            cycles, divergent = artifacts.device_host_refine(
                cycles, lambda: cycle_anomalies_cpu(
                    enc, realtime=self.realtime,
                    process_order=self.process_order))
        verdict = render_wr_verdict(enc, cycles, self.prohibited)
        return artifacts.attach(verdict, divergent, test, opts)

    def render_failure(self, test, history, res, opts) -> None:
        """Per-key artifact hook for batched independent dispatch."""
        from . import artifacts
        artifacts.attach(res, res.get("device-host-divergence", {}),
                         test, opts)

    def check_batch(self, test, histories: list, opts,
                    stats_out: list | None = None) -> list[dict]:
        """Batched per-key dispatch: host version-order inference per
        history, then length-bucketed device cycle dispatches over the
        packed edge matrices (kernels.check_edge_batch_bucketed);
        flagged histories re-run the host oracle for witnesses.
        `stats_out` (a list) is extended with per-history kernel
        search-stat dicts on the device path (None per history on the
        CPU oracle — it runs no closure to report on)."""
        from ...devices import resolve_backend
        backend = resolve_backend(self.backend)
        encs = [encode_wr_history(h, **self.opts) for h in histories]
        kw = dict(realtime=self.realtime,
                  process_order=self.process_order)
        if backend != "tpu":
            if stats_out is not None:
                stats_out.extend(None for _ in encs)
            return [render_wr_verdict(e, cycle_anomalies_cpu(e, **kw),
                                      self.prohibited) for e in encs]
        from . import artifacts, kernels
        cycles_list = kernels.check_edge_batch_bucketed(
            [to_edge_dict(e) for e in encs], stats_out=stats_out, **kw)
        out = []
        for enc, cycles in zip(encs, cycles_list):
            divergent: dict = {}
            if cycles:
                cycles, divergent = artifacts.device_host_refine(
                    cycles,
                    lambda enc=enc: cycle_anomalies_cpu(enc, **kw))
            verdict = render_wr_verdict(enc, cycles, self.prohibited)
            if divergent:
                verdict["device-host-divergence"] = divergent
            out.append(verdict)
        return out


def rw_register_checker(anomalies: Iterable[str] = ("G2", "G1a", "G1b",
                                                    "internal"),
                        backend: str = "auto", **kw: Any) -> Checker:
    return WrChecker(anomalies, backend, **kw)
