"""Host-condensed checking for very long histories (100k+ ops).

The dense [T,T] closure kernel caps around ~32k txns per slice (HBM
holds T² cells per matrix). This module is the scale path behind it —
the reason the reference decomposes histories at all
(jepsen/src/jepsen/independent.clj:1-7; SURVEY.md §5.7) — built on one
graph fact:

    every dependency cycle lies inside one strongly-connected component
    of the FULL dependency graph, and so does every path between two
    members of an SCC (any intermediate node closes a cycle through the
    SCC and is therefore a member).

Hence each anomaly query is *exactly* answerable inside its SCC: the
offending edge plus its return path form a cycle, and the "not in
ww∪wr closure" side condition of G2-item also restricts losslessly,
because any ww∪wr return path between SCC members is SCC-internal.

Pipeline:
  1. vectorized numpy edge build (searchsorted writer lookup — no
     Python per-op loops),
  2. native C++ Tarjan over CSR arrays (realtime order sparsified to
     O(T) via a completion-rank aux chain),
  3. valid histories (no nontrivial SCC — the common case) finish here
     in milliseconds with zero device work,
  4. anomalous histories ship their (small) SCC subgraphs to the
     batched MXU classification kernel, flags OR-ed across SCCs.

This mirrors how Elle itself leans on Tarjan-over-bifurcan for the
search (SURVEY.md §2.3) while keeping classification on device.
"""

from __future__ import annotations

import numpy as np

from ... import native_lib
from .encode import EncodedHistory, effective_complete_index
from . import graph as G


def _append_lookup(enc: EncodedHistory):
    """Writer lookup (key, pos) -> txn row via sorted-id binary search.

    Returns a callable look(keys, positions) -> txn rows (or -1); only
    live appends (pos >= 1) participate, matching graph.build_edges."""
    a = np.asarray(enc.appends, np.int64).reshape(-1, 3)
    P2 = enc.max_pos + 2
    live = a[:, 2] >= 1
    ids = a[live, 1] * P2 + a[live, 2]
    txns = a[live, 0]
    order = np.argsort(ids)
    sids, stx = ids[order], txns[order]

    def look(keys: np.ndarray, positions: np.ndarray) -> np.ndarray:
        if len(sids) == 0 or len(keys) == 0:
            return np.full(len(keys), -1, np.int64)
        q = keys.astype(np.int64) * P2 + positions.astype(np.int64)
        i = np.minimum(np.searchsorted(sids, q), len(sids) - 1)
        return np.where(sids[i] == q, stx[i], -1)

    return look


def build_edges_arrays(enc: EncodedHistory, process_order: bool = False
                       ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized (src, dst, cls) edge arrays — the numpy counterpart of
    graph.build_edges (same ww/wr/rw semantics, graph.py:31-64), minus
    realtime (see rt_aux_edges)."""
    a = np.asarray(enc.appends, np.int64).reshape(-1, 3)
    r = np.asarray(enc.reads, np.int64).reshape(-1, 3)
    look = _append_lookup(enc)
    srcs, dsts, clss = [], [], []

    def emit(src, dst, cls):
        keep = (src >= 0) & (dst >= 0) & (src != dst)
        srcs.append(src[keep])
        dsts.append(dst[keep])
        clss.append(np.full(int(keep.sum()), cls, np.int32))

    m = a[:, 2] >= 2                      # ww: writer(pos-1) -> writer(pos)
    emit(look(a[m, 1], a[m, 2] - 1), a[m, 0], G.WW)
    m = r[:, 2] >= 1                      # wr: writer(pos) -> reader
    emit(look(r[m, 1], r[m, 2]), r[m, 0], G.WR)
    m = r[:, 2] >= 0                      # rw: reader -> writer(pos+1)
    emit(r[m, 0], look(r[m, 1], r[m, 2] + 1), G.RW)

    if process_order and enc.n:
        eff = effective_complete_index(enc.status, enc.complete_index)
        pr = np.asarray(enc.process, np.int64)
        rows = np.arange(enc.n, dtype=np.int64)[pr >= 0]
        order = rows[np.lexsort((eff[pr >= 0], pr[pr >= 0]))]
        if len(order) > 1:
            src, dst = order[:-1], order[1:]
            same = pr[src] == pr[dst]
            emit(src[same], dst[same], G.PROC)

    if not srcs:
        z = np.zeros(0, np.int64)
        return z, z, np.zeros(0, np.int32)
    return (np.concatenate(srcs), np.concatenate(dsts),
            np.concatenate(clss))


def aux_chain(eff: np.ndarray, inv: np.ndarray
              ) -> tuple[np.ndarray, np.ndarray]:
    """Completion-rank aux chain over (complete, invoke) index arrays:
    the O(n) sparsification of the realtime order.

    Aux node n+k means "after the k-th completion (in completion
    order)". Edges: txn j -> aux rank(j); aux_k -> aux_{k+1}; and
    aux_{k_i} -> txn i where k_i is the last completion rank strictly
    before i's invocation. Reachability j -> i through aux nodes is
    then exactly complete(j) < invoke(i). Returns (src, dst); callers
    add n aux node ids on top of their own node space."""
    n = len(eff)
    order = np.argsort(eff, kind="stable")
    sorted_eff = eff[order]
    rank = np.empty(n, np.int64)
    rank[order] = np.arange(n)
    aux = n + np.arange(n, dtype=np.int64)

    srcs = [np.arange(n, dtype=np.int64), aux[:-1]]
    dsts = [aux[rank], aux[1:]]
    k = np.searchsorted(sorted_eff, inv) - 1   # last completion < invoke
    has = k >= 0
    srcs.append(aux[k[has]])
    dsts.append(np.arange(n, dtype=np.int64)[has])
    return np.concatenate(srcs), np.concatenate(dsts)


def rt_aux_edges(enc: EncodedHistory
                 ) -> tuple[np.ndarray, np.ndarray, int]:
    """aux_chain over a whole encoded history. Returns (src, dst,
    n_aux)."""
    n = enc.n
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64), 0
    eff = effective_complete_index(enc.status, enc.complete_index)
    inv = np.asarray(enc.invoke_index, np.int64)
    src, dst = aux_chain(eff, inv)
    return src, dst, n


def _scc_csr(n_nodes: int, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    """SCC ids from edge arrays: CSR build in numpy, native Tarjan, and
    a pure-Python fallback when no toolchain exists."""
    order = np.argsort(src, kind="stable")
    col = dst[order]
    row_ptr = np.zeros(n_nodes + 1, np.int64)
    np.cumsum(np.bincount(src.astype(np.int64), minlength=n_nodes),
              out=row_ptr[1:])
    out = native_lib.tarjan_scc_csr(n_nodes, row_ptr, col)
    if out is not None:
        return out
    adj: list[list[int]] = [[] for _ in range(n_nodes)]
    for s, d in zip(src.tolist(), dst.tolist()):
        adj[s].append(d)
    return np.asarray(G._tarjan_scc_py(n_nodes, adj), np.int64)


def condense(enc: EncodedHistory, realtime: bool = False,
             process_order: bool = False) -> tuple[list[np.ndarray], tuple]:
    """Nontrivial SCCs (>= 2 txn rows each) of the full dependency
    graph. Returns (member-row arrays, cached (src, dst, cls) edges)."""
    src, dst, cls = build_edges_arrays(enc, process_order=process_order)
    if realtime:
        rs, rd, _ = rt_aux_edges(enc)
        all_src = np.concatenate([src, rs])
        all_dst = np.concatenate([dst, rd])
        n_nodes = 2 * enc.n
    else:
        all_src, all_dst = src, dst
        n_nodes = enc.n
    if len(all_src) == 0 or enc.n == 0:
        return [], (src, dst, cls)
    scc = _scc_csr(n_nodes, all_src, all_dst)[: enc.n]
    counts = np.bincount(scc)
    big = np.flatnonzero(counts >= 2)
    members = [np.flatnonzero(scc == b) for b in big]
    return members, (src, dst, cls)


# An SCC bigger than this goes to the host classifier instead of the
# dense device kernel (whose [T,T] matrices are what the condensation
# exists to avoid at full history size).
DEVICE_SCC_LIMIT = 8_192


def _classify_scc_host(enc: EncodedHistory, rows: np.ndarray,
                       src, dst, cls, keep, local,
                       realtime: bool) -> dict:
    """Host classification of one oversized SCC: graph.classify_cycles
    over the subgraph, with the realtime order carried by a
    member-local completion-rank aux chain (exact rt reachability, no
    dense [m,m] relation)."""
    m = len(rows)
    edges = list(zip(local[src[keep]].tolist(), local[dst[keep]].tolist(),
                     cls[keep].tolist()))
    n_nodes = m
    if realtime:
        eff = effective_complete_index(enc.status, enc.complete_index)[rows]
        inv = np.asarray(enc.invoke_index, np.int64)[rows]
        asrc, adst = aux_chain(eff, inv)   # member-local rt chain
        edges += [(int(s), int(d), G.RT) for s, d in zip(asrc, adst)]
        n_nodes = 2 * m
    res = G.classify_cycles(n_nodes, edges, want_witnesses=False)
    return {name: True for name in res}


def condensed_stats(enc: EncodedHistory, members, src, dst, cls,
                    realtime: bool) -> dict:
    """The host-side search-stats record for a condensed check — the
    long-history sibling of `kernels.stats_row`. Edge and SCC facts
    are exact (the condensation computed them anyway: distinct edges
    per class, nontrivial SCC count/shape from the native Tarjan, and
    the realtime-edge count via one searchsorted over the completion
    ranks rather than the O(n²) dense relation); closure-round/margin
    telemetry is -1 — no dense closure ran on this path, and an
    invented number would poison the planner's training data."""
    from . import graph as G2
    from . import kernels as K
    if len(src):
        distinct = np.unique(
            np.stack([src, dst, cls.astype(np.int64)], axis=1), axis=0)
        counts = np.bincount(distinct[:, 2], minlength=4)
    else:
        counts = np.zeros(4, np.int64)
    rt = 0
    if realtime and enc.n:
        eff = effective_complete_index(enc.status, enc.complete_index)
        inv = np.asarray(enc.invoke_index, np.int64)
        # |{(j, i): complete(j) < invoke(i)}| — a txn's own completion
        # never precedes its invocation, so self-pairs drop out free
        rt = int(np.searchsorted(np.sort(eff), inv, side="left").sum())
    sizes = np.asarray([len(m) for m in members], np.int64)
    has = len(sizes) > 0
    return {
        "ww_edges": int(counts[G2.WW]), "wr_edges": int(counts[G2.WR]),
        "rw_edges": int(counts[G2.RW]), "rt_edges": rt,
        "proc_edges": int(counts[G2.PROC]),
        "closure_rounds": -1,
        "cycle_round": 0 if has else -1,
        "scc_count": int(len(sizes)),
        "scc_max": int(sizes.max()) if has else 0,
        "scc_min": int(sizes.min()) if has else 0,
        "cycle_txns": int(sizes.sum()) if has else 0,
        "margin": -1,
        "n_txns": int(enc.n), "t_pad": int(enc.n),
        "closure_bound": K.closure_steps(max(enc.n, 1)),
        "pad_waste_cells": 0,
        "path": "condensed",
    }


def check_condensed(enc: EncodedHistory, *, classify: bool = True,
                    realtime: bool = False, process_order: bool = False,
                    devices=None,
                    device_scc_limit: int = DEVICE_SCC_LIMIT,
                    stats_out: list | None = None) -> dict:
    """Check ONE long history via SCC condensation. Returns the same
    {anomaly: True} flag dict as the dense device path.

    Valid histories (no nontrivial SCC) cost one numpy edge build plus
    one native Tarjan — no device dispatch at all. Anomalous ones ship
    each SCC subgraph to the batched classification kernel; restriction
    to the SCC is exact (module docstring). SCCs beyond
    `device_scc_limit` rows classify on the host instead (their dense
    [m,m] matrices are the very thing condensation avoids).

    `stats_out` (a list) gains one `condensed_stats` record for the
    history — the JEPSEN_TPU_KERNEL_STATS path."""
    members, (src, dst, cls) = condense(enc, realtime=realtime,
                                        process_order=process_order)
    if stats_out is not None:
        stats_out.append(condensed_stats(enc, members, src, dst, cls,
                                         realtime))
    if not members:
        return {}
    if not classify:
        return {"cycle": True}

    from . import kernels as K
    eff = effective_complete_index(enc.status, enc.complete_index)
    # One global local-id map + one argsort groups every (same-SCC)
    # edge by SCC id — O(E log E) total, independent of SCC count.
    local = np.full(enc.n, -1, np.int64)
    scc_of = np.full(enc.n, -1, np.int64)
    for b, rows in enumerate(members):
        local[rows] = np.arange(len(rows))
        scc_of[rows] = b
    same_idx = np.flatnonzero((scc_of[src] >= 0) &
                              (scc_of[src] == scc_of[dst]))
    grp = scc_of[src[same_idx]]
    order = np.argsort(grp, kind="stable")
    by_grp = same_idx[order]
    bounds = np.searchsorted(grp[order], np.arange(len(members) + 1))

    flags: dict = {}
    per_scc = []
    for b, rows in enumerate(members):
        keep = by_grp[bounds[b]:bounds[b + 1]]
        if len(rows) > device_scc_limit:
            flags.update(_classify_scc_host(
                enc, rows, src, dst, cls, keep, local, realtime))
            continue
        # PROC edges ride along as WW-class on device (same role:
        # cycle-strengthening order edges, kernels.py module doc).
        sub_cls = np.where(cls[keep] == G.PROC, G.WW, cls[keep])
        per_scc.append({
            "n": len(rows),
            "edges": list(zip(local[src[keep]].tolist(),
                              local[dst[keep]].tolist(),
                              sub_cls.tolist())),
            "invoke_index": np.asarray(enc.invoke_index)[rows],
            "complete_index": eff[rows],
            "process": np.asarray(enc.process)[rows],
        })
    if per_scc:
        # bucketed: many small SCCs padded to the largest one's T would
        # otherwise pack into a single over-budget [B,T,T]x3 dispatch
        # fused=False: every SCC here is cyclic by construction, so the
        # fused kernel's any-cycle cond would always fire and its
        # unseeded full closure would just re-walk what the chained
        # warm starts get for free
        for res in K.check_edge_batch_bucketed(per_scc, classify=True,
                                               realtime=realtime,
                                               process_order=False,
                                               devices=devices,
                                               fused=False):
            flags.update(res)
    return flags
