"""TPU kernel: batched just-in-time linearizability search.

The knossos `linear` analysis walks a history maintaining a set of
*configurations* — (model state, which currently-pending ops have
already been linearized) — pruning configurations that miss an op's
completion deadline. That search is irregular on a JVM but maps onto a
TPU as dense frontier algebra (SURVEY.md §7 stage 4):

- A configuration is two int32s: interned register state + a bitmask
  over pending-op slots. The frontier is a fixed [F] arena in
  HBM/VMEM, kept sorted and deduplicated.
- One *expansion round* applies every pending unapplied op to every
  configuration at once ([F, S] candidate grid on the VPU), merges with
  the originals, and compacts via two `lax.sort` passes (bitonic sorts
  — TPU-native) — candidate generation, dedup, and compaction are all
  branch-free.
- Expansion runs to fixpoint (a `lax.while_loop` with an
  equality-on-sorted-frontier exit) only at completion events; an op's
  completion then *filters* the frontier to configurations that
  linearized it, mirroring the just-in-time deadline rule.
- Indeterminate (:info) ops occupy a slot forever and never filter —
  they may linearize anywhere after invocation or not at all.

The whole event walk is one `lax.scan`, vmapped over histories and
sharded over the device mesh by the callers in `..` / `parallel`.
Frontier overflow (more live configurations than F) degrades the
verdict to "unknown", never to a wrong answer — the same pragmatism the
reference applies to Knossos memory blowups
(jepsen/src/jepsen/checker.clj:216-219).

Verdict parity with the CPU WGL engine (`__init__.wgl`) is the
acceptance criterion; `tests/test_knossos.py` checks it differentially.

Performance characteristics (measured, v5 lite single chip, etcd-shaped
CAS histories at concurrency 10): the CPU WGL engine wins on *valid*
histories by an order of magnitude — its depth-first greedy path rarely
backtracks, while this kernel pays the full frontier cost at every
completion, and the frontier arena genuinely needs to be large
(2^concurrency-ish) to avoid overflow. What the device path buys is
*shape-bound, predictable* cost: WGL degenerates exponentially on
highly-concurrent or invalid histories (the reference caps its output
because "writing these can take *hours*", checker.clj:216-219), while
the frontier walk costs the same whether the history is valid,
invalid, or adversarial. Hence the checker defaults to CPU and
`Linearizable(backend="tpu")` is the opt-in bounded-latency engine;
overflow degrades to "unknown" and re-routes to the CPU oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...devices import default_devices, ensure_platform_pin

ensure_platform_pin()
from ...util import pad_to_multiple
from .encode import (CAS, COMPLETE_EV, INVOKE_EV, READ, WRITE,
                     EncodedRegisterHistory, RegisterBatchShape,
                     pack_register_batch)

_BIG = jnp.int32(2**31 - 1)


def _step_register(state, f, a1, a2, known):
    """Vectorized CAS-register transition. Returns (ok, new_state).

    read: legal iff value unknown or equal to state; write: always
    legal; cas [old new]: legal iff state == old. A linearized cas
    always succeeds — a failed cas is a no-op, represented by *not*
    linearizing it (its completion filter never fires for :info ops,
    and :ok cas implies success)."""
    is_w = f == WRITE
    is_c = f == CAS
    is_r = f == READ
    ok = jnp.where(is_r, (known == 0) | (state == a1),
                   jnp.where(is_c, state == a1, True))
    new = jnp.where(is_w, a1, jnp.where(is_c, a2, state))
    return ok, new


def _sorted_unique(states, masks, valid, F: int):
    """Sort (state, mask) pairs with invalid entries last, mark first
    occurrences, compact the unique live ones into the first F slots.
    Returns (states, masks, valid, n_unique) each [F]."""
    k1 = jnp.where(valid, states, _BIG)
    k2 = jnp.where(valid, masks, _BIG)
    k1, k2, s, m, v = jax.lax.sort(
        (k1, k2, states, masks, valid.astype(jnp.int32)), num_keys=2)
    first = jnp.ones_like(k1, dtype=bool).at[1:].set(
        (k1[1:] != k1[:-1]) | (k2[1:] != k2[:-1]))
    keep = first & (v > 0)
    n_unique = jnp.sum(keep.astype(jnp.int32))
    # Canonical compaction: kept entries to the front in (state, mask)
    # order — a deterministic arrangement of the set, so the fixpoint
    # loop's equality exit is well-defined.
    ck = (~keep).astype(jnp.int32)
    _, s, m, v = jax.lax.sort(
        (ck, s, m, keep.astype(jnp.int32)), num_keys=3)
    return s[:F], m[:F], v[:F] > 0, n_unique


def _expand_fixpoint(states, masks, valid, slot_f, slot_a1, slot_a2,
                     slot_known, enabled, F: int, S: int,
                     with_stats: bool = False):
    """Close the frontier under single-op linearization: repeatedly apply
    every occupied, unapplied slot to every configuration until the
    sorted frontier stops changing. Returns (states, masks, valid,
    overflow) — plus (peak frontier width, rounds run, candidate
    configurations generated) under `with_stats` (the kernel-stats
    telemetry path; the frontier math itself is identical — the extra
    carry only observes it)."""
    slot_bits = jnp.int32(1) << jnp.arange(S, dtype=jnp.int32)

    def round_(states, masks, valid):
        occupied = slot_f >= 0                               # [S]
        unapplied = (masks[:, None] & slot_bits[None, :]) == 0
        can = valid[:, None] & occupied[None, :] & unapplied  # [F,S]
        ok, new_state = _step_register(
            states[:, None], slot_f[None, :], slot_a1[None, :],
            slot_a2[None, :], slot_known[None, :])
        can = can & ok
        cand_states = jnp.broadcast_to(new_state, (F, S)).reshape(-1)
        cand_masks = (masks[:, None] | slot_bits[None, :]).reshape(-1)
        all_states = jnp.concatenate([states, cand_states])
        all_masks = jnp.concatenate([masks, cand_masks])
        all_valid = jnp.concatenate([valid, can.reshape(-1)])
        s, m, v, n = _sorted_unique(all_states, all_masks, all_valid,
                                    F)
        changed = ~(jnp.all((s == states) & (m == masks))
                    & jnp.all(v == valid))
        n_cand = jnp.sum(can.astype(jnp.int32))
        return s, m, v, changed, n > F, n, n_cand

    def cond(front):
        # Bounded by S+2 rounds: any forced chain applies at most S ops,
        # and the bound also guarantees termination under frontier
        # truncation (where the verdict is already "unknown").
        return front[3] & (front[5] < S + 2)

    if with_stats:
        def body(front):
            s, m, v, changed, ovf, n, nc = round_(front[0], front[1],
                                                  front[2])
            return (s, m, v, changed, front[4] | ovf, front[5] + 1,
                    jnp.maximum(front[6], n), front[7] + nc)

        init = (states, masks, valid, enabled, jnp.bool_(False),
                jnp.int32(0), jnp.int32(0), jnp.int32(0))
        (states, masks, valid, _, overflow, rounds, peak,
         explored) = jax.lax.while_loop(cond, body, init)
        return states, masks, valid, overflow, peak, rounds, explored

    def body(front):
        s, m, v, changed, ovf, _n, _nc = round_(front[0], front[1],
                                                front[2])
        return s, m, v, changed, front[4] | ovf, front[5] + 1

    # First round unconditionally sorts/dedups the incoming frontier
    # (it may be unsorted after a completion filter); the exit test
    # compares successive sorted frontiers.
    init = (states, masks, valid, enabled, jnp.bool_(False),
            jnp.int32(0))
    states, masks, valid, _, overflow, _ = jax.lax.while_loop(
        cond, body, init)
    return states, masks, valid, overflow


def _scan_history(events, F: int, S: int, with_stats: bool = False):
    """Run the event walk for one history. events: [E, 6] int32.
    Returns (valid?, overflow) — plus (peak frontier width, expansion
    rounds, configurations generated) under `with_stats`."""
    E = events.shape[0]

    init = (
        jnp.zeros((F,), jnp.int32),                       # states
        jnp.zeros((F,), jnp.int32),                       # masks
        jnp.zeros((F,), bool).at[0].set(True),            # valid
        jnp.full((S,), -1, jnp.int32),                    # slot_f
        jnp.zeros((S,), jnp.int32),                       # slot_a1
        jnp.zeros((S,), jnp.int32),                       # slot_a2
        jnp.zeros((S,), jnp.int32),                       # slot_known
        jnp.bool_(False),                                 # overflow
    )
    if with_stats:
        init = init + (jnp.int32(1),                      # peak width
                       jnp.int32(0),                      # rounds
                       jnp.int32(0))                      # explored

    def step(carry, ev):
        (states, masks, valid, slot_f, slot_a1, slot_a2, slot_known,
         overflow, *stats) = carry
        kind, slot, f, a1, a2, known = (ev[0], ev[1], ev[2], ev[3],
                                        ev[4], ev[5])
        is_inv = kind == INVOKE_EV
        is_comp = kind == COMPLETE_EV

        slot_f = slot_f.at[slot].set(
            jnp.where(is_inv, f, slot_f[slot]))
        slot_a1 = slot_a1.at[slot].set(
            jnp.where(is_inv, a1, slot_a1[slot]))
        slot_a2 = slot_a2.at[slot].set(
            jnp.where(is_inv, a2, slot_a2[slot]))
        slot_known = slot_known.at[slot].set(
            jnp.where(is_inv, known, slot_known[slot]))

        if with_stats:
            (states, masks, valid, ovf, peak, rounds,
             explored) = _expand_fixpoint(
                states, masks, valid, slot_f, slot_a1, slot_a2,
                slot_known, is_comp, F, S, with_stats=True)
            stats = (jnp.maximum(stats[0], peak), stats[1] + rounds,
                     stats[2] + explored)
        else:
            states, masks, valid, ovf = _expand_fixpoint(
                states, masks, valid, slot_f, slot_a1, slot_a2,
                slot_known, is_comp, F, S)
        overflow |= ovf

        # Completion deadline: only configurations that linearized the
        # op survive; its slot bit retires and the slot frees.
        bit = (masks >> slot) & 1
        valid = valid & jnp.where(is_comp, bit == 1, True)
        masks = jnp.where(is_comp, masks & ~(jnp.int32(1) << slot),
                          masks)
        slot_f = slot_f.at[slot].set(
            jnp.where(is_comp, -1, slot_f[slot]))

        return (states, masks, valid, slot_f, slot_a1, slot_a2,
                slot_known, overflow) + tuple(stats), None

    carry, _ = jax.lax.scan(step, init, events, length=E)
    if with_stats:
        valid, overflow = carry[2], carry[7]
        return (jnp.any(valid), overflow, carry[8], carry[9],
                carry[10])
    states, masks, valid, *_rest, overflow = carry
    return jnp.any(valid), overflow


@functools.partial(jax.jit, static_argnames=("frontier", "n_slots",
                                             "with_stats"))
def check_batch_device(events, *, frontier: int = 512,
                       n_slots: int = 16, with_stats: bool = False):
    """Jitted batched entry: events [B, E, 6] -> (valid [B] bool,
    overflow [B] bool), plus (peak, rounds, explored) [B] int32 each
    under with_stats."""
    return jax.vmap(
        functools.partial(_scan_history, F=frontier, S=n_slots,
                          with_stats=with_stats))(events)


def check_encoded_batch(encs: list[EncodedRegisterHistory],
                        frontier: int = 512,
                        devices=None, packed: bool | None = None,
                        stats_out: list | None = None) -> list[dict]:
    """Check encoded register histories on device. Returns knossos-shaped
    verdicts: {"valid?": True|False|"unknown", "analyzer": "tpu-jit"}.

    Batches shard across addressable devices on a 1-D dp mesh (the
    analysis data plane, SURVEY.md §5.8); ragged batches are padded to a
    device multiple by replicating the last history (extras dropped) so
    sharding never silently degrades to one device.

    `packed=None` (auto) routes to the packed single-int32 kernel
    (`.packed`: 2 sort operands per compaction instead of 9; measured
    ~13x wall-clock on the CPU backend at conc-10) whenever every
    history's interned values fit `state << n_slots` in an int32 —
    differential parity with this kernel and the WGL oracle is pinned
    by tests/test_knossos.py::TestPackedKernelParity. An explicit
    packed=True downgrades to the unpacked kernel if the batch doesn't
    fit: aliased packings could return confident wrong verdicts, and
    this module never trades correctness for speed."""
    if not encs:
        return []
    n = len(encs)
    devices = devices if devices is not None else default_devices()
    encs = pad_to_multiple(encs, len(devices))
    batch = pack_register_batch(encs)
    shape: RegisterBatchShape = batch["shape"]
    events = jnp.asarray(batch["events"])

    if len(devices) > 1:
        mesh = jax.sharding.Mesh(np.asarray(devices), ("dp",))
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("dp"))
        events = jax.device_put(events, sharding)

    from .packed import packable
    fits = all(packable(e.n_values, shape.n_slots) for e in encs)
    with_stats = stats_out is not None
    # stats requested -> the unpacked kernel (the only one carrying
    # the telemetry carry); verdict parity between the two kernels is
    # pinned by tests, so the downgrade is observability-only
    packed = (fits if packed is None else (packed and fits)) \
        and not with_stats
    peak = rounds = explored = None
    if packed:
        from .packed import check_batch_device_packed
        valid, overflow = check_batch_device_packed(
            events, frontier=frontier, n_slots=shape.n_slots)
    elif with_stats:
        valid, overflow, peak, rounds, explored = check_batch_device(
            events, frontier=frontier, n_slots=shape.n_slots,
            with_stats=True)
        peak = np.asarray(peak)
        rounds = np.asarray(rounds)
        explored = np.asarray(explored)
    else:
        valid, overflow = check_batch_device(
            events, frontier=frontier, n_slots=shape.n_slots)
    valid = np.asarray(valid)
    overflow = np.asarray(overflow)
    out = []
    for i, e in enumerate(encs[:n]):
        if overflow[i]:
            out.append({"valid?": "unknown", "analyzer": "tpu-jit",
                        "cause": ":frontier-overflow"})
        else:
            out.append({"valid?": bool(valid[i]),
                        "analyzer": "tpu-jit",
                        "op-count": int(
                            (e.events[:, 0] == INVOKE_EV).sum())})
        if with_stats:
            stats_out.append({
                "engine": "tpu-jit",
                "frontier_peak": int(peak[i]),
                "frontier": int(frontier),
                "rounds": int(rounds[i]),
                "configs": int(explored[i]),
                "overflow": bool(overflow[i]),
                "n_slots": int(shape.n_slots)})
    return out
