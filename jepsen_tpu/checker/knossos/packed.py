"""Packed-configuration variant of the frontier linearizability kernel.

`kernels._scan_history` carries a configuration as two int32s (interned
register state, pending-slot bitmask) and its per-round compaction
sorts five operand arrays under two keys, twice per expansion round.
Almost every real history fits a far cheaper representation: when
`(n_values << n_slots) <= 2^31 - 1`, a configuration packs into ONE
int32 — `state << S | mask` — with 2^31-1 as the "empty slot" sentinel.
Sorting then moves a single int32 array (2 sort operands per
compaction round instead of 9 — measured ~13x wall-clock on the CPU
backend at conc-10, the sort being the kernel's dominant cost), dedup
is an adjacent compare on the packed key itself, and the fixpoint-exit
equality is one array compare.

Semantics are identical to the unpacked kernel (same expansion,
completion-filter, overflow and verdict rules — see kernels.py's
module docstring for the model); `tests/test_knossos.py` pins packed
vs unpacked vs the CPU WGL oracle differentially. `check_encoded_batch`
in kernels.py routes here automatically when every history in the
batch fits the packed budget, which conc-10 CAS histories always do
(S=10 leaves 21 bits for interned values) and conc-20 ones almost
always do (S=20 leaves 11 bits: 2047 distinct values).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...devices import ensure_platform_pin

ensure_platform_pin()
from .kernels import _BIG, _step_register
from .encode import COMPLETE_EV, INVOKE_EV


def packable(n_values: int, n_slots: int) -> bool:
    """Does state << S | mask stay below the _BIG sentinel?"""
    return n_slots < 31 and (n_values << n_slots) <= 2**31 - 1


def _sorted_unique_packed(cfgs, F: int):
    """Sort packed configs (invalid == _BIG last), drop duplicates,
    return (cfgs[:F], n_unique). Two single-operand sorts replace the
    unpacked path's 5-operand + 4-operand pair."""
    cfgs = jax.lax.sort(cfgs)
    dup = jnp.zeros_like(cfgs, dtype=bool).at[1:].set(
        cfgs[1:] == cfgs[:-1])
    cfgs = jnp.where(dup, _BIG, cfgs)
    n_unique = jnp.sum((cfgs != _BIG).astype(jnp.int32))
    cfgs = jax.lax.sort(cfgs)
    return cfgs[:F], n_unique


def _expand_fixpoint_packed(cfgs, slot_f, slot_a1, slot_a2, slot_known,
                            enabled, F: int, S: int):
    """Close the packed frontier under single-op linearization (the
    packed twin of kernels._expand_fixpoint)."""
    slot_bits = jnp.int32(1) << jnp.arange(S, dtype=jnp.int32)
    low = jnp.int32((1 << S) - 1)

    def round_(front):
        cfgs, _, overflow, _r = front
        live = cfgs != _BIG
        masks = cfgs & low
        states = cfgs >> S
        occupied = slot_f >= 0                                # [S]
        unapplied = (masks[:, None] & slot_bits[None, :]) == 0
        can = live[:, None] & occupied[None, :] & unapplied   # [F,S]
        ok, new_state = _step_register(
            states[:, None], slot_f[None, :], slot_a1[None, :],
            slot_a2[None, :], slot_known[None, :])
        can = can & ok
        cand = jnp.where(
            can,
            (jnp.broadcast_to(new_state, (F, S)) << S)
            | (masks[:, None] | slot_bits[None, :]),
            _BIG).reshape(-1)
        all_cfgs = jnp.concatenate([cfgs, cand])
        c, n = _sorted_unique_packed(all_cfgs, F)
        changed = jnp.any(c != cfgs)
        return c, changed, n > F, _r

    def cond(front):
        # Bounded by S+2 rounds, as in the unpacked kernel.
        return front[1] & (front[3] < S + 2)

    def body(front):
        c, changed, ovf, r = round_(front)
        return c, changed, front[2] | ovf, r + 1

    init = (cfgs, enabled, jnp.bool_(False), jnp.int32(0))
    cfgs, _, overflow, _ = jax.lax.while_loop(cond, body, init)
    return cfgs, overflow


def _scan_history_packed(events, F: int, S: int):
    """Event walk for one history over packed configs. events: [E, 6]
    int32. Returns (valid?, overflow)."""
    E = events.shape[0]

    init = (
        jnp.full((F,), _BIG, jnp.int32).at[0].set(0),      # cfgs
        jnp.full((S,), -1, jnp.int32),                     # slot_f
        jnp.zeros((S,), jnp.int32),                        # slot_a1
        jnp.zeros((S,), jnp.int32),                        # slot_a2
        jnp.zeros((S,), jnp.int32),                        # slot_known
        jnp.bool_(False),                                  # overflow
    )

    def step(carry, ev):
        cfgs, slot_f, slot_a1, slot_a2, slot_known, overflow = carry
        kind, slot, f, a1, a2, known = (ev[0], ev[1], ev[2], ev[3],
                                        ev[4], ev[5])
        is_inv = kind == INVOKE_EV
        is_comp = kind == COMPLETE_EV

        slot_f = slot_f.at[slot].set(jnp.where(is_inv, f, slot_f[slot]))
        slot_a1 = slot_a1.at[slot].set(
            jnp.where(is_inv, a1, slot_a1[slot]))
        slot_a2 = slot_a2.at[slot].set(
            jnp.where(is_inv, a2, slot_a2[slot]))
        slot_known = slot_known.at[slot].set(
            jnp.where(is_inv, known, slot_known[slot]))

        cfgs, ovf = _expand_fixpoint_packed(
            cfgs, slot_f, slot_a1, slot_a2, slot_known, is_comp, F, S)
        overflow |= ovf

        # Completion deadline. _BIG has every low bit set, so the
        # sentinel must be exempted explicitly before the bit test.
        live = cfgs != _BIG
        bit = (cfgs >> slot) & 1
        keep = live & (bit == 1)
        filtered = jnp.where(keep, cfgs & ~(jnp.int32(1) << slot), _BIG)
        cfgs = jnp.where(is_comp, filtered, cfgs)
        slot_f = slot_f.at[slot].set(
            jnp.where(is_comp, -1, slot_f[slot]))

        return (cfgs, slot_f, slot_a1, slot_a2, slot_known,
                overflow), None

    carry, _ = jax.lax.scan(step, init, events, length=E)
    cfgs, *_rest, overflow = carry
    return jnp.any(cfgs != _BIG), overflow


@functools.partial(jax.jit, static_argnames=("frontier", "n_slots"))
def check_batch_device_packed(events, *, frontier: int = 512,
                              n_slots: int = 16):
    """Jitted packed entry: events [B, E, 6] -> (valid [B], overflow
    [B])."""
    return jax.vmap(
        functools.partial(_scan_history_packed, F=frontier,
                          S=n_slots))(events)
