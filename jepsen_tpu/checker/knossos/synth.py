"""Synthetic register histories for benchmarks and differential tests.

Simulates a real atomic register: each operation takes effect at one
instant between its invocation and completion, so generated histories
are linearizable by construction — the Knossos analogue of
`..elle.synth` for list-append. `corrupt` flips one ok-read's value,
which (almost always) breaks linearizability.

Shapes mirror the etcd suite's independent CAS registers
(etcd/src/jepsen/etcd.clj:149-180: 10 threads/key, a few hundred ops
per key) so benchmark batches look like real per-key subhistories.
"""

from __future__ import annotations

import random


def _op(type_: str, process: int, f: str, value=None) -> dict:
    return {"type": type_, "process": process, "f": f, "value": value}


def synth_register_history(n_ops: int = 100, n_procs: int = 10,
                           n_values: int = 5, info_prob: float = 0.02,
                           seed: int = 0,
                           max_pending: int | None = None) -> list[dict]:
    """One linearizable register history: `n_ops` read/write/cas ops
    from `n_procs` concurrent processes.

    `max_pending` bounds how many invocations are simultaneously open
    (crashed `info` ops count — they stay open forever). The uniform
    walk otherwise keeps ~all procs saturated, which is the worst case
    for windowed checkers: real staggered workloads at high nominal
    concurrency have much lower instantaneous overlap."""
    rng = random.Random(f"knossos-synth:{seed}")
    hist: list[dict] = []
    value = None
    free = list(range(n_procs))
    pending: list[list] = []  # [process, op, applied?, result]
    crashed = 0               # info ops: open slots for the checker
    ops_left = n_ops
    while ops_left > 0 or pending:
        choices = []
        if free and ops_left > 0 and (
                max_pending is None
                or len(pending) + crashed < max_pending):
            choices.append("invoke")
        if any(not p[2] for p in pending):
            choices.append("apply")
        if any(p[2] for p in pending):
            choices.append("complete")
        if not choices:
            # every slot crashed away under a tight max_pending: end
            # the walk early — the cap is a hard encodability contract
            # (crashed ops hold checker slots forever, so letting an
            # invoke through would silently exceed it)
            break
        action = rng.choice(choices)
        if action == "invoke":
            p = free.pop(rng.randrange(len(free)))
            f = rng.choice(["read", "write", "cas"])
            if f == "read":
                o = _op("invoke", p, "read")
            elif f == "write":
                o = _op("invoke", p, "write", rng.randrange(n_values))
            else:
                o = _op("invoke", p, "cas",
                        [rng.randrange(n_values), rng.randrange(n_values)])
            hist.append(o)
            pending.append([p, o, False, None])
            ops_left -= 1
        elif action == "apply":
            ent = rng.choice([p for p in pending if not p[2]])
            f, v = ent[1]["f"], ent[1]["value"]
            if f == "read":
                ent[3] = ("ok", value)
            elif f == "write":
                value = v
                ent[3] = ("ok", v)
            else:
                old, new = v
                if old == value:
                    value = new
                    ent[3] = ("ok", v)
                else:
                    ent[3] = ("fail", v)
            ent[2] = True
        else:
            ent = rng.choice([p for p in pending if p[2]])
            pending.remove(ent)
            p, o = ent[0], ent[1]
            if rng.random() < info_prob:
                hist.append(_op("info", p, o["f"], o["value"]))
                crashed += 1
            else:
                t, rv = ent[3]
                hist.append(_op(t, p, o["f"], rv))
            free.append(p)
    return hist


def corrupt(hist: list[dict], seed: int = 0) -> list[dict]:
    """Flip one ok read's value — usually breaking linearizability."""
    rng = random.Random(f"knossos-corrupt:{seed}")
    hist = [dict(o) for o in hist]
    reads = [o for o in hist if o["type"] == "ok" and o["f"] == "read"]
    if reads:
        o = rng.choice(reads)
        o["value"] = (o["value"] or 0) + 7
    return hist


def synth_register_batch(B: int = 100, n_ops: int = 500,
                         n_procs: int = 10, n_values: int = 5,
                         info_prob: float = 0.02,
                         seed: int = 0,
                         max_pending: int | None = None
                         ) -> list[list[dict]]:
    """B independent per-key subhistories, etcd-shaped."""
    return [synth_register_history(n_ops=n_ops, n_procs=n_procs,
                                   n_values=n_values, info_prob=info_prob,
                                   seed=seed * 10_000 + i,
                                   max_pending=max_pending)
            for i in range(B)]
