"""History -> tensor encoding for the TPU linearizability kernel.

Register-shaped histories (f in {read, write, cas} — the model family the
reference checks with knossos.model/cas-register; see the etcd suite's
client ops and jepsen/src/jepsen/checker.clj:188-219) compile to a dense
event stream:

    events[E, 6] int32 = (kind, slot, f, arg1, arg2, known)

kind: 0 invoke, 1 complete, 2 pad. Each determinate op contributes an
invoke and a complete event at its real-time positions; indeterminate
(:info) ops contribute only an invoke — their return is at infinity, so
they occupy a pending slot forever and are never *required* to
linearize. `slot` is a dense pending-op slot id (freed on completion);
the kernel tracks "which pending slots has this configuration already
applied" as a bitmask over slots, so the maximum concurrent pending
count must stay under the kernel's slot budget.

Register values are interned to small ints: nil -> 0, observed values
-> 1..V-1. `known` = 0 marks reads whose value is unknown (indeterminate
reads), which constrain nothing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np


READ, WRITE, CAS, ACQUIRE, RELEASE = 0, 1, 2, 3, 4
INVOKE_EV, COMPLETE_EV, PAD_EV = 0, 1, 2

_F_CODES = {"read": READ, "write": WRITE, "cas": CAS}


class EncodingError(ValueError):
    """History doesn't fit the register kernel (unknown :f, too much
    concurrency, non-internable values). Callers fall back to the CPU
    engine."""


@dataclass
class EncodedRegisterHistory:
    events: np.ndarray      # [E, 6] int32
    n_events: int
    n_slots: int            # max concurrently-pending ops
    n_values: int           # interned values incl. nil
    values: list            # intern table, index -> original value
    #: max simultaneously-open UNCONDITIONAL ops — writes, plus reads
    #: whose return value is unknown: those apply in any order, so each
    #: open one roughly doubles the frontier. Open cas ops and
    #: known-value reads instead PRUNE on state mismatch (about half a
    #: doubling each, empirically).
    uncond_peak: int = 0
    #: max over time of (2*open_unconditional + open_conditional) —
    #: the JOINT per-moment load in half-doublings. Summing the two
    #: independently-attained maxima would overstate histories whose
    #: conditional and unconditional phases don't coincide.
    #: The tiered router's feasibility signal: ~2^(peak/2) configs.
    half_doublings_peak: int = 0


def _reduced_seq(raw_history: list[dict]) -> list[tuple]:
    """The dict-free twin of reduce_history for the encoder: tuple
    passes replicating client_ops / complete / remove_failures — each
    with ITS OWN pairing semantics, which diverge on malformed
    histories. The reduction pairing runs over the PRE-deletion op
    list while the encoder re-pairs the post-deletion survivors — a
    stray ok can complete a stale invoke once the fail pair between
    them is deleted, so reduction and encoder pairing must stay
    separate (complete and remove_failures themselves share one
    pairing and are fused below). Output rows are
    (kind, process, f, value) with kind in {0 invoke, 1 info,
    2 other-completion}; ok-completed invocations carry the
    completion's value; failed pairs and fail ops are gone. ~2x the
    encoder throughput vs materializing three dict lists; events
    equality with the dict pipeline is pinned by
    tests/test_knossos.py's reduction-parity fuzz and verdict parity
    by the kernel-vs-oracle differentials."""
    items: list = []           # (ty, p, f, v) client ops, in order
    for o in raw_history:
        p = o.get("process")
        if not isinstance(p, int):
            continue
        items.append((o.get("type"), p, o.get("f"), o.get("value")))

    # complete() + remove_failures() share one pairing (both pair over
    # the PRE-deletion op list with pending popped by any completion
    # type): ok completions hand their value to THEIR invocation,
    # nil-valued info completions inherit the invocation's value, and
    # pairs-matched fail completions delete their invocation (every
    # fail op vanishes regardless)
    value = [v for _ty, _p, _f, v in items]
    pend: dict = {}
    dropped: set = set()
    for i, (ty, p, f, v) in enumerate(items):
        if ty == "invoke":
            pend[p] = i
            continue
        j = pend.pop(p, None)
        if ty == "fail":
            dropped.add(i)
            if j is not None:
                dropped.add(j)
        elif j is not None:
            if ty == "ok":
                value[j] = v
            elif ty == "info" and v is None:
                value[i] = value[j]

    # surviving ops, completion-kind resolved; the encoder walk does
    # its own slot pairing exactly as it did over the dict list
    out: list = []
    for i, (ty, p, f, v) in enumerate(items):
        if i in dropped:
            continue
        if ty == "invoke":
            out.append((0, p, f, value[i]))
        elif ty == "info":
            out.append((1, p, f, value[i]))
        else:                  # ok or unknown completion type
            out.append((2, p, f, v))
    return out


_F_CODES_MUTEX = {"acquire": ACQUIRE, "release": RELEASE}


def encode_mutex_history(raw_history: list[dict],
                         max_slots: int = 4096) -> "np.ndarray":
    """Compile a mutex history (acquire/release, no values) into the
    [E, 6] event stream the native WGL search consumes — same slot
    bookkeeping as the register encoder, no interning (the lock's
    state space is {free, held})."""
    hist = _reduced_seq(raw_history)
    events: list = []
    slot_of: dict = {}
    free: list = []
    next_slot = 0
    for kind, p, fname, v in hist:
        if kind == 0:
            f = _F_CODES_MUTEX.get(fname)
            if f is None:
                raise EncodingError(f"unencodable mutex op f={fname!r}")
            if free:
                slot = free.pop()
            else:
                slot = next_slot
                next_slot += 1
                if next_slot > max_slots:
                    raise EncodingError(
                        f"concurrency exceeds {max_slots} pending slots")
            slot_of[p] = slot
            events.append((INVOKE_EV, slot, f, 0, 0, 0))
        elif p in slot_of:
            slot = slot_of.pop(p)
            if kind == 1:
                continue   # info: return at infinity, slot stays held
            events.append((COMPLETE_EV, slot, 0, 0, 0, 0))
            free.append(slot)
    return np.asarray(events, np.int32).reshape(-1, 6)


def encode_register_history(raw_history: list[dict],
                            max_slots: int = 24) -> EncodedRegisterHistory:
    """Compile one register history into the kernel event stream."""
    hist = _reduced_seq(raw_history)
    intern: dict[Any, int] = {None: 0}
    values: list = [None]
    vkind: dict[int, str] = {}

    def vid(v: Any) -> int:
        # lists intern as tuples (hashability). If an EQUAL tuple value
        # also occurs, the intern map would equate what the Python
        # model's == distinguishes — the interned engines could then
        # mask a real violation, so such histories are unencodable and
        # route to the Python oracle instead.
        kind = "list" if isinstance(v, list) else (
            "tuple" if isinstance(v, tuple) else "scalar")
        if kind == "list":
            v = tuple(v)
        i = intern.get(v)
        if i is None:
            i = len(values)
            intern[v] = i
            values.append(v)
        if kind != "scalar":
            prev = vkind.setdefault(i, kind)
            if prev != kind:
                raise EncodingError(
                    "value interned from both a list and an equal "
                    "tuple: interned comparison would diverge from "
                    "the model's")
        return i

    events: list[tuple[int, int, int, int, int, int]] = []
    slot_of: dict[Any, int] = {}       # process -> slot
    kind_of: dict[int, bool] = {}      # slot -> counts as unconditional
    free: list[int] = []
    next_slot = 0
    peak = 0
    open_now = 0
    open_uncond = 0
    uncond_peak = 0
    half_peak = 0

    for kind, p, fname, v in hist:
        if kind == 0:          # invoke
            f = _F_CODES.get(fname)
            if f is None:
                raise EncodingError(f"unencodable op f={fname!r}")
            if free:
                slot = free.pop()
            else:
                slot = next_slot
                next_slot += 1
                peak = max(peak, next_slot)
                if next_slot > max_slots:
                    raise EncodingError(
                        f"concurrency exceeds {max_slots} pending slots")
            slot_of[p] = slot
            if f == CAS:
                if not (isinstance(v, (list, tuple)) and len(v) == 2):
                    raise EncodingError(f"cas value {v!r} is not [old new]")
                a1, a2, known = vid(v[0]), vid(v[1]), 1
            elif f == WRITE:
                a1, a2, known = vid(v), 0, 1
            else:  # READ: value known only for determinate reads
                known = 0 if v is None else 1
                a1, a2 = (vid(v) if known else 0), 0
            events.append((INVOKE_EV, slot, f, a1, a2, known))
            # writes always apply; unknown-value reads apply anywhere;
            # cas and known-value reads prune on state mismatch
            uncond = f == WRITE or (f == READ and not known)
            kind_of[slot] = uncond
            open_now += 1
            if uncond:
                open_uncond += 1
                uncond_peak = max(uncond_peak, open_uncond)
            half_peak = max(half_peak, open_now + open_uncond)
        elif p in slot_of:
            slot = slot_of.pop(p)
            if kind == 1:
                # info: return at infinity — slot stays occupied, no
                # event (and, if unconditional, keeps inflating the
                # frontier forever; uncond_peak already counts it)
                continue
            events.append((COMPLETE_EV, slot, 0, 0, 0, 0))
            open_now -= 1
            if kind_of.pop(slot, False):
                open_uncond -= 1
            free.append(slot)
    arr = np.asarray(events, np.int32).reshape(-1, 6)
    return EncodedRegisterHistory(
        events=arr, n_events=len(events), n_slots=max(peak, 1),
        n_values=len(values), values=values,
        uncond_peak=uncond_peak, half_doublings_peak=half_peak)


@dataclass(frozen=True)
class RegisterBatchShape:
    """Static padding plan for a batch of encoded register histories."""

    n_events: int
    n_slots: int

    @staticmethod
    def plan(encs: list[EncodedRegisterHistory],
             multiple: int = 8) -> "RegisterBatchShape":
        ev = max((e.n_events for e in encs), default=1)
        ev = max(multiple, ((ev + multiple - 1) // multiple) * multiple)
        return RegisterBatchShape(
            n_events=ev,
            n_slots=max((e.n_slots for e in encs), default=1))


def pack_register_batch(encs: list[EncodedRegisterHistory],
                        shape: RegisterBatchShape | None = None) -> dict:
    """Stack encoded histories into one padded [B, E, 6] tensor."""
    shape = shape or RegisterBatchShape.plan(encs)
    B = len(encs)
    events = np.full((B, shape.n_events, 6), 0, np.int32)
    events[:, :, 0] = PAD_EV
    for i, e in enumerate(encs):
        if e.n_events > shape.n_events or e.n_slots > shape.n_slots:
            raise ValueError(f"history {i} exceeds batch shape {shape}")
        events[i, : e.n_events] = e.events
    return {"events": events, "shape": shape}
