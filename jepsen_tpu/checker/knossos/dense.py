"""Dense-bitset linearizability kernel — the fast TPU Knossos path.

The sorted-frontier kernel (`.kernels`) keeps a bounded arena of live
(state, mask) configurations and pays two bitonic sorts per expansion
round. This module replaces the arena with the *whole* configuration
space as a dense boolean occupancy grid

    valid[V, M]   V = interned register values, M = 2^S pending slots

which turns the just-in-time linearizability search (knossos.linear,
jepsen/src/jepsen/checker.clj:188-219) into pure dense algebra:

- dedup is free (a bitset has no duplicates),
- one expansion round = a gather (configurations that haven't applied
  slot s) + one small matmul on the MXU (scatter linearized states
  through a one-hot transition matrix) + an OR,
- the completion filter and slot-retire are two static gathers,
- there is NO frontier overflow: the grid covers every configuration,
  so verdicts are exact — never "unknown" (the reference's truncation
  pragmatism, checker.clj:216-219, is simply unnecessary here).

Two exact reductions keep the grid small:

1. Indeterminate (:info) *reads* are dropped at encode time: they never
   filter (no completion) and never change the register, so whether or
   when they linearize cannot affect any other configuration's
   reachability.
2. The event walk visits *completions only*. Between completions the
   frontier can only grow, and growth is forced lazily by the next
   completion's deadline; the pending-slot register file at each
   completion is history-determined, so it is precomputed on the host
   as a [C, S, 4] timeline and the kernel's sequential depth is C
   (completions), not E (all events).

Histories whose pending-slot peak exceeds the grid budget (long runs
with many crashed writes/cas — each occupies a slot forever) raise
EncodingError and fall back to the CPU WGL oracle, which is fast on
exactly the low-concurrency-per-instant shapes the grid can't hold.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ...devices import default_devices, ensure_platform_pin

ensure_platform_pin()
from ...util import pad_to_multiple
from .encode import CAS, READ, WRITE, EncodingError, _reduced_seq

_F_CODES = {"read": READ, "write": WRITE, "cas": CAS}


@dataclass
class DenseEncoded:
    """Per-completion slot-register timeline for one history."""

    regs: np.ndarray       # [C, S, 4] int32: (f|-1, a1, a2, known)
    comp_slot: np.ndarray  # [C] int32: slot completing at each step
    n_steps: int
    n_slots: int
    n_values: int
    n_ops: int             # determinate+indeterminate ops linearized over


def encode_dense_history(raw_history: list[dict], max_slots: int = 14,
                         max_values: int = 64) -> DenseEncoded:
    """Compile one register history to the dense kernel's timeline."""
    hist = _reduced_seq(raw_history)   # dict-free reduce_history twin

    # Which invocations never complete determinately? (info ops, and
    # open calls at history end). Info *reads* are dropped entirely.
    opens: dict = {}
    determinate: set[int] = set()
    for i, (kind, p, f, v) in enumerate(hist):
        if kind == 0:
            opens[p] = i
        elif p in opens:
            j = opens.pop(p)
            if kind != 1:
                determinate.add(j)

    intern: dict = {None: 0}
    values: list = [None]

    vkind: dict[int, str] = {}

    def vid(v):
        # same list/tuple ambiguity rule as encode.vid: equating what
        # the model distinguishes is unencodable
        kind = ("list" if isinstance(v, list)
                else "tuple" if isinstance(v, tuple) else "scalar")
        if kind == "list":
            v = tuple(v)
        i = intern.get(v)
        fresh = i is None
        if fresh:
            i = len(values)
            intern[v] = i
            values.append(v)
        if kind != "scalar" and vkind.setdefault(i, kind) != kind:
            raise EncodingError(
                "value interned from both a list and an equal tuple")
        if fresh:
            if len(values) > max_values:
                raise EncodingError(
                    f"more than {max_values} distinct register values")
        return i

    S = max_slots
    regs = np.full((S, 4), -1, np.int32)
    regs[:, 1:] = 0
    slot_of: dict = {}
    free = list(range(S))  # kept sorted: lowest slot first, compact peak
    steps_regs: list[np.ndarray] = []
    steps_comp: list[int] = []
    n_ops = 0
    peak = 1

    for i, (kind, p, fname, v) in enumerate(hist):
        if kind == 0:
            f = _F_CODES.get(fname)
            if f is None:
                raise EncodingError(f"unencodable op f={fname!r}")
            if i not in determinate and f == READ:
                continue  # reduction 1: info reads constrain nothing
            if not free:
                raise EncodingError(
                    f"concurrency exceeds {S} pending slots")
            slot = free.pop(0)
            peak = max(peak, slot + 1)
            slot_of[p] = slot
            if f == CAS:
                if not (isinstance(v, (list, tuple)) and len(v) == 2):
                    raise EncodingError(f"cas value {v!r} is not [old new]")
                row = (f, vid(v[0]), vid(v[1]), 1)
            elif f == WRITE:
                row = (f, vid(v), 0, 1)
            else:
                known = 0 if v is None else 1
                row = (f, vid(v) if known else 0, 0, known)
            regs[slot] = row
            n_ops += 1
        elif p in slot_of:
            slot = slot_of.pop(p)
            if kind == 1:
                continue  # return at infinity: slot stays occupied
            steps_regs.append(regs.copy())
            steps_comp.append(slot)
            regs[slot] = (-1, 0, 0, 0)
            free.append(slot)
            free.sort()

    C = len(steps_regs)
    return DenseEncoded(
        regs=(np.stack(steps_regs)[:, :peak] if C
              else np.full((0, peak, 4), -1, np.int32)),
        comp_slot=np.asarray(steps_comp, np.int32),
        n_steps=C, n_slots=peak, n_values=len(values), n_ops=n_ops)


@dataclass(frozen=True)
class DenseBatchShape:
    n_steps: int
    n_slots: int
    n_values: int

    @staticmethod
    def plan(encs: list[DenseEncoded], multiple: int = 8,
             v_multiple: int = 8) -> "DenseBatchShape":
        c = max((e.n_steps for e in encs), default=1)
        c = max(multiple, -(-c // multiple) * multiple)
        v = max((e.n_values for e in encs), default=1)
        v = max(v_multiple, -(-v // v_multiple) * v_multiple)
        return DenseBatchShape(
            n_steps=c,
            n_slots=max((e.n_slots for e in encs), default=1),
            n_values=v)


def pack_dense_batch(encs: list[DenseEncoded],
                     shape: DenseBatchShape | None = None) -> dict:
    """Stack timelines into [B, C, S, 4] / [B, C]; pad steps with
    comp_slot = -1 (a no-op step: no expansion, no filter)."""
    shape = shape or DenseBatchShape.plan(encs)
    B = len(encs)
    regs = np.full((B, shape.n_steps, shape.n_slots, 4), -1, np.int32)
    regs[..., 1:] = 0
    comp = np.full((B, shape.n_steps), -1, np.int32)
    for i, e in enumerate(encs):
        if (e.n_steps > shape.n_steps or e.n_slots > shape.n_slots
                or e.n_values > shape.n_values):
            raise ValueError(f"history {i} exceeds batch shape {shape}")
        regs[i, : e.n_steps, : e.n_slots] = e.regs
        comp[i, : e.n_steps] = e.comp_slot
    return {"regs": regs, "comp": comp, "shape": shape}


def _has_bit_table(S: int) -> np.ndarray:
    """Static [S, M] table: does mask m contain bit s?"""
    m = np.arange(1 << S, dtype=np.int32)[None, :]
    s = np.arange(S, dtype=np.int32)[:, None]
    return ((m >> s) & 1).astype(bool)


def _scan_dense(regs, comp, V: int, S: int, with_stats: bool = False):
    """One history: regs [C, S, 4], comp [C] -> valid? (exact); with
    `with_stats`, additionally (peak occupied configurations, total
    expansion rounds) — the dense grid's search telemetry (the grid
    has no overflow, so occupancy IS the frontier-width analogue).

    Gather-free: the mask-axis index maps (m -> m & ~bit_s on expansion,
    m -> m | bit_s on retire) are wrap-free shifts by 2^s over the
    entries that lack/have bit s, so they lower to static rolls + masks
    instead of TPU gathers; the value-axis scatter u -> new_v[u, s] has
    only three cases per op kind (read: identity, write: collapse to
    a1, cas: move row a1 to row a2), so it is select/reduce algebra on
    the VPU rather than a one-hot matmul."""
    M = 1 << S
    has_t = jnp.asarray(_has_bit_table(S))  # [S, M]
    lacks_t = ~has_t
    v_ids = jnp.arange(V, dtype=jnp.int32)

    valid0 = jnp.zeros((V, M), bool).at[0, 0].set(True)

    def step(carry, xs):
        valid, *stats = carry
        r, cs = xs
        f, a1, a2, known = r[:, 0], r[:, 1], r[:, 2], r[:, 3]
        occupied = f >= 0
        is_w = f == WRITE
        is_c = f == CAS
        is_r = f == READ
        # ok[u, s]: may config with state u linearize slot s?
        ok = jnp.where(is_r[None, :],
                       (known[None, :] == 0) | (v_ids[:, None] == a1[None, :]),
                       jnp.where(is_c[None, :],
                                 v_ids[:, None] == a1[None, :], True))
        ok = ok & occupied[None, :]
        onehot_a1 = v_ids[:, None] == a1[None, :]            # [V, S]
        onehot_a2 = v_ids[:, None] == a2[None, :]

        def round_(carry):
            valid, _changed, rnd = carry
            # x[u, s, m] = valid[u, m & ~bit_s] for m with bit s, gated
            # by ok: masks lacking s shifted up by 2^s (wrap-free since
            # bit s is clear in every unmasked source index).
            x = jnp.stack(
                [jnp.roll(valid & lacks_t[s][None, :], 1 << s, axis=1)
                 for s in range(S)], axis=1)                 # [V, S, M]
            x = x & ok[:, :, None]
            # value transition per op kind
            anyx = jnp.any(x, axis=0)                        # [S, M]
            rowa1 = jnp.any(x & onehot_a1[:, :, None], axis=0)
            add = jnp.any(
                jnp.where(is_r[None, :, None], x,
                          jnp.where(is_w[None, :, None],
                                    onehot_a1[:, :, None] & anyx[None, :, :],
                                    onehot_a2[:, :, None] & rowa1[None, :, :])),
                axis=1)                                      # [V, M]
            nv = valid | add
            return nv, jnp.any(nv != valid), rnd + 1

        def cond(carry):
            return carry[1] & (carry[2] < S + 2)

        valid, _, rnd = jax.lax.while_loop(
            cond, round_, (valid, cs >= 0, jnp.int32(0)))
        if with_stats:
            occ = jnp.sum(valid).astype(jnp.int32)
            stats = (jnp.maximum(stats[0], occ), stats[1] + rnd)

        # completion deadline: survivors linearized slot cs; retire its
        # bit: valid'[v, m'] = valid[v, m' | bit_cs] for m' lacking cs —
        # a wrap-free shift down by 2^cs, selected from S static rolls
        # (a dynamic-shift roll would lower to a gather under vmap).
        retired = jnp.zeros_like(valid)
        for s in range(S):
            r_s = jnp.roll(valid, -(1 << s), axis=1) & lacks_t[s][None, :]
            retired = jnp.where(cs == s, r_s, retired)
        valid = jnp.where(cs >= 0, retired, valid)
        return (valid,) + tuple(stats), None

    init = (valid0, jnp.int32(1), jnp.int32(0)) if with_stats \
        else (valid0,)
    carry, _ = jax.lax.scan(step, init, (regs, comp))
    if with_stats:
        return jnp.any(carry[0]), carry[1], carry[2]
    return jnp.any(carry[0])


@functools.partial(jax.jit, static_argnames=("n_values", "n_slots",
                                             "with_stats"))
def check_dense_device(regs, comp, *, n_values: int, n_slots: int,
                       with_stats: bool = False):
    """Jitted batched entry: regs [B,C,S,4], comp [B,C] -> valid [B]
    (plus peak-occupancy and rounds [B] int32 under with_stats)."""
    return jax.vmap(
        functools.partial(_scan_dense, V=n_values, S=n_slots,
                          with_stats=with_stats))(regs, comp)


def check_encoded_dense_batch(encs: list[DenseEncoded],
                              devices=None,
                              stats_out: list | None = None
                              ) -> list[dict]:
    """Check dense-encoded histories on device; exact verdicts.

    Histories are bucketed by pending-slot peak so one high-concurrency
    history doesn't double the M = 2^S grid for the whole batch; each
    bucket is one dispatch, sharded over a 1-D dp mesh (ragged buckets
    pad by replicating the last history, extras dropped)."""
    if not encs:
        return []
    devices = devices if devices is not None else default_devices()
    buckets: dict[int, list[int]] = {}
    for i, e in enumerate(encs):
        # bucket key: slots rounded up to even — halves compiled-shape
        # diversity for at most one doubling of M within a bucket
        buckets.setdefault(e.n_slots + (e.n_slots & 1), []).append(i)
    out: list[dict | None] = [None] * len(encs)
    with_stats = stats_out is not None
    sout: list = [None] * len(encs)
    for _slots, idxs in sorted(buckets.items()):
        group = [encs[i] for i in idxs]
        padded = pad_to_multiple(group, len(devices))
        batch = pack_dense_batch(padded)
        shape: DenseBatchShape = batch["shape"]
        regs = jnp.asarray(batch["regs"])
        comp = jnp.asarray(batch["comp"])
        if len(devices) > 1:
            mesh = jax.sharding.Mesh(np.asarray(devices), ("dp",))
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("dp"))
            regs = jax.device_put(regs, sharding)
            comp = jax.device_put(comp, sharding)
        if with_stats:
            valid, peak, rounds = check_dense_device(
                regs, comp, n_values=shape.n_values,
                n_slots=shape.n_slots, with_stats=True)
            peak = np.asarray(peak)
            rounds = np.asarray(rounds)
        else:
            valid = check_dense_device(
                regs, comp, n_values=shape.n_values,
                n_slots=shape.n_slots)
        valid = np.asarray(valid)
        for j, i in enumerate(idxs):
            out[i] = {"valid?": bool(valid[j]), "analyzer": "tpu-dense",
                      "op-count": encs[i].n_ops}
            if with_stats:
                sout[i] = {
                    "engine": "tpu-dense",
                    "frontier_peak": int(peak[j]),
                    "grid_configs": int(shape.n_values
                                        * (1 << shape.n_slots)),
                    "rounds": int(rounds[j]),
                    "n_slots": int(shape.n_slots)}
    if with_stats:
        stats_out.extend(sout)
    return out  # type: ignore[return-value]
