"""Linearizability checking — the Knossos-equivalent engine.

The reference delegates linearizability to the knossos library
(jepsen/src/jepsen/checker.clj:188-219): `knossos.wgl/analysis` (the
Wing-Gong-Lowe search), `knossos.linear/analysis` (just-in-time
configuration search), and `knossos.competition/analysis` (race both).
This package rebuilds that capability natively:

- CPU reference (this module): an iterative WGL search with a
  (linearized-set, model-state) memo cache, over any `models.Model`.
  This is the verdict oracle for kernel parity tests.
- TPU path (`.kernels`): just-in-time linearizability as a batched
  frontier expansion over (state, pending-mask) configurations in HBM,
  vmapped across histories — the analogue of knossos.linear, designed
  for the MXU/VPU rather than translated from the JVM search.

History semantics follow knossos: a history is completed
(`history.complete`) so ok reads know their returned value; definite
failures are dropped (`history.remove_failures`); `:info` ops may or
may not have taken effect — their linearization point, if any, lies
anywhere after their invocation (modelled as a return at infinity, and
they are never *required* to linearize).

Verdict shape mirrors knossos analyses: `{"valid?": True|False|"unknown",
"op-count": N, ...}` with `configs` / `final-paths` truncated to 10
entries, matching the reference's cost-control pragmatism
(jepsen/src/jepsen/checker.clj:216-219).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ... import history as h
from .. import models

MAX_REPORTED = 10  # knossos truncation (checker.clj:216-219)


@dataclass
class Entry:
    """One call or return event in the WGL doubly-linked entry list."""

    kind: str               # "call" | "return"
    op: dict                # the (completed) invocation op
    op_id: int              # dense id of the operation
    info: bool = False      # indeterminate op (return at infinity)
    match: "Entry | None" = None   # call <-> return link
    prev: "Entry | None" = field(default=None, repr=False)
    next: "Entry | None" = field(default=None, repr=False)


def reduce_history(raw_history: list[dict]) -> list[dict]:
    """The preprocessing every linearizability path shares: client ops
    only, completed (ok reads know their value), definite failures
    dropped."""
    return h.remove_failures(h.complete(h.client_ops(raw_history)))


def prepare(raw_history: list[dict]) -> list[tuple[dict, bool]]:
    """Reduce a raw history to the operations the search linearizes:
    (completed-invocation, indeterminate?) in invocation order."""
    out: list[tuple[dict, bool]] = []
    for inv, comp in h.pairs(reduce_history(raw_history)):
        if not h.is_invoke(inv):
            continue
        indeterminate = comp is None or h.is_info(comp)
        out.append((inv, indeterminate))
    return out


def _build_entries(hist: list[dict]) -> tuple["Entry", int, int]:
    """Build the entry list in real-time order from a reduced history:
    calls at invocation positions, returns at completion positions;
    indeterminate ops get no return entry (their return is at
    infinity). Returns (head, op-count, return-count)."""
    calls: dict[Any, Entry] = {}     # process -> open call entry
    head = Entry("head", {}, -1)
    tail = head
    op_id = 0

    def append(e: Entry) -> None:
        nonlocal tail
        e.prev, e.next = tail, None
        tail.next = e
        tail = e

    for o in hist:
        p = o.get("process")
        if h.is_invoke(o):
            e = Entry("call", o, op_id)
            op_id += 1
            calls[p] = e
            append(e)
        elif p in calls:
            call = calls.pop(p)
            if h.is_info(o):
                call.info = True       # return at infinity
            else:
                r = Entry("return", call.op, call.op_id, match=call)
                call.match = r
                append(r)
    # Any never-completed invocations are indeterminate too.
    for call in calls.values():
        call.info = True
    returns = 0
    e = head.next
    while e is not None:
        if e.kind == "return":
            returns += 1
        e = e.next
    return head, op_id, returns


@dataclass
class _Frame:
    entry: Entry
    state: Any


def _unlift(e: Entry) -> None:
    e.prev.next = e
    if e.next is not None:
        e.next.prev = e


def _lift(e: Entry) -> None:
    e.prev.next = e.next
    if e.next is not None:
        e.next.prev = e.prev


def wgl(model: models.Model, raw_history: list[dict],
        max_configs: int = 10_000_000,
        search_stats: dict | None = None) -> dict:
    """Wing-Gong-Lowe linearizability search with memoization.

    Walks the entry list looking for a call to linearize next; lifting a
    call applies it to the model and removes call+return; hitting a
    return whose call is unlinearized forces a backtrack. A cache of
    (linearized-bitmask, model-state) prunes re-exploration. Valid when
    no return entries remain (all determinate ops linearized);
    indeterminate ops may be left unlinearized. "unknown" when the
    config cache exceeds `max_configs` (mirrors knossos's memory
    pragmatism rather than running the JVM out of heap).

    CAS-register and fresh-mutex histories route to the C++ twin of
    this search (native/wgl.cc) when it's available — same walk, same
    cache discipline, same verdicts (differential parity pinned in
    tests/test_knossos.py); final-paths/configs witnesses are lean
    there. This Python engine is the oracle, the fallback, and the
    only engine for every other model.

    `search_stats` (a dict, filled in place — the kernel-stats
    telemetry seam) gains the engine's search counters: configs
    explored (the memo-cache size), max linearization depth, and — on
    the Python engine, whose walk exposes them — backtracks. The
    verdict dict itself never changes shape."""
    if type(model) is models.CASRegister and model.value is None:
        res = _wgl_native(raw_history, max_configs, "cas",
                          search_stats)
        if res is not None:
            return res
    elif type(model) is models.Mutex and model.locked is False:
        res = _wgl_native(raw_history, max_configs, "mutex",
                          search_stats)
        if res is not None:
            return res
    return _wgl_python(model, raw_history, max_configs, search_stats)


def _wgl_native(raw_history: list[dict], max_configs: int,
                model_kind: str = "cas",
                search_stats: dict | None = None) -> dict | None:
    """Run the native WGL (CAS register or mutex); None -> use the
    Python engine (lib missing, unencodable history, or un-internable
    values)."""
    from ... import native_lib
    L = native_lib.wgl_lib()
    if L is None:
        return None
    from . import encode as kenc
    try:
        if model_kind == "mutex":
            ev, model_id = kenc.encode_mutex_history(raw_history), 1
        else:
            # the device kernels cap pending slots at 24 (frontier
            # width); the C++ search has no such limit and high
            # concurrency is exactly where its speedup matters, so
            # give the CPU route a far larger budget
            ev = kenc.encode_register_history(
                raw_history, max_slots=4096).events
            model_id = 0
    except (kenc.EncodingError, TypeError):
        return None
    import ctypes

    import numpy as np
    ev = np.ascontiguousarray(ev, np.int32)
    out = (ctypes.c_int64 * 5)()
    L.jt_wgl_run(ev.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                 ev.shape[0], max_configs, model_id, out)
    verdict, n, depth, fail_op, _cache = out
    if search_stats is not None:
        # the C++ ABI exposes the cache size and depth, not the
        # backtrack count — no "backtracks" key rather than a fake 0
        search_stats.update(engine="wgl-native", configs=int(_cache),
                            max_depth=int(depth), op_count=int(n))
    if n == 0:
        return {"valid?": True, "op-count": 0, "analyzer": "wgl"}
    if verdict == 1:
        return {"valid?": True, "op-count": int(n), "analyzer": "wgl",
                "max-depth": int(depth), "final-paths": []}
    if verdict == 2:
        return {"valid?": "unknown", "op-count": int(n),
                "analyzer": "wgl", "cause": ":config-cache-exhausted",
                "configs": []}
    op: Any = int(fail_op)
    if 0 <= fail_op:        # recover the op dict for the witness
        pairs = prepare(raw_history)
        if fail_op < len(pairs):
            op = pairs[int(fail_op)][0]
    return {"valid?": False, "op-count": int(n), "analyzer": "wgl",
            "op": op, "max-depth": int(depth),
            "final-paths": [], "configs": []}


def _wgl_python(model: models.Model, raw_history: list[dict],
                max_configs: int = 10_000_000,
                search_stats: dict | None = None) -> dict:
    """The pure-Python WGL engine (any model; the parity oracle).
    `search_stats` gains the walk's own telemetry: configs (memo-cache
    size), backtracks (forced un-linearizations — exactly 0 on a
    history the greedy depth-first path linearizes outright), and the
    deepest linearization reached."""
    hist = reduce_history(raw_history)
    head, n, returns_left = _build_entries(hist)
    backtracks = 0

    def _note(configs: int, depth: int) -> None:
        if search_stats is not None:
            search_stats.update(engine="wgl", configs=configs,
                                backtracks=backtracks, max_depth=depth,
                                op_count=n)

    if n == 0:
        _note(0, 0)
        return {"valid?": True, "op-count": 0, "analyzer": "wgl"}

    state: Any = model
    linearized = 0
    cache: set[tuple[int, Any]] = {(0, state)}
    stack: list[_Frame] = []
    best_depth = 0

    entry = head.next
    while returns_left > 0:
        if entry is None:
            # Walked past every remaining entry without finding a return:
            # cannot happen while returns remain, but guard for safety.
            if not stack:
                break
            backtracks += 1
            frame = stack.pop()
            e2 = frame.entry
            _unlift(e2)
            if e2.match is not None:
                _unlift(e2.match)
                returns_left += 1
            linearized &= ~(1 << e2.op_id)
            state = frame.state
            entry = e2.next
            continue
        if entry.kind == "call":
            s2 = state.step(entry.op)
            key = (linearized | (1 << entry.op_id), s2)
            if not models.is_inconsistent(s2) and key not in cache:
                if len(cache) >= max_configs:
                    _note(len(cache), best_depth)
                    return {"valid?": "unknown", "op-count": n,
                            "analyzer": "wgl",
                            "cause": ":config-cache-exhausted",
                            "configs": [_config_map(state, linearized)]}
                cache.add(key)
                stack.append(_Frame(entry, state))
                _lift(entry)
                if entry.match is not None:
                    _lift(entry.match)
                    returns_left -= 1
                state = s2
                linearized |= 1 << entry.op_id
                if bin(linearized).count("1") > best_depth:
                    best_depth = bin(linearized).count("1")
                entry = head.next
            else:
                entry = entry.next
        else:
            # A completed op we failed to linearize before its return.
            if not stack:
                _note(len(cache), best_depth)
                return {"valid?": False, "op-count": n, "analyzer": "wgl",
                        "op": entry.op,
                        "max-depth": best_depth,
                        "final-paths": _final_paths(stack),
                        "configs": [_config_map(state, linearized)]}
            backtracks += 1
            frame = stack.pop()
            e2 = frame.entry
            _unlift(e2)
            if e2.match is not None:
                _unlift(e2.match)
                returns_left += 1
            linearized &= ~(1 << e2.op_id)
            state = frame.state
            entry = e2.next

    _note(len(cache), best_depth)
    return {"valid?": True, "op-count": n, "analyzer": "wgl",
            "max-depth": best_depth,
            "final-paths": _final_paths(stack)}


def _config_map(state: Any, linearized: int) -> dict:
    return {"model": repr(state),
            "linearized-count": bin(linearized).count("1")}


def _final_paths(stack: list[_Frame]) -> list[dict]:
    path = [{"op": f.entry.op, "model": repr(f.state)} for f in stack]
    return path[-MAX_REPORTED:]


def analysis(model: models.Model, raw_history: list[dict],
             algorithm: str = "wgl",
             search_stats: dict | None = None, **kw: Any) -> dict:
    """Entry point matching knossos.{wgl,linear,competition}/analysis.

    On CPU every algorithm name routes to the WGL engine (knossos's
    `competition` races wgl and linear and returns whichever finishes —
    verdicts are identical by construction; this build keeps one CPU
    engine and puts the `linear`-style config search on TPU instead,
    see `.kernels`)."""
    if algorithm not in ("wgl", "linear", "competition"):
        raise ValueError(f"unknown algorithm {algorithm!r}")
    return wgl(model, raw_history, search_stats=search_stats, **kw)
