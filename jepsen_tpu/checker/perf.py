"""Performance analysis: latency and throughput plots.

Counterpart of the reference's jepsen.checker.perf
(jepsen/src/jepsen/checker/perf.clj). Where the reference shells out to
gnuplot, this renders directly with matplotlib's Agg backend — no external
binary, and the same artifacts: ``latency-raw.png`` (point_graph,
perf.clj:485), ``latency-quantiles.png`` (quantiles_graph, perf.clj:514),
``rate.png`` (rate_graph, perf.clj:560), with nemesis activity shaded
behind the series (nemesis-regions perf.clj:242, nemesis-lines
perf.clj:272).

The pure data layer (buckets/quantiles, perf.clj:33-86) is exposed
separately so it can be golden-tested without touching a renderer.
"""

from __future__ import annotations

import math
from typing import Any, Iterable, Sequence

from .. import util
from . import Checker

# Reference palette (perf.clj:59-63) and nemesis shading defaults
# (perf.clj:18-19).
TYPE_COLORS = {"ok": "#81BFFC", "info": "#FFA400", "fail": "#FF1E90"}
DEFAULT_NEMESIS_COLOR = "#cccccc"
NEMESIS_ALPHA = 0.6
TYPES = ("ok", "info", "fail")


# ---------------------------------------------------------------------------
# Pure data layer
# ---------------------------------------------------------------------------

def bucket_scale(dt: float, b: float) -> float:
    """Time at the midpoint of bucket number b (perf.clj:21-25)."""
    return int(b) * dt + dt / 2


def bucket_time(dt: float, t: float) -> float:
    """Midpoint of the bucket t falls into (perf.clj:27-31)."""
    return bucket_scale(dt, t / dt)


def buckets(dt: float, tmax: float) -> list[float]:
    """Midpoints of every bucket up to tmax (perf.clj:33-40)."""
    out, b = [], 0
    while True:
        t = bucket_scale(dt, b)
        if t > tmax:
            return out
        out.append(t)
        b += 1


def bucket_points(dt: float, points: Iterable[Sequence[float]]) -> dict:
    """Group [time, v] points into a sorted {bucket-midpoint: [points]}
    map (perf.clj:42-49)."""
    out: dict[float, list] = {}
    for p in points:
        out.setdefault(bucket_time(dt, p[0]), []).append(p)
    return dict(sorted(out.items()))


def quantiles(qs: Sequence[float], points: Sequence[float]) -> dict:
    """Map each quantile in qs to its value over points, using the
    reference's floor(n*q) index rule (perf.clj:51-61)."""
    s = sorted(points)
    if not s:
        return {}
    n = len(s)
    return {q: s[min(n - 1, int(math.floor(n * q)))] for q in qs}


def latencies_to_quantiles(dt: float, qs: Sequence[float],
                           points: Iterable[Sequence[float]]) -> dict:
    """Bucket [time, latency] points by dt and emit
    {q: [(bucket-time, latency-at-q), ...]} (perf.clj:63-86)."""
    for q in qs:
        assert 0 <= q <= 1, q
    bucketed = [(t, quantiles(qs, [p[1] for p in ps]))
                for t, ps in bucket_points(dt, points).items()]
    return {q: [(t, qv[q]) for t, qv in bucketed] for q in qs}


def nanos_to_secs(t: float | None) -> float:
    return (t or 0) / 1e9


def nanos_to_ms(t: float | None) -> float:
    return (t or 0) / 1e6


def latency_point(op: dict) -> tuple[float, float]:
    """[time-in-seconds, latency-in-ms] for an op (perf.clj:143-148)."""
    return (nanos_to_secs(op.get("time")), nanos_to_ms(op.get("latency")))


def invokes_by_f(history: Sequence[dict]) -> dict:
    out: dict[Any, list] = {}
    for op in history:
        if op.get("type") == "invoke":
            out.setdefault(op.get("f"), []).append(op)
    return out


def invokes_by_f_type(history: Sequence[dict]) -> dict:
    """{f: {type: [invocations whose completion has that type]}}
    (perf.clj:98-118). The history must be latency-annotated."""
    out: dict[Any, dict] = {}
    for f, ops in invokes_by_f(history).items():
        by_type: dict[str, list] = {}
        for op in ops:
            ctype = (op.get("completion") or {}).get("type")
            if ctype in TYPES:
                by_type.setdefault(ctype, []).append(op)
        out[f] = by_type
    return out


def fs_order(fs: Iterable) -> list:
    """Deterministic plotting order for :f values (util/polysort)."""
    return sorted(fs, key=lambda f: (f is None, str(f)))


# ---------------------------------------------------------------------------
# Nemesis activity
# ---------------------------------------------------------------------------

def nemesis_activity(nemeses: Sequence[dict] | None,
                     history: Sequence[dict]) -> list[dict]:
    """Resolve nemesis spec maps ({"name","color","start","stop","fs"})
    against the history: attach their ops and paired activity intervals
    (perf.clj:204-242)."""
    nemeses = list(nemeses or [])
    nem_ops = [o for o in history if o.get("process") == "nemesis"]
    out = []
    claimed: set[int] = set()
    for n in nemeses:
        fs = set(n.get("fs") or ()) | set(n.get("start") or ()) \
            | set(n.get("stop") or ())
        ops = [o for o in nem_ops if not fs or o.get("f") in fs]
        claimed.update(id(o) for o in ops)
        intervals = util.nemesis_intervals(
            ops, {"start": n.get("start"), "stop": n.get("stop")})
        out.append({**n, "ops": ops, "intervals": intervals})
    # Unmatched nemesis ops render under a default band so fault activity
    # never silently disappears from a plot (nemesis-ops, perf.clj:204-216).
    rest = [o for o in nem_ops if id(o) not in claimed]
    if rest or not nemeses:
        out.append({"name": "nemesis", "ops": rest,
                    "intervals": util.nemesis_intervals(rest)})
    return out


def draw_nemeses(ax, history, nemeses, t_max: float) -> None:
    """Shade activity intervals and draw event lines, one horizontal band
    per nemesis from the top of the axes (perf.clj:242-296)."""
    acts = nemesis_activity(nemeses, history)
    height, padding = 0.0834, 0.00615
    for i, n in enumerate(acts):
        color = n.get("fill-color") or n.get("color") or DEFAULT_NEMESIS_COLOR
        bot = 1 - height * (i + 1)
        for a, b in n["intervals"]:
            t0 = nanos_to_secs(a.get("time"))
            t1 = nanos_to_secs(b.get("time")) if b else t_max
            ax.axvspan(t0, t1, ymin=bot + padding,
                       ymax=bot + height - padding, color=color,
                       alpha=n.get("transparency", NEMESIS_ALPHA), lw=0,
                       label=None)
        line_color = n.get("line-color") or n.get("color") \
            or DEFAULT_NEMESIS_COLOR
        for o in n["ops"]:
            ax.axvline(nanos_to_secs(o.get("time")), color=line_color,
                       lw=0.8, alpha=0.8)
        if n["ops"]:
            ax.plot([], [], color=color, lw=4, label=str(n.get("name")))


# ---------------------------------------------------------------------------
# Renderers
# ---------------------------------------------------------------------------

def fig_ax(title: str, ylabel: str, logy: bool):
    # The OO API (Figure + Agg canvas), NOT pyplot: checkers render
    # concurrently (Compose.real_pmap, independent's bounded_pmap) and
    # pyplot's global figure registry is not thread-safe.
    from matplotlib.backends.backend_agg import FigureCanvasAgg
    from matplotlib.figure import Figure
    fig = Figure(figsize=(9, 4), dpi=100)
    FigureCanvasAgg(fig)
    ax = fig.add_subplot()
    ax.set_title(title)
    ax.set_xlabel("Time (s)")
    ax.set_ylabel(ylabel)
    if logy:
        ax.set_yscale("log")
    return fig, ax


def finish(fig, ax, path) -> None:
    handles, labels = ax.get_legend_handles_labels()
    if handles:
        ax.legend(loc="upper left", bbox_to_anchor=(1.01, 1.0),
                  fontsize="small")
    fig.savefig(path, bbox_inches="tight")


def t_max(history) -> float:
    return max((nanos_to_secs(o.get("time")) for o in history), default=1.0)


def point_graph(test: dict, history: Sequence[dict], path,
                nemeses=None) -> bool:
    """latency-raw.png: every completed invocation as a point, colored by
    completion type (perf.clj:485-512). Returns False when there are no
    points (the reference throws ::no-points, checker returns anyway)."""
    lh = util.history_latencies(history)
    datasets = invokes_by_f_type(lh)
    markers = "osv^D*Pp"
    fig, ax = fig_ax(f"{test.get('name', '')} latency", "Latency (ms)", True)
    any_points = False
    for i, f in enumerate(fs_order(datasets)):
        for t in TYPES:
            ops = datasets[f].get(t)
            if not ops:
                continue
            pts = [latency_point(o) for o in ops]
            ax.scatter([p[0] for p in pts], [p[1] for p in pts], s=8,
                       color=TYPE_COLORS[t], marker=markers[i % len(markers)],
                       label=f"{util.name_of(f)} {t}")
            any_points = True
    draw_nemeses(ax, history, nemeses, t_max(history))
    finish(fig, ax, path)
    return any_points


def quantiles_graph(test: dict, history: Sequence[dict], path,
                    nemeses=None, dt: float = 30,
                    qs: Sequence[float] = (0.5, 0.95, 0.99, 1)) -> bool:
    """latency-quantiles.png: per-f latency quantiles over dt-second
    windows (perf.clj:514-556)."""
    lh = util.history_latencies(history)
    by_f = {f: latencies_to_quantiles(
        dt, qs, [latency_point(o) for o in ops if "latency" in o])
        for f, ops in invokes_by_f(lh).items()}
    palette = ["#FF1E90", "#FFA400", "#81BFFC", "#53DF83", "#909090"]
    q_colors = {q: palette[i % len(palette)]
                for i, q in enumerate(sorted(qs, reverse=True))}
    fig, ax = fig_ax(f"{test.get('name', '')} latency", "Latency (ms)", True)
    any_points = False
    markers = "osv^D*Pp"
    for i, f in enumerate(fs_order(by_f)):
        for q in qs:
            pts = by_f[f].get(q) or []
            if not pts:
                continue
            ax.plot([p[0] for p in pts], [p[1] for p in pts],
                    marker=markers[i % len(markers)], ms=4,
                    color=q_colors[q], label=f"{util.name_of(f)} {q}")
            any_points = True
    draw_nemeses(ax, history, nemeses, t_max(history))
    finish(fig, ax, path)
    return any_points


def rates(history: Sequence[dict], dt: float = 10) -> dict:
    """{f: {type: {bucket-time: hz}}} over client completions
    (rate-graph! accumulation, perf.clj:560-586)."""
    out: dict[Any, dict] = {}
    for op in history:
        if op.get("type") == "invoke" or not isinstance(
                op.get("process"), int):
            continue
        if op.get("type") not in TYPES:
            continue
        b = bucket_time(dt, nanos_to_secs(op.get("time")))
        slot = out.setdefault(op.get("f"), {}).setdefault(op["type"], {})
        slot[b] = slot.get(b, 0.0) + 1.0 / dt
    return out


def rate_graph(test: dict, history: Sequence[dict], path,
               nemeses=None, dt: float = 10) -> bool:
    """rate.png: completion throughput (hz) by f and type
    (perf.clj:560-600)."""
    datasets = rates(history, dt)
    tmax = t_max(history)
    fig, ax = fig_ax(f"{test.get('name', '')} rate", "Throughput (hz)", False)
    markers = "osv^D*Pp"
    any_points = False
    for i, f in enumerate(fs_order(datasets)):
        for t in TYPES:
            m = datasets[f].get(t)
            if not m:
                continue
            xs = buckets(dt, tmax)
            ax.plot(xs, [m.get(x, 0.0) for x in xs],
                    marker=markers[i % len(markers)], ms=4,
                    color=TYPE_COLORS[t], label=f"{util.name_of(f)} {t}")
            any_points = True
    draw_nemeses(ax, history, nemeses, tmax)
    finish(fig, ax, path)
    return any_points


# ---------------------------------------------------------------------------
# Checkers
# ---------------------------------------------------------------------------

def store_path(test: dict, opts: dict, filename: str):
    store = test.get("store")
    if store is None:
        return None
    sub = (opts or {}).get("subdirectory")
    parts = [sub] if isinstance(sub, str) else list(sub or [])
    return store.path(test, *[str(p) for p in parts], filename)


class LatencyGraph(Checker):
    """Renders latency-raw.png and latency-quantiles.png
    (checker.clj:797-808)."""

    def __init__(self, nemeses=None):
        self.nemeses = nemeses

    def check(self, test, history, opts):
        nemeses = self.nemeses or (test.get("plot") or {}).get("nemeses")
        p1 = store_path(test, opts, "latency-raw.png")
        p2 = store_path(test, opts, "latency-quantiles.png")
        if p1 is not None:
            point_graph(test, history, p1, nemeses)
            quantiles_graph(test, history, p2, nemeses)
        return {"valid?": True}


class RateGraph(Checker):
    """Renders rate.png (checker.clj:810-820)."""

    def __init__(self, nemeses=None):
        self.nemeses = nemeses

    def check(self, test, history, opts):
        nemeses = self.nemeses or (test.get("plot") or {}).get("nemeses")
        p = store_path(test, opts, "rate.png")
        if p is not None:
            rate_graph(test, history, p, nemeses)
        return {"valid?": True}


def latency_graph(nemeses=None) -> Checker:
    return LatencyGraph(nemeses)


def rate_graph_checker(nemeses=None) -> Checker:
    return RateGraph(nemeses)


def perf(opts: dict | None = None) -> Checker:
    """Composite latency + rate checker (checker.clj:822-829)."""
    from . import compose
    nemeses = (opts or {}).get("nemeses")
    return compose({"latency-graph": latency_graph(nemeses),
                    "rate-graph": rate_graph_checker(nemeses)})
