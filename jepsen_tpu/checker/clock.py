"""Clock-skew analysis and plot.

Counterpart of jepsen.checker.clock (jepsen/src/jepsen/checker/clock.clj):
any op carrying a ``clock-offsets`` map (node -> offset seconds, annotated
by the clock nemesis) contributes points; per-node step series are rendered
to ``clock-skew.png``.
"""

from __future__ import annotations

from typing import Sequence

from . import Checker
from .perf import draw_nemeses, fig_ax, finish, store_path, nanos_to_secs


def history_to_datasets(history: Sequence[dict]) -> dict:
    """{node: [(t-seconds, offset), ...]} from clock-offsets annotations,
    each series extended to the final history time (clock.clj:13-34)."""
    if not history:
        return {}
    final_t = max((nanos_to_secs(o.get("time")) for o in history),
                  default=0.0)
    series: dict = {}
    for op in history:
        offsets = op.get("clock-offsets")
        if not offsets:
            continue
        t = nanos_to_secs(op.get("time"))
        for node, offset in offsets.items():
            series.setdefault(node, []).append((t, offset))
    return {node: pts + [(final_t, pts[-1][1])]
            for node, pts in series.items()}


def short_node_names(nodes: Sequence[str]) -> list[str]:
    """Strip the longest common dotted suffix: n1.foo.com, n2.foo.com ->
    n1, n2 (clock.clj:36-45)."""
    if len(nodes) < 2:
        return list(nodes)
    split = [str(n).split(".") for n in nodes]
    k = 0
    min_len = min(len(s) for s in split)
    while k < min_len - 1 and len({tuple(s[len(s) - 1 - k:]) for s in split}) == 1:
        k += 1
    return [".".join(s[: len(s) - k]) for s in split]


def plot(test: dict, history: Sequence[dict], path,
         nemeses=None) -> bool:
    """Render clock-skew.png with nemesis activity overlaid; returns
    False when no op has offsets (clock.clj:47-75)."""
    datasets = history_to_datasets(history)
    if not datasets:
        return False
    nodes = sorted(datasets, key=str)
    names = short_node_names(nodes)
    fig, ax = fig_ax(f"{test.get('name', '')} clock skew", "Skew (s)", False)
    for node, name in zip(nodes, names):
        pts = datasets[node]
        ax.step([p[0] for p in pts], [p[1] for p in pts], where="post",
                label=name)
    final_t = max((nanos_to_secs(o.get("time")) for o in history),
                  default=1.0)
    draw_nemeses(ax, history, nemeses, final_t)
    finish(fig, ax, path)
    return True


class ClockPlot(Checker):
    """Checker wrapper (checker.clj:831-837)."""

    def check(self, test, history, opts):
        p = store_path(test, opts or {}, "clock-skew.png")
        if p is not None and history:
            plot(test, history, p,
                 (test.get("plot") or {}).get("nemeses"))
        return {"valid?": True}


def clock_plot() -> Checker:
    return ClockPlot()
