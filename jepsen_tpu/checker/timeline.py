"""HTML timeline of operations, one swimlane per process.

Counterpart of jepsen.checker.timeline
(jepsen/src/jepsen/checker/timeline.clj): pairs up invocations with their
completions (pairs, timeline.clj:33), renders each as an absolutely
positioned div colored by completion type (pair->div timeline.clj:97,
stylesheet timeline.clj:14-31), and writes ``timeline.html`` into the
store. Hovering shows duration, error, value, and the full op; anchors
``#i<index>`` allow deep-linking an op from a verdict.
"""

from __future__ import annotations

import html as _html
import json
from typing import Sequence

from .. import history as h
from . import Checker
from .perf import store_path, nanos_to_ms

COL_WIDTH = 100    # px (timeline.clj:12: col-width 100)
GUTTER = 106       # px between process columns (col-width + 6)
ROW_HEIGHT = 16    # px per op row

STYLESHEET = """
body { font-family: sans-serif; font-size: 12px; }
.ops { position: absolute; }
.op { position: absolute; padding: 2px; border-radius: 2px;
      box-shadow: 0 1px 3px rgba(0,0,0,0.12), 0 1px 2px rgba(0,0,0,0.24);
      overflow: hidden; }
.op.invoke { background: #eeeeee; }
.op.ok     { background: #6DB6FE; }
.op.info   { background: #FFAA26; }
.op.fail   { background: #FEB5DA; }
.op:target { box-shadow: 0 14px 28px rgba(0,0,0,0.25),
             0 10px 10px rgba(0,0,0,0.22); }
.process-label { position: absolute; top: 0; font-weight: bold; }
"""


def _render_value(v) -> str:
    try:
        return json.dumps(v, default=repr)
    except Exception:
        return repr(v)


def _title(start: dict, stop: dict | None) -> str:
    """Tooltip: duration, error, op dump (title, timeline.clj:76-85)."""
    parts = []
    if stop is not None and stop.get("time") is not None \
            and start.get("time") is not None:
        parts.append(f"Dur: {int(nanos_to_ms(stop['time'] - start['time']))} ms")
    op = stop or start
    if op.get("error") is not None:
        parts.append(f"Err: {_render_value(op.get('error'))}")
    parts.append(f"Op: {_render_value(op)}")
    return "\n".join(parts)


def _body(start: dict, stop: dict | None) -> str:
    """Visible text: process, f, value(s) (body, timeline.clj:87-95)."""
    op = stop or start
    txt = f"{start.get('process')} {op.get('f')}"
    if start.get("process") != "nemesis":
        txt += f" {_render_value(start.get('value'))}"
        if stop is not None and stop.get("value") != start.get("value"):
            txt += f" → {_render_value(stop.get('value'))}"
    return txt


def render_html(test: dict, history: Sequence[dict]) -> str:
    """Full timeline.html document (html, timeline.clj:159-179)."""
    history = h.index(list(history))
    procs = sorted({o.get("process") for o in history},
                   key=lambda p: (not isinstance(p, int),
                                  p if isinstance(p, int) else str(p)))
    col = {p: i for i, p in enumerate(procs)}
    divs = []
    for row, (start, stop) in enumerate(h.pairs(history)):
        op = stop or start
        typ = op.get("type", "info")
        left = col[start.get("process")] * GUTTER
        top = ROW_HEIGHT * (row + 1) + 4
        idx = op.get("index", row)
        divs.append(
            f'<a href="#i{idx}"><div class="op {_html.escape(str(typ))}"'
            f' id="i{idx}"'
            f' style="left:{left}px;top:{top}px;width:{COL_WIDTH}px;"'
            f' title="{_html.escape(_title(start, stop))}">'
            f'{_html.escape(_body(start, stop))}</div></a>')
    labels = "".join(
        f'<div class="process-label" style="left:{col[p] * GUTTER}px;">'
        f'{_html.escape(str(p))}</div>' for p in procs)
    name = _html.escape(str(test.get("name", "")))
    return (
        "<!DOCTYPE html><html><head><meta charset='utf-8'>"
        f"<title>{name} timeline</title>"
        f"<style>{STYLESHEET}</style></head>"
        f"<body><h1>{name}</h1><div class='ops'>{labels}"
        + "".join(divs) + "</div></body></html>")


class Timeline(Checker):
    """Writes timeline.html into the store (html, timeline.clj:159)."""

    def check(self, test, history, opts):
        p = store_path(test, opts or {}, "timeline.html")
        if p is not None:
            p.write_text(render_html(test, history))
        return {"valid?": True}


def html() -> Checker:
    return Timeline()
