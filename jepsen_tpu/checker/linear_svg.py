"""Render a linearizability failure to ``linear.svg``.

Counterpart of knossos.linear.report (used by checker/linearizable at
jepsen/src/jepsen/checker.clj:209-213, which renders linear.svg for
invalid analyses): per-process swimlanes of the operations concurrent
with the failing op, the failing op highlighted, and the deepest
linearization path found (``final-paths``) listed with its model states.
Like the reference renderer, output is bounded — it "can't handle really
broad concurrencies" so lanes are capped.
"""

from __future__ import annotations

import html as _html
from typing import Sequence

from .. import history as h

LANE_H = 26
BAR_H = 18
PX_PER_OP = 28
LABEL_W = 70
MAX_LANES = 32

OK_COLOR = "#6DB6FE"
FAIL_COLOR = "#FEB5DA"
INFO_COLOR = "#FFAA26"
BAD_COLOR = "#FF1E90"


def _same_op(a: dict | None, b: dict | None) -> bool:
    """Identify the analysis' failing op within the raw history. The
    analyzer's 'index' comes from its own reduced history, so match on
    the stable identity fields instead."""
    if a is None or b is None:
        return False
    return (a.get("process") == b.get("process")
            and a.get("time") == b.get("time")
            and a.get("f") == b.get("f"))


def _window(history: Sequence[dict], bad_op: dict | None,
            radius: int = 40) -> tuple[list[tuple[dict, dict | None]], int]:
    """(invoke/complete pairs within `radius` positions of the failing
    op, position of the failing pair or -1)."""
    pairs = list(h.pairs(h.index(list(history))))
    bad_pos = next((i for i, (inv, comp) in enumerate(pairs)
                    if _same_op(inv, bad_op) or _same_op(comp, bad_op)),
                   -1)
    if bad_pos < 0:
        return pairs[:radius], -1
    lo = max(0, bad_pos - radius // 2)
    window = pairs[lo: bad_pos + radius // 2]
    return window, bad_pos - lo


def render_svg(analysis: dict, history: Sequence[dict]) -> str:
    """SVG document for a (usually invalid) wgl/linear analysis."""
    bad = analysis.get("op")
    pairs, bad_pos = _window(history, bad)
    procs = []
    for inv, _ in pairs:
        if inv.get("process") not in procs:
            procs.append(inv.get("process"))
    if len(procs) > MAX_LANES:
        keep = procs[:MAX_LANES]
        if 0 <= bad_pos < len(pairs):
            # The failing op's lane must survive truncation — it carries
            # the BAD_COLOR highlight the whole render exists for.
            bad_proc = pairs[bad_pos][0].get("process")
            if bad_proc in procs and bad_proc not in keep:
                keep[-1] = bad_proc
        procs = keep
    lane = {p: i for i, p in enumerate(procs)}
    idxs = [i.get("index", 0) for i, _ in pairs] + \
        [(c or i).get("index", 0) for i, c in pairs]
    lo, hi = (min(idxs), max(idxs)) if idxs else (0, 1)
    span = max(hi - lo, 1)
    width = LABEL_W + 24 * min(span, 400) + 120
    px = (width - LABEL_W - 100) / span

    elems = []
    for pos, (inv, comp) in enumerate(pairs):
        p = inv.get("process")
        if p not in lane:
            continue
        y = 30 + lane[p] * LANE_H
        x0 = LABEL_W + (inv.get("index", lo) - lo) * px
        x1 = LABEL_W + ((comp or inv).get("index", lo) - lo + 1) * px
        op = comp or inv
        color = {"ok": OK_COLOR, "fail": FAIL_COLOR}.get(
            op.get("type"), INFO_COLOR)
        if pos == bad_pos:
            color = BAD_COLOR
        label = f"{op.get('f')} {op.get('value')}"
        tooltip = _html.escape(repr(op))
        elems.append(
            f'<rect x="{x0:.1f}" y="{y}" width="{max(x1 - x0, 4):.1f}" '
            f'height="{BAR_H}" rx="3" fill="{color}">'
            f'<title>{tooltip}</title></rect>'
            f'<text x="{x0 + 3:.1f}" y="{y + 13}" font-size="10">'
            f'{_html.escape(str(label))[:24]}</text>')
    for p, i in lane.items():
        elems.append(
            f'<text x="4" y="{30 + i * LANE_H + 13}" font-size="11" '
            f'font-weight="bold">{_html.escape(str(p))}</text>')

    y = 30 + len(procs) * LANE_H + 24
    path = analysis.get("final-paths") or []
    elems.append(f'<text x="4" y="{y}" font-size="12" font-weight="bold">'
                 f'deepest linearization '
                 f'(depth {analysis.get("max-depth")}):</text>')
    for step in path[-12:]:
        y += 16
        op = step.get("op", {})
        elems.append(
            f'<text x="12" y="{y}" font-size="11">'
            f'{_html.escape(str(op.get("f")))} '
            f'{_html.escape(str(op.get("value")))} → model '
            f'{_html.escape(str(step.get("model")))}</text>')
    if bad is not None:
        y += 20
        elems.append(
            f'<text x="4" y="{y}" font-size="12" fill="{BAD_COLOR}" '
            f'font-weight="bold">cannot linearize: '
            f'{_html.escape(str(bad.get("f")))} '
            f'{_html.escape(str(bad.get("value")))}</text>')
    height = y + 24
    return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{width:.0f}" '
            f'height="{height}" font-family="sans-serif">'
            f'<text x="4" y="16" font-size="13" font-weight="bold">'
            f'linearizability analysis</text>' + "".join(elems) + "</svg>")


def render_analysis(test: dict, analysis: dict,
                    history: Sequence[dict], opts: dict | None = None):
    """Write linear.svg into the store; returns the path or None."""
    from .perf import store_path
    p = store_path(test, opts or {}, "linear.svg")
    if p is None:
        return None
    p.write_text(render_svg(analysis, history))
    return p
