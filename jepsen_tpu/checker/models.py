"""Data-type models: pure state machines consistency checks step through.

Counterpart of knossos.model (used by the reference's queue and
linearizable checkers; jepsen/src/jepsen/checker.clj:188-240). A model's
`step(op)` returns the next model state, or an `Inconsistent` describing why
the transition is illegal. Models must be hashable and comparable so the
linearizability search can deduplicate configurations.
"""

from __future__ import annotations

from typing import Any


class Inconsistent:
    __slots__ = ("msg",)

    def __init__(self, msg: str):
        self.msg = msg

    def __repr__(self) -> str:
        return f"Inconsistent({self.msg!r})"


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(m: Any) -> bool:
    return isinstance(m, Inconsistent)


class Model:
    """Base model. step returns the successor state or Inconsistent."""

    def step(self, op: dict) -> "Model | Inconsistent":
        raise NotImplementedError


class Register(Model):
    """A read/write register."""

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def step(self, op: dict) -> Model | Inconsistent:
        f, v = op.get("f"), op.get("value")
        if f == "write":
            return Register(v)
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"read {v!r}, but expected {self.value!r}")
        return inconsistent(f"unknown op f={f!r}")

    def __eq__(self, o):
        return isinstance(o, Register) and o.value == self.value

    def __hash__(self):
        return hash(("Register", self.value))

    def __repr__(self):
        return f"Register({self.value!r})"


class CASRegister(Model):
    """A register supporting read / write / cas [old new].

    The canonical model for etcd-style linearizable registers
    (knossos.model/cas-register; reference etcd suite client ops)."""

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def step(self, op: dict) -> Model | Inconsistent:
        f, v = op.get("f"), op.get("value")
        if f == "write":
            return CASRegister(v)
        if f == "cas":
            if v is None:
                return inconsistent("cas with nil value")
            old, new = v
            if old == self.value:
                return CASRegister(new)
            return inconsistent(f"can't CAS {self.value!r} from {old!r} to {new!r}")
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"can't read {v!r} from register {self.value!r}")
        return inconsistent(f"unknown op f={f!r}")

    def __eq__(self, o):
        return isinstance(o, CASRegister) and o.value == self.value

    def __hash__(self):
        return hash(("CASRegister", self.value))

    def __repr__(self):
        return f"CASRegister({self.value!r})"


class Mutex(Model):
    """A lock: acquire / release."""

    __slots__ = ("locked",)

    def __init__(self, locked: bool = False):
        self.locked = locked

    def step(self, op: dict) -> Model | Inconsistent:
        f = op.get("f")
        if f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire a held lock")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return inconsistent("cannot release a free lock")
            return Mutex(False)
        return inconsistent(f"unknown op f={f!r}")

    def __eq__(self, o):
        return isinstance(o, Mutex) and o.locked == self.locked

    def __hash__(self):
        return hash(("Mutex", self.locked))

    def __repr__(self):
        return f"Mutex({'locked' if self.locked else 'free'})"


class UnorderedQueue(Model):
    """A queue where dequeues may come back in any order — used by the queue
    checker, which doesn't explore orderings (checker.clj:221-240)."""

    __slots__ = ("pending",)

    def __init__(self, pending: frozenset | None = None):
        # pending is a multiset encoded as frozenset of (value, copy#).
        self.pending = pending if pending is not None else frozenset()

    def _counts(self) -> dict:
        out: dict = {}
        for v, _ in self.pending:
            out[v] = out.get(v, 0) + 1
        return out

    def step(self, op: dict) -> Model | Inconsistent:
        f, v = op.get("f"), op.get("value")
        if f == "enqueue":
            n = self._counts().get(v, 0)
            return UnorderedQueue(self.pending | {(v, n)})
        if f == "dequeue":
            n = self._counts().get(v, 0)
            if n == 0:
                return inconsistent(f"can't dequeue {v!r} which was never enqueued")
            return UnorderedQueue(self.pending - {(v, n - 1)})
        return inconsistent(f"unknown op f={f!r}")

    def __eq__(self, o):
        return isinstance(o, UnorderedQueue) and o.pending == self.pending

    def __hash__(self):
        return hash(("UnorderedQueue", self.pending))

    def __repr__(self):
        return f"UnorderedQueue({sorted(self.pending)})"


class FIFOQueue(Model):
    """A single-consumer FIFO queue."""

    __slots__ = ("items",)

    def __init__(self, items: tuple = ()):
        self.items = items

    def step(self, op: dict) -> Model | Inconsistent:
        f, v = op.get("f"), op.get("value")
        if f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if f == "dequeue":
            if not self.items:
                return inconsistent(f"can't dequeue {v!r} from empty queue")
            if self.items[0] != v:
                return inconsistent(
                    f"expected to dequeue {self.items[0]!r}, got {v!r}")
            return FIFOQueue(self.items[1:])
        return inconsistent(f"unknown op f={f!r}")

    def __eq__(self, o):
        return isinstance(o, FIFOQueue) and o.items == self.items

    def __hash__(self):
        return hash(("FIFOQueue", self.items))

    def __repr__(self):
        return f"FIFOQueue({list(self.items)})"


def cas_register(value: Any = None) -> CASRegister:
    return CASRegister(value)


def register(value: Any = None) -> Register:
    return Register(value)


def mutex() -> Mutex:
    return Mutex()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()
