"""Hazelcast Open Client Protocol (1.x) wire driver.

The reference suite drives Hazelcast through the JVM client
(hazelcast/src/jepsen/hazelcast.clj:119-144 `connect`,
lock-client 412, queue-client 270, atomic-long-id-client 146,
map-client 453); this is a from-scratch Python implementation of the
same binary protocol the 3.x client speaks, covering exactly the
surface those workloads need: authentication, IMap get/put/replace/
putIfAbsent, IQueue offer/poll/take/size, ILock lock/tryLock/unlock,
and IAtomicLong incrementAndGet/get/addAndGet.

Wire format (Open Client Protocol 1.x):

  connect, send b"CB2", then length-prefixed client messages:
    frame  = len:int32 LE | version:u8 | flags:u8 (0xC0 begin+end)
           | type:u16 LE | correlation:int64 LE | partition:int32 LE
           | dataOffset:u16 LE (18) | payload
  strings in the payload are int32-length + utf8; values cross as
  hazelcast `Data` blobs: partition-hash:int32 BE | type-id:int32 BE
  | big-endian body (type ids: -8 long, -11 string, -17 long[]).

Constants follow the published protocol spec; in this zero-egress
build they are exercised round-trip against the in-tree fake server
(tests/fake_hazelcast.py), with live-cluster verification in the
opt-in integration tier like every other wire driver here.
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading

from . import DBError, DriverError

PROTOCOL_INIT = b"CB2"
VERSION = 1
FLAG_BEGIN_END = 0xC0
HEADER = struct.Struct("<iBBHqiH")  # len, ver, flags, type, corr, part, off
HEADER_SIZE = HEADER.size  # 22

# -- request message types (protocol 1.x codecs) ---------------------------
AUTH = 0x0002
MAP_PUT = 0x0101
MAP_GET = 0x0102
MAP_REPLACE_IF_SAME = 0x0105
MAP_PUT_IF_ABSENT = 0x010D
QUEUE_OFFER = 0x0301
QUEUE_SIZE = 0x0303
QUEUE_POLL = 0x0305
QUEUE_TAKE = 0x0306
LOCK_LOCK = 0x0705
LOCK_UNLOCK = 0x0706
LOCK_TRY_LOCK = 0x0708
ATOMIC_LONG_ADD_AND_GET = 0x0A05
ATOMIC_LONG_GET = 0x0A08
ATOMIC_LONG_INCREMENT_AND_GET = 0x0A0B

# -- response message types ------------------------------------------------
RESP_VOID = 0x0064
RESP_BOOL = 0x0065
RESP_INT = 0x0066
RESP_LONG = 0x0067
RESP_STRING = 0x0068
RESP_DATA = 0x0069
RESP_AUTH = 0x006B
RESP_ERROR = 0x006D

# -- hazelcast serialization type ids (Data body is big-endian) ------------
TYPE_NULL = 0
TYPE_LONG = -8
TYPE_STRING = -11
TYPE_LONG_ARRAY = -17


class HazelcastError(DBError):
    """Server-side error frame (error code + class name + message)."""


def ser_data(v) -> bytes:
    """Python value -> hazelcast Data blob."""
    if v is None:
        return struct.pack(">ii", 0, TYPE_NULL)
    if isinstance(v, bool):
        raise DriverError("bool Data not needed by these workloads")
    if isinstance(v, int):
        return struct.pack(">iiq", 0, TYPE_LONG, v)
    if isinstance(v, str):
        b = v.encode()
        return struct.pack(">ii i", 0, TYPE_STRING, len(b)) + b
    if isinstance(v, (list, tuple)) and all(isinstance(x, int) for x in v):
        return (struct.pack(">iii", 0, TYPE_LONG_ARRAY, len(v))
                + b"".join(struct.pack(">q", x) for x in v))
    raise DriverError(f"unserializable value {v!r}")


def deser_data(b: bytes):
    """Hazelcast Data blob -> Python value."""
    if len(b) < 8:
        raise DriverError(f"short Data blob ({len(b)}B)")
    (tid,) = struct.unpack(">i", b[4:8])
    body = b[8:]
    if tid == TYPE_NULL:
        return None
    if tid == TYPE_LONG:
        return struct.unpack(">q", body)[0]
    if tid == TYPE_STRING:
        (n,) = struct.unpack(">i", body[:4])
        return body[4:4 + n].decode()
    if tid == TYPE_LONG_ARRAY:
        (n,) = struct.unpack(">i", body[:4])
        return list(struct.unpack(f">{n}q", body[4:4 + 8 * n]))
    raise DriverError(f"unknown Data type id {tid}")


class _W:
    """Little-endian payload writer."""

    def __init__(self):
        self.parts: list[bytes] = []

    def string(self, s: str) -> "_W":
        b = s.encode()
        self.parts.append(struct.pack("<i", len(b)) + b)
        return self

    def nullable_string(self, s: str | None) -> "_W":
        if s is None:
            self.parts.append(b"\x01")
        else:
            self.parts.append(b"\x00")
            self.string(s)
        return self

    def boolean(self, v: bool) -> "_W":
        self.parts.append(b"\x01" if v else b"\x00")
        return self

    def u8(self, v: int) -> "_W":
        self.parts.append(struct.pack("<B", v))
        return self

    def i64(self, v: int) -> "_W":
        self.parts.append(struct.pack("<q", v))
        return self

    def data(self, v) -> "_W":
        b = ser_data(v)
        self.parts.append(struct.pack("<i", len(b)) + b)
        return self

    def bytes_(self) -> bytes:
        return b"".join(self.parts)


class _R:
    """Little-endian payload reader."""

    def __init__(self, b: bytes):
        self.b = b
        self.i = 0

    def _take(self, n: int) -> bytes:
        if self.i + n > len(self.b):
            raise DriverError("truncated hazelcast payload")
        out = self.b[self.i:self.i + n]
        self.i += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def boolean(self) -> bool:
        return self._take(1)[0] != 0

    def i32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def string(self) -> str:
        return self._take(self.i32()).decode()

    def nullable_string(self) -> str | None:
        return None if self.u8() else self.string()

    def data(self) -> bytes:
        return self._take(self.i32())

    def nullable_data(self):
        return None if self.u8() else deser_data(self.data())


def pack_message(msg_type: int, correlation: int, payload: bytes,
                 partition: int = -1) -> bytes:
    return HEADER.pack(HEADER_SIZE + len(payload), VERSION, FLAG_BEGIN_END,
                       msg_type, correlation, partition,
                       HEADER_SIZE) + payload


def unpack_message(frame: bytes) -> tuple[int, int, bytes]:
    """frame (with length prefix) -> (type, correlation, payload)."""
    (_ln, _v, _fl, typ, corr, _part, off) = HEADER.unpack_from(frame)
    return typ, corr, frame[off:]


class HzConn:
    """One authenticated client connection to a member."""

    def __init__(self, host: str, port: int = 5701,
                 timeout: float = 10.0, username: str = "dev",
                 password: str = "dev-pass"):
        self.lock = threading.Lock()
        self.corr = itertools.count(1)
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.sock.sendall(PROTOCOL_INIT)
            self._authenticate(username, password)
        except OSError as e:
            raise DriverError(f"hazelcast connect {host}:{port}: {e}") from e

    # -- transport --------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise DriverError("hazelcast connection closed")
            buf += chunk
        return buf

    def request(self, msg_type: int, payload: bytes,
                partition: int = -1) -> tuple[int, _R]:
        """Send one message, read frames until our correlation id answers
        (event frames for other correlations are skipped)."""
        with self.lock:
            corr = next(self.corr)
            try:
                self.sock.sendall(
                    pack_message(msg_type, corr, payload, partition))
                while True:
                    head = self._recv_exact(4)
                    (ln,) = struct.unpack("<i", head)
                    frame = head + self._recv_exact(ln - 4)
                    typ, c, body = unpack_message(frame)
                    if c != corr:
                        continue
                    if typ == RESP_ERROR:
                        r = _R(body)
                        code = r.i32()
                        cls = r.nullable_string() or "?"
                        msg = r.nullable_string() or ""
                        raise HazelcastError(code, f"{cls}: {msg}")
                    return typ, _R(body)
            except OSError as e:
                raise DriverError(f"hazelcast io: {e}") from e

    def _authenticate(self, username: str, password: str) -> None:
        p = (_W().string(username).string(password)
             .nullable_string(None).nullable_string(None)
             .boolean(True).string("JPT").u8(1).string("3.10"))
        typ, r = self.request(AUTH, p.bytes_())
        if typ != RESP_AUTH:
            raise DriverError(f"unexpected auth response type {typ:#x}")
        status = r.u8()
        if status != 0:
            raise DBError(status, f"authentication failed ({status})")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- IMap -------------------------------------------------------------

    def map_get(self, name: str, key):
        _, r = self.request(
            MAP_GET, _W().string(name).data(key).i64(1).bytes_())
        return r.nullable_data()

    def map_put(self, name: str, key, value, ttl: int = -1):
        _, r = self.request(
            MAP_PUT,
            _W().string(name).data(key).data(value).i64(1).i64(ttl)
            .bytes_())
        return r.nullable_data()

    def map_put_if_absent(self, name: str, key, value, ttl: int = -1):
        """Returns the PREVIOUS value (None means the put won)."""
        _, r = self.request(
            MAP_PUT_IF_ABSENT,
            _W().string(name).data(key).data(value).i64(1).i64(ttl)
            .bytes_())
        return r.nullable_data()

    def map_replace_if_same(self, name: str, key, old, new) -> bool:
        _, r = self.request(
            MAP_REPLACE_IF_SAME,
            _W().string(name).data(key).data(old).data(new).i64(1)
            .bytes_())
        return r.boolean()

    # -- IQueue -----------------------------------------------------------

    def queue_offer(self, name: str, value, timeout_ms: int = 0) -> bool:
        _, r = self.request(
            QUEUE_OFFER,
            _W().string(name).data(value).i64(timeout_ms).bytes_())
        return r.boolean()

    def queue_poll(self, name: str, timeout_ms: int = 0):
        _, r = self.request(
            QUEUE_POLL, _W().string(name).i64(timeout_ms).bytes_())
        return r.nullable_data()

    def queue_take(self, name: str):
        _, r = self.request(QUEUE_TAKE, _W().string(name).bytes_())
        return r.nullable_data()

    def queue_size(self, name: str) -> int:
        _, r = self.request(QUEUE_SIZE, _W().string(name).bytes_())
        return r.i32()

    # -- ILock ------------------------------------------------------------

    def lock_lock(self, name: str, lease_ms: int = -1,
                  thread_id: int = 1, ref_id: int = 0) -> None:
        self.request(
            LOCK_LOCK,
            _W().string(name).i64(lease_ms).i64(thread_id).i64(ref_id)
            .bytes_())

    def lock_try_lock(self, name: str, timeout_ms: int,
                      lease_ms: int = -1, thread_id: int = 1,
                      ref_id: int = 0) -> bool:
        _, r = self.request(
            LOCK_TRY_LOCK,
            _W().string(name).i64(lease_ms).i64(timeout_ms)
            .i64(thread_id).i64(ref_id).bytes_())
        return r.boolean()

    def lock_unlock(self, name: str, thread_id: int = 1,
                    ref_id: int = 0) -> None:
        self.request(
            LOCK_UNLOCK,
            _W().string(name).i64(thread_id).i64(ref_id).bytes_())

    # -- IAtomicLong ------------------------------------------------------

    def atomic_long_increment_and_get(self, name: str) -> int:
        _, r = self.request(ATOMIC_LONG_INCREMENT_AND_GET,
                            _W().string(name).bytes_())
        return r.i64()

    def atomic_long_add_and_get(self, name: str, delta: int) -> int:
        _, r = self.request(ATOMIC_LONG_ADD_AND_GET,
                            _W().string(name).i64(delta).bytes_())
        return r.i64()

    def atomic_long_get(self, name: str) -> int:
        _, r = self.request(ATOMIC_LONG_GET, _W().string(name).bytes_())
        return r.i64()
