"""Apache Ignite thin-client binary protocol driver.

The reference suite drives Ignite through the JVM thick client
(ignite/src/java/client/Client.java, Bank.java); this is a from-scratch
implementation of the documented thin-client binary protocol (port
10800, protocol 1.x as spoken by Ignite 2.7+), covering the cache
surface the register/set/bank workloads need: handshake, get, put,
putIfAbsent, replaceIfEquals (the CAS primitive), and getAndPut.

Wire format: every packet is int32-LE length + body.

  handshake  body = u8 1 | i16 major | i16 minor | i16 patch | u8 2
             response: u8 success (1) | [server ver + error on failure]
  request    body = i16 op | i64 request_id | payload
             response: i64 request_id | i32 status | [error string]
             | payload

Values are binary-protocol typed: u8 type code + LE body (4 long,
8 bool, 9 string, 101 null). Cache ids are the Java String hashCode of
the cache name. Constants follow the published protocol spec; exercised
round-trip against tests/fake_ignite.py (zero-egress build), live
cluster in the opt-in tier.
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading

from . import DBError, DriverError

# -- op codes (thin client protocol) ---------------------------------------
OP_CACHE_GET = 1000
OP_CACHE_PUT = 1001
OP_CACHE_PUT_IF_ABSENT = 1002
OP_CACHE_GET_AND_PUT = 1005
OP_CACHE_REPLACE_IF_EQUALS = 1010
OP_CACHE_GET_OR_CREATE_WITH_NAME = 1052
OP_TX_START = 4000
OP_TX_END = 4001

FLAG_TRANSACTIONAL = 0x02

# -- binary type codes -----------------------------------------------------
T_LONG = 4
T_BOOL = 8
T_STRING = 9
T_NULL = 101


class IgniteError(DBError):
    pass


def java_hash(s: str) -> int:
    """Java String.hashCode — the protocol's cache-name -> cache-id map."""
    h = 0
    for c in s:
        h = (31 * h + ord(c)) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def ser(v) -> bytes:
    if v is None:
        return struct.pack("<B", T_NULL)
    if isinstance(v, bool):
        return struct.pack("<BB", T_BOOL, int(v))
    if isinstance(v, int):
        return struct.pack("<Bq", T_LONG, v)
    if isinstance(v, str):
        b = v.encode()
        return struct.pack("<Bi", T_STRING, len(b)) + b
    raise DriverError(f"unserializable ignite value {v!r}")


def deser(r: "_R"):
    t = r.u8()
    if t == T_NULL:
        return None
    if t == T_BOOL:
        return r.u8() != 0
    if t == T_LONG:
        return r.i64()
    if t == T_STRING:
        return r.take(r.i32()).decode()
    raise DriverError(f"unknown ignite type code {t}")


class _R:
    def __init__(self, b: bytes):
        self.b = b
        self.i = 0

    def take(self, n: int) -> bytes:
        if self.i + n > len(self.b):
            raise DriverError("truncated ignite payload")
        out = self.b[self.i:self.i + n]
        self.i += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def i16(self) -> int:
        return struct.unpack("<h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self.take(8))[0]

    def string(self) -> str | None:
        v = deser(self)
        if v is not None and not isinstance(v, str):
            raise DriverError(f"expected string, got {v!r}")
        return v


class IgniteConn:
    """One handshaked thin-client connection."""

    def __init__(self, host: str, port: int = 10800,
                 timeout: float = 10.0):
        self.lock = threading.Lock()
        self.req_id = itertools.count(1)
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self._handshake()
        except OSError as e:
            raise DriverError(f"ignite connect {host}:{port}: {e}") from e

    def _send_packet(self, body: bytes) -> None:
        self.sock.sendall(struct.pack("<i", len(body)) + body)

    def _recv_packet(self) -> _R:
        head = self._recv_exact(4)
        (ln,) = struct.unpack("<i", head)
        return _R(self._recv_exact(ln))

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise DriverError("ignite connection closed")
            buf += chunk
        return buf

    def _handshake(self) -> None:
        self._send_packet(struct.pack("<BhhhB", 1, 1, 0, 0, 2))
        r = self._recv_packet()
        if r.u8() != 1:
            ver = (r.i16(), r.i16(), r.i16())
            msg = r.string() or ""
            raise DBError("handshake", f"server {ver}: {msg}")

    def request(self, op: int, payload: bytes) -> _R:
        with self.lock:
            rid = next(self.req_id)
            try:
                self._send_packet(
                    struct.pack("<hq", op, rid) + payload)
                r = self._recv_packet()
            except OSError as e:
                raise DriverError(f"ignite io: {e}") from e
        got = r.i64()
        if got != rid:
            raise DriverError(f"request id mismatch {got} != {rid}")
        status = r.i32()
        if status != 0:
            raise IgniteError(status, r.string() or f"status {status}")
        return r

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- cache ops ---------------------------------------------------------

    @staticmethod
    def _cache_header(cache: str, tx: int | None = None) -> bytes:
        """Cache id + flags [+ tx id when the op joins a transaction —
        thin-client transactions are protocol 1.5+/Ignite 2.8+]."""
        if tx is None:
            return struct.pack("<iB", java_hash(cache), 0)
        return struct.pack("<iBi", java_hash(cache), FLAG_TRANSACTIONAL,
                           tx)

    # -- transactions (OP_TX_*, Ignite 2.8+) -------------------------------

    def tx_start(self, concurrency: int = 1, isolation: int = 2,
                 timeout_ms: int = 5000) -> int:
        """PESSIMISTIC (1) / REPEATABLE_READ (2) by default — the modes
        the reference bank workload runs under (ignite Client.java)."""
        r = self.request(OP_TX_START,
                         struct.pack("<BBq", concurrency, isolation,
                                     timeout_ms) + ser(None))
        return r.i32()

    def tx_end(self, tx: int, commit: bool) -> None:
        self.request(OP_TX_END, struct.pack("<iB", tx, int(commit)))

    def get_or_create_cache(self, cache: str) -> None:
        self.request(OP_CACHE_GET_OR_CREATE_WITH_NAME, ser(cache))

    def get(self, cache: str, key, tx: int | None = None):
        r = self.request(OP_CACHE_GET,
                         self._cache_header(cache, tx) + ser(key))
        return deser(r)

    def put(self, cache: str, key, value, tx: int | None = None) -> None:
        self.request(OP_CACHE_PUT,
                     self._cache_header(cache, tx) + ser(key) + ser(value))

    def get_and_put(self, cache: str, key, value):
        r = self.request(OP_CACHE_GET_AND_PUT,
                         self._cache_header(cache) + ser(key) + ser(value))
        return deser(r)

    def put_if_absent(self, cache: str, key, value) -> bool:
        r = self.request(OP_CACHE_PUT_IF_ABSENT,
                         self._cache_header(cache) + ser(key) + ser(value))
        return deser(r) is True

    def replace_if_equals(self, cache: str, key, old, new) -> bool:
        r = self.request(
            OP_CACHE_REPLACE_IF_EQUALS,
            self._cache_header(cache) + ser(key) + ser(old) + ser(new))
        return deser(r) is True
