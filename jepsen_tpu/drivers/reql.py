"""RethinkDB ReQL wire protocol client (no external deps).

The reference's rethinkdb suite uses the official JVM driver
(rethinkdb/src/jepsen/rethinkdb.clj); this client speaks the wire
protocol directly: the V1_0 handshake (magic + SCRAM-SHA-256 over
NUL-terminated JSON frames) and START queries as JSON-serialized term
ASTs with 8-byte tokens.

Only the terms a register/set workload needs are modeled: DB(14),
TABLE(15), GET(16), INSERT(56, conflict update/replace), DELETE(54),
TABLE_CREATE(60), DB_CREATE(57), and raw datum arguments. Write acks
ride the query options (durability, read_mode).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import socket
import struct

from . import DBError, DriverError

V1_0_MAGIC = 0x34C2BDC3

# term type ids (ql2.proto)
DB, TABLE, GET, INSERT = 14, 15, 16, 56
DELETE, DB_CREATE, TABLE_CREATE = 54, 57, 60

START, CONTINUE, STOP = 1, 2, 3

# response types
SUCCESS_ATOM, SUCCESS_SEQUENCE, SUCCESS_PARTIAL = 1, 2, 3
CLIENT_ERROR, COMPILE_ERROR, RUNTIME_ERROR = 16, 17, 18


class ReqlConn:
    def __init__(self, host: str, port: int = 28015,
                 user: str = "admin", password: str = "",
                 timeout: float = 10.0):
        self._buf = b""
        self._token = 0
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.sock.settimeout(timeout)
            self._handshake(user, password)
        except (OSError, DriverError, DBError):
            self._abandon()
            raise

    # -- transport ------------------------------------------------------

    def _recvn(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_nul_json(self) -> dict:
        while b"\0" not in self._buf:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        frame, self._buf = self._buf.split(b"\0", 1)
        out = json.loads(frame)
        if not out.get("success", True):
            raise DBError(str(out.get("error_code", "auth")),
                          out.get("error", "handshake failed"))
        return out

    def _abandon(self) -> None:
        try:
            if getattr(self, "sock", None) is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None

    # -- handshake ------------------------------------------------------

    def _handshake(self, user: str, password: str) -> None:
        self.sock.sendall(struct.pack("<I", V1_0_MAGIC))
        self._recv_nul_json()                       # server version info
        nonce = base64.b64encode(os.urandom(18)).decode()
        first_bare = f"n={user},r={nonce}"
        self.sock.sendall(json.dumps({
            "protocol_version": 0,
            "authentication_method": "SCRAM-SHA-256",
            "authentication": "n,," + first_bare,
        }).encode() + b"\0")
        resp = self._recv_nul_json()
        server_first = resp["authentication"]
        attrs = dict(p.split("=", 1) for p in server_first.split(","))
        salted = hashlib.pbkdf2_hmac(
            "sha256", password.encode(),
            base64.b64decode(attrs["s"]), int(attrs["i"]))
        ckey = hmac.digest(salted, b"Client Key", "sha256")
        stored = hashlib.sha256(ckey).digest()
        final_bare = f"c=biws,r={attrs['r']}"
        auth_msg = ",".join((first_bare, server_first,
                             final_bare)).encode()
        sig = hmac.digest(stored, auth_msg, "sha256")
        proof = bytes(a ^ b for a, b in zip(ckey, sig))
        self.sock.sendall(json.dumps({
            "authentication":
                f"{final_bare},p={base64.b64encode(proof).decode()}",
        }).encode() + b"\0")
        self._recv_nul_json()                       # server signature

    # -- queries --------------------------------------------------------

    def _send_query(self, token: int, q: list) -> dict:
        payload = json.dumps(q).encode()
        try:
            self.sock.sendall(struct.pack("<Q", token) +
                              struct.pack("<I", len(payload)) + payload)
        except OSError as e:
            self._abandon()
            raise DriverError(f"send failed: {e}") from e
        rtoken, = struct.unpack("<Q", self._recvn(8))
        rlen, = struct.unpack("<I", self._recvn(4))
        resp = json.loads(self._recvn(rlen))
        if rtoken != token:
            self._abandon()
            raise DriverError(f"token mismatch {rtoken} != {token}")
        t = resp.get("t")
        if t in (CLIENT_ERROR, COMPILE_ERROR, RUNTIME_ERROR):
            raise DBError(f"reql-{t}",
                          "; ".join(str(r) for r in resp.get("r", [])))
        return resp

    def run(self, term, opts: dict | None = None):
        """START a term; returns the decoded result (atom or full
        sequence — partial cursors are drained with CONTINUE)."""
        if self.sock is None:
            raise DriverError("connection is closed")
        self._token += 1
        token = self._token
        resp = self._send_query(token, [START, term, opts or {}])
        if resp.get("t") == SUCCESS_ATOM:
            r = resp.get("r", [])
            return r[0] if r else None
        out = list(resp.get("r", []))
        while resp.get("t") == SUCCESS_PARTIAL:
            resp = self._send_query(token, [CONTINUE])
            out += resp.get("r", [])
        return out                                   # full sequence

    # -- term builders --------------------------------------------------

    @staticmethod
    def table(db: str, name: str):
        return [TABLE, [[DB, [db]], name]]

    def db_create(self, name: str):
        try:
            return self.run([DB_CREATE, [name]])
        except DBError as e:
            if "already exists" in e.message:
                return None
            raise

    def table_create(self, db: str, name: str, **opts):
        try:
            return self.run([TABLE_CREATE, [[DB, [db]], name],
                             opts] if opts else
                            [TABLE_CREATE, [[DB, [db]], name]])
        except DBError as e:
            if "already exists" in e.message:
                return None
            raise

    def get(self, db: str, tbl: str, key, read_mode: str = "majority"):
        return self.run([GET, [self.table(db, tbl), key]],
                        {"read_mode": read_mode})

    def insert(self, db: str, tbl: str, doc: dict,
               conflict: str = "replace",
               durability: str = "hard") -> dict:
        res = self.run([INSERT, [self.table(db, tbl), doc],
                        {"conflict": conflict}],
                       {"durability": durability})
        # ReQL reports write failures in the result document, not as
        # an error response
        if isinstance(res, dict) and res.get("errors"):
            raise DBError("insert",
                          str(res.get("first_error", "insert failed")))
        return res

    def close(self) -> None:
        self._abandon()


def connect(host: str, port: int = 28015, user: str = "admin",
            password: str = "", timeout: float = 10.0) -> ReqlConn:
    return ReqlConn(host, port, user, password, timeout)
