"""Aerospike wire protocol driver (info + message protocols).

The reference suite drives Aerospike through the JVM client
(aerospike/src/aerospike/cas_register.clj:43 `AerospikeClient`,
counter.clj) — CAS is generation-check writes. This is a from-scratch
implementation of the server's bespoke binary protocol (port 3000):

  proto   8 bytes BE: version u8 (2) | type u8 (1 info, 3 message)
          | size u48
  info    payload = newline-separated names; reply "name\\tvalue\\n"
  message 22-byte header, all BE: header_sz u8 (22) | info1 u8
          | info2 u8 | info3 u8 | unused u8 | result u8
          | generation u32 | record_ttl u32 | transaction_ttl u32
          | n_fields u16 | n_ops u16, then fields and ops.
  field   size u32 (covers type+data) | type u8 | data
          (0 namespace, 1 set, 2 RIPEMD-160 key digest)
  op      size u32 | op u8 (1 read, 2 write) | particle u8
          (1 integer, 3 string) | version u8 | name_len u8 | name
          | particle data (integers are 8-byte BE)

CAS = read returning the record generation, then a write with
INFO2_GENERATION and the expected generation in the header — result 3
(generation mismatch) is the cas-failure. Exercised round-trip against
tests/fake_aerospike.py; live-cluster verification is the opt-in tier.
"""

from __future__ import annotations

import hashlib
import socket
import struct
import threading

from . import DBError, DriverError

PROTO_VERSION = 2
TYPE_INFO = 1
TYPE_MSG = 3

INFO1_READ = 0x01
INFO1_GET_ALL = 0x02
INFO2_WRITE = 0x01
INFO2_DELETE = 0x02
INFO2_GENERATION = 0x04
INFO2_CREATE_ONLY = 0x20

FIELD_NAMESPACE = 0
FIELD_SET = 1
FIELD_DIGEST = 2

PARTICLE_INTEGER = 1
PARTICLE_STRING = 3

RESULT_OK = 0
RESULT_NOT_FOUND = 2
RESULT_GENERATION = 3

MSG_HEADER = struct.Struct(">BBBBBBIIIHH")  # 22 bytes


class AerospikeError(DBError):
    pass


def key_digest(set_name: str, key) -> bytes:
    """RIPEMD-160 over set name + particle-typed key — the record
    address every request carries."""
    if isinstance(key, int):
        kb = bytes([PARTICLE_INTEGER]) + struct.pack(">q", key)
    else:
        kb = bytes([PARTICLE_STRING]) + str(key).encode()
    h = hashlib.new("ripemd160")
    h.update(set_name.encode())
    h.update(kb)
    return h.digest()


def _field(ftype: int, data: bytes) -> bytes:
    return struct.pack(">iB", len(data) + 1, ftype) + data


def _op(op: int, name: str, value=None) -> bytes:
    nb = name.encode()
    if value is None:
        body = struct.pack(">BBBB", op, 0, 0, len(nb)) + nb
    elif isinstance(value, int):
        body = (struct.pack(">BBBB", op, PARTICLE_INTEGER, 0, len(nb))
                + nb + struct.pack(">q", value))
    else:
        vb = str(value).encode()
        body = (struct.pack(">BBBB", op, PARTICLE_STRING, 0, len(nb))
                + nb + vb)
    return struct.pack(">i", len(body)) + body


def pack_message(info1: int, info2: int, generation: int,
                 fields: list[bytes], ops: list[bytes],
                 result: int = 0, info3: int = 0) -> bytes:
    body = MSG_HEADER.pack(22, info1, info2, info3, 0, result,
                           generation, 0, 1000, len(fields), len(ops))
    body += b"".join(fields) + b"".join(ops)
    return struct.pack(">Q",
                       (PROTO_VERSION << 56) | (TYPE_MSG << 48)
                       | len(body)) + body


def unpack_proto(head: bytes) -> tuple[int, int, int]:
    (word,) = struct.unpack(">Q", head)
    return word >> 56, (word >> 48) & 0xFF, word & ((1 << 48) - 1)


def parse_message(body: bytes) -> dict:
    """-> {result, generation, bins: {name: value}}"""
    (hsz, _i1, _i2, _i3, _u, result, gen, _ttl, _ttt, n_fields,
     n_ops) = MSG_HEADER.unpack_from(body)
    i = hsz
    for _ in range(n_fields):
        (sz,) = struct.unpack_from(">i", body, i)
        i += 4 + sz
    bins: dict = {}
    for _ in range(n_ops):
        (sz,) = struct.unpack_from(">i", body, i)
        op_body = body[i + 4:i + 4 + sz]
        i += 4 + sz
        _opc, particle, _ver, name_len = struct.unpack_from(
            ">BBBB", op_body)
        name = op_body[4:4 + name_len].decode()
        data = op_body[4 + name_len:]
        if particle == PARTICLE_INTEGER:
            bins[name] = struct.unpack(">q", data)[0]
        elif particle == PARTICLE_STRING:
            bins[name] = data.decode()
        else:
            bins[name] = None
    return {"result": result, "generation": gen, "bins": bins}


class AsConn:
    """One connection to a node; requests are serialized."""

    def __init__(self, host: str, port: int = 3000,
                 timeout: float = 10.0, namespace: str = "jepsen",
                 set_name: str = "jepsen"):
        self.lock = threading.Lock()
        self.namespace = namespace
        self.set_name = set_name
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
        except OSError as e:
            raise DriverError(
                f"aerospike connect {host}:{port}: {e}") from e

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise DriverError("aerospike connection closed")
            buf += chunk
        return buf

    def _roundtrip(self, packet: bytes) -> dict:
        with self.lock:
            try:
                self.sock.sendall(packet)
                ver, typ, size = unpack_proto(self._recv_exact(8))
                body = self._recv_exact(size)
            except OSError as e:
                raise DriverError(f"aerospike io: {e}") from e
        if ver != PROTO_VERSION or typ != TYPE_MSG:
            raise DriverError(f"bad proto header v{ver} t{typ}")
        return parse_message(body)

    def _key_fields(self, key) -> list[bytes]:
        return [_field(FIELD_NAMESPACE, self.namespace.encode()),
                _field(FIELD_SET, self.set_name.encode()),
                _field(FIELD_DIGEST, key_digest(self.set_name, key))]

    def info(self, names: list[str]) -> dict:
        payload = ("\n".join(names) + "\n").encode()
        with self.lock:
            try:
                self.sock.sendall(struct.pack(
                    ">Q", (PROTO_VERSION << 56) | (TYPE_INFO << 48)
                    | len(payload)) + payload)
                ver, typ, size = unpack_proto(self._recv_exact(8))
                body = self._recv_exact(size)
            except OSError as e:
                raise DriverError(f"aerospike io: {e}") from e
        out = {}
        for line in body.decode().splitlines():
            if "\t" in line:
                k, v = line.split("\t", 1)
                out[k] = v
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- record ops --------------------------------------------------------

    def get(self, key) -> dict | None:
        """-> {"bins": ..., "generation": n} or None when absent."""
        r = self._roundtrip(pack_message(
            INFO1_READ | INFO1_GET_ALL, 0, 0, self._key_fields(key), []))
        if r["result"] == RESULT_NOT_FOUND:
            return None
        if r["result"] != RESULT_OK:
            raise AerospikeError(r["result"], f"get: {r['result']}")
        return {"bins": r["bins"], "generation": r["generation"]}

    def put(self, key, bins: dict, generation: int | None = None,
            create_only: bool = False) -> None:
        """Write bins; with `generation`, only if the record's current
        generation matches (the CAS primitive); with create_only, only
        if the record doesn't exist. Raises AerospikeError(3) /
        AerospikeError(5) respectively on conflict."""
        info2 = INFO2_WRITE
        gen = 0
        if generation is not None:
            info2 |= INFO2_GENERATION
            gen = generation
        if create_only:
            info2 |= INFO2_CREATE_ONLY
        ops = [_op(2, n, v) for n, v in bins.items()]
        r = self._roundtrip(pack_message(
            0, info2, gen, self._key_fields(key), ops))
        if r["result"] != RESULT_OK:
            raise AerospikeError(r["result"], f"put: {r['result']}")

    def add(self, key, bin_name: str, delta: int) -> None:
        """Server-side counter increment (op 5 = INCR)."""
        r = self._roundtrip(pack_message(
            0, INFO2_WRITE, 0, self._key_fields(key),
            [_op(5, bin_name, delta)]))
        if r["result"] != RESULT_OK:
            raise AerospikeError(r["result"], f"add: {r['result']}")
