"""FaunaDB HTTP wire client + FQL wire-JSON constructors, no deps.

The reference drives FaunaDB through its JVM driver, which is an HTTP
client underneath: `FaunaClient/builder` pointed at `http://node:8443`
(or the `/linearized` endpoint) with the root key "secret"
(faunadb/src/jepsen/faunadb/client.clj:36-60). The wire protocol is a
single POST of the FQL expression in its wire-JSON form, Basic-auth'd
with `secret:`; responses come back as `{"resource": <tagged JSON>}`
or `{"errors": [...]}`.

This module carries both halves:

* the transport (`FaunaConn.query` / `query_all` pagination), and
* the FQL constructors the workloads need — the `q/...` forms of
  faunadb/query.clj re-expressed as wire JSON (`ref_`, `get_`,
  `if_`, `let`, `select`, `update`, `match`, `paginate`, `abort`, ...).

Error taxonomy: an HTTP response with a parseable `errors` array is a
*definite* rejection -> DBError(code, description) (`transaction
aborted` carries the abort message, which the bank workload
discriminates, faunadb/bank.clj:33-41); transport failures and
unparseable responses are indeterminate -> DriverError.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request
from typing import Any

from . import DBError, DriverError

API_VERSION = "2.7"


class Ref:
    """A decoded FaunaDB reference (client.clj's Ref record)."""

    __slots__ = ("id", "cls")

    def __init__(self, id: str, cls: "Ref | None" = None):
        self.id = id
        self.cls = cls

    def __eq__(self, other):
        return (isinstance(other, Ref) and other.id == self.id
                and other.cls == self.cls)

    def __hash__(self):
        return hash((self.id, self.cls))

    def __repr__(self):
        return f"Ref({self.id!r}, {self.cls!r})"


class Expr:
    """An already-encoded wire-JSON expression; `wrap` passes it
    through untouched so constructors compose."""

    __slots__ = ("json",)

    def __init__(self, j):
        self.json = j

    def __repr__(self):
        return f"Expr({self.json!r})"


def wrap(v: Any):
    """Python value -> wire JSON. Literal dicts become `{"object": ..}`
    (the driver does the same via Fn$UnescapedObject)."""
    if isinstance(v, Expr):
        return v.json
    if isinstance(v, Ref):
        # round-trip a decoded ref back into the expression language
        base = class_(v.cls.id) if v.cls is not None else None
        return ref_(base, v.id).json if base is not None \
            else {"@ref": v.id}
    if isinstance(v, dict):
        return {"object": {k: wrap(x) for k, x in v.items()}}
    if isinstance(v, (list, tuple)):
        return [wrap(x) for x in v]
    return v


def _fn(**parts) -> Expr:
    return Expr({k.rstrip("_"): wrap(v) for k, v in parts.items()})


# -- constructors (faunadb/query.clj equivalents) -------------------------

def class_(name: str) -> Expr:
    return Expr({"class": name})


def index(name: str) -> Expr:
    return Expr({"index": name})


def ref_(cls: Expr, id: Any) -> Expr:
    return Expr({"ref": wrap(cls), "id": str(id)})


def create_class(params: dict) -> Expr:
    return _fn(create_class=params)


def create_index(params: dict) -> Expr:
    return _fn(create_index=params)


def create(ref: Expr, params: dict) -> Expr:
    return Expr({"create": wrap(ref), "params": wrap(params)})


def update(ref: Expr, params: dict) -> Expr:
    return Expr({"update": wrap(ref), "params": wrap(params)})


def delete(ref: Expr) -> Expr:
    return _fn(delete=ref)


def get_(ref: Expr) -> Expr:
    return _fn(get=ref)


def exists(ref: Expr) -> Expr:
    return _fn(exists=ref)


def if_(cond, then=None, else_=None) -> Expr:
    return Expr({"if": wrap(cond), "then": wrap(then),
                 "else": wrap(else_)})


def when(cond, then) -> Expr:
    """q/when: if with a nil else branch."""
    return if_(cond, then, None)


def let(bindings: dict, in_) -> Expr:
    return Expr({"let": {k: wrap(v) for k, v in bindings.items()},
                 "in": wrap(in_)})


def var(name: str) -> Expr:
    return _fn(var=name)


def select(path: list, from_) -> Expr:
    return Expr({"select": wrap(path), "from": wrap(from_)})


def equals(*args) -> Expr:
    return Expr({"equals": [wrap(a) for a in args]})


def add(*args) -> Expr:
    return Expr({"add": [wrap(a) for a in args]})


def subtract(*args) -> Expr:
    return Expr({"subtract": [wrap(a) for a in args]})


def lt(*args) -> Expr:
    return Expr({"lt": [wrap(a) for a in args]})


def and_(*args) -> Expr:
    return Expr({"and": [wrap(a) for a in args]})


def not_(a) -> Expr:
    return _fn(not_=a)


def do(*exprs) -> Expr:
    return Expr({"do": [wrap(e) for e in exprs]})


def match(idx: Expr, *terms) -> Expr:
    j: dict = {"match": wrap(idx)}
    if terms:
        j["terms"] = [wrap(t) for t in terms]
    return Expr(j)


def paginate(set_, size: int = 1024, after=None) -> Expr:
    j = {"paginate": wrap(set_), "size": size}
    if after is not None:
        j["after"] = wrap(after)
    return Expr(j)


def abort(msg: str) -> Expr:
    return _fn(abort=msg)


def time(s: str) -> Expr:
    return _fn(time=s)


def lambda_(params: list[str] | str, body) -> Expr:
    return Expr({"lambda": params, "expr": wrap(body)})


def map_(f: Expr, collection) -> Expr:
    return Expr({"map": wrap(f), "collection": wrap(collection)})


def foreach(f: Expr, collection) -> Expr:
    return Expr({"foreach": wrap(f), "collection": wrap(collection)})


def at(ts, expr) -> Expr:
    return Expr({"at": wrap(ts), "expr": wrap(expr)})


# -- decoding -------------------------------------------------------------

def decode(j: Any) -> Any:
    """Tagged wire JSON -> Python (client.clj's `decode`)."""
    if isinstance(j, dict):
        if "@ref" in j:
            r = j["@ref"]
            if isinstance(r, dict):
                cls = decode(r.get("class")) if "class" in r else None
                return Ref(r.get("id"), cls)
            return Ref(str(r))
        if "@obj" in j:
            return decode(j["@obj"])
        if "@set" in j:
            return decode(j["@set"])
        if "@ts" in j or "@date" in j:
            return j["@ts"] if "@ts" in j else j["@date"]
        return {k: decode(v) for k, v in j.items()}
    if isinstance(j, list):
        return [decode(v) for v in j]
    return j


# -- transport ------------------------------------------------------------

class FaunaConn:
    """One HTTP endpoint (optionally the /linearized path) + secret."""

    def __init__(self, host: str, port: int = 8443,
                 secret: str = "secret", path: str = "",
                 timeout: float = 10.0):
        self.base = f"http://{host}:{port}{path}"
        self.timeout = timeout
        tok = base64.b64encode(f"{secret}:".encode()).decode()
        self.headers = {
            "Authorization": f"Basic {tok}",
            "Content-Type": "application/json; charset=utf-8",
            "X-FaunaDB-API-Version": API_VERSION,
        }

    def query(self, expr) -> Any:
        """POST one FQL expression; return the decoded resource."""
        body = json.dumps(wrap(expr)).encode()
        req = urllib.request.Request(self.base + "/", data=body,
                                     method="POST", headers=self.headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                out = json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                errs = json.loads(e.read()).get("errors") or []
            except Exception:
                errs = []
            # Only 4xx responses are definite rejections. 5xx (internal
            # error / unavailable) may have committed server-side — the
            # reference maps InternalException/UnavailableException to
            # indeterminate :info (faunadb client.clj with-errors), so
            # raise DriverError and let invoke classify writes as info.
            if errs and e.code < 500:
                first = errs[0]
                desc = "; ".join(
                    f"{x.get('code', '?')}: {x.get('description', '')}"
                    for x in errs)
                raise DBError(first.get("code", str(e.code)), desc) from e
            raise DriverError(f"fauna http {e.code}: {e.reason}") from e
        except (OSError, json.JSONDecodeError) as e:
            raise DriverError(f"fauna request failed: {e}") from e
        if "resource" not in out:
            raise DriverError(f"malformed fauna response: {out!r}")
        return decode(out["resource"])

    def query_all(self, set_expr, size: int = 1024) -> list:
        """Paginate a set expression to exhaustion at ONE snapshot
        (client.clj's query-all): the first request pins a timestamp
        with time('now'), and every page — including the first — runs
        inside at(ts, ...), so a multi-page read under concurrent
        writes stays snapshot-consistent. (The explicitly
        non-transactional variant is query_all_naive.)"""
        # decode() strips the @ts tag to a plain ISO string; re-tag it
        # with time() or at() would receive a bare string literal.
        ts = time(self.query(time("now")))
        out: list = []
        after = None
        while True:
            page = self.query(
                at(ts, paginate(set_expr, size=size, after=after)))
            out.extend(page.get("data", []))
            after = page.get("after")
            if not after:
                return out

    def query_all_naive(self, set_expr, size: int = 1024) -> list:
        """Cursor-follow with a fresh transaction per page (the
        reference's query-all-naive): each page sees a different
        snapshot, so cross-page isolation is deliberately absent. The
        pages workload reads with the PINNED query_all by default, like
        the reference (pages.clj reads via f/query-all) — whether the
        server's at()-pinned pagination is actually atomic is the
        property under test; pass pages-naive-reads to hunt the
        known-torn variant instead."""
        out: list = []
        after = None
        while True:
            page = self.query(paginate(set_expr, size=size, after=after))
            out.extend(page.get("data", []))
            after = page.get("after")
            if not after:
                return out

    def close(self) -> None:
        pass


def connect(host: str, port: int = 8443, secret: str = "secret",
            linearized: bool = False, timeout: float = 10.0) -> FaunaConn:
    """`linearized` selects the /linearized endpoint the register and
    set workloads use (client.clj:56-60)."""
    return FaunaConn(host, port, secret,
                     "/linearized" if linearized else "", timeout)
