"""MySQL client/server protocol driver (no external deps).

Speaks the text protocol against MySQL-compatible servers — built for
TiDB, which the reference drives through jdbc/mysql
(tidb/src/tidb/sql.clj). Supports the classic handshake
(HandshakeV10 / HandshakeResponse41), mysql_native_password and
cleartext auth, COM_QUERY with text result sets, and COM_QUIT.
CLIENT_DEPRECATE_EOF is deliberately not negotiated so result sets use
the classic EOF framing (one framing to parse, and every server still
speaks it).

Wire format (https://dev.mysql.com/doc/dev/mysql-server/latest/):
packets are `len:3(LE) seq:1 payload`, sequence id resets per command.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from dataclasses import dataclass, field

from . import DBError, DriverError

CLIENT_CONNECT_WITH_DB = 0x00000008
CLIENT_PROTOCOL_41 = 0x00000200
CLIENT_TRANSACTIONS = 0x00002000
CLIENT_SECURE_CONNECTION = 0x00008000
CLIENT_PLUGIN_AUTH = 0x00080000

SERVER_MORE_RESULTS_EXISTS = 0x0008


@dataclass
class Result:
    columns: list = field(default_factory=list)
    rows: list = field(default_factory=list)
    affected_rows: int = 0
    last_insert_id: int = 0


class MyConn:
    def __init__(self, host: str, port: int = 3306, user: str = "root",
                 database: str = "", password: str = "",
                 timeout: float = 10.0):
        self.host, self.port, self.user = host, port, user
        self._buf = b""
        self._seq = 0
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.sock.settimeout(timeout)
            self._handshake(database, password)
        except (OSError, DriverError, DBError):
            self._abandon()
            raise

    # ---- framing ------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_packet(self) -> bytes:
        head = self._recv_exact(4)
        length = head[0] | (head[1] << 8) | (head[2] << 16)
        self._seq = (head[3] + 1) & 0xFF
        payload = self._recv_exact(length)
        if length == 0xFFFFFF:  # continuation framing for >16MB payloads
            payload += self._recv_packet()
        return payload

    def _send_packet(self, payload: bytes) -> None:
        try:
            self.sock.sendall(
                struct.pack("<I", len(payload))[:3] +
                bytes([self._seq]) + payload)
            self._seq = (self._seq + 1) & 0xFF
        except OSError as e:
            self._abandon()
            raise DriverError(f"send failed: {e}") from e

    def _abandon(self) -> None:
        try:
            if getattr(self, "sock", None) is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None

    # ---- handshake ----------------------------------------------------

    def _handshake(self, database: str, password: str) -> None:
        greeting = self._recv_packet()
        if greeting[:1] == b"\xff":
            raise _err_packet(greeting)
        if greeting[0] != 10:
            raise DriverError(f"unsupported protocol {greeting[0]}")
        off = 1
        end = greeting.index(b"\0", off)           # server version
        off = end + 1 + 4                          # thread id
        auth_data = greeting[off:off + 8]          # scramble part 1
        off += 8 + 1                               # filler
        off += 2                                   # capabilities (lower)
        plugin = "mysql_native_password"
        if len(greeting) > off:
            off += 1 + 2 + 2                       # charset, status, cap hi
            auth_len = greeting[off]
            off += 1 + 10                          # reserved
            more = max(13, auth_len - 8)
            auth_data += greeting[off:off + more].rstrip(b"\0")
            off += more
            if off < len(greeting):
                plugin = greeting[off:].split(b"\0")[0].decode()

        caps = (CLIENT_PROTOCOL_41 | CLIENT_SECURE_CONNECTION |
                CLIENT_TRANSACTIONS | CLIENT_PLUGIN_AUTH)
        if database:
            caps |= CLIENT_CONNECT_WITH_DB
        auth = _scramble(password, auth_data[:20], plugin)
        payload = struct.pack("<IIB23x", caps, 1 << 24, 33)
        payload += self.user.encode() + b"\0"
        payload += bytes([len(auth)]) + auth
        if database:
            payload += database.encode() + b"\0"
        payload += plugin.encode() + b"\0"
        self._send_packet(payload)

        pkt = self._recv_packet()
        while True:
            if pkt[:1] == b"\x00":
                return
            if pkt[:1] == b"\xff":
                raise _err_packet(pkt)
            if pkt[:1] == b"\xfe":                 # AuthSwitchRequest
                parts = pkt[1:].split(b"\0")
                plugin = parts[0].decode()
                seed = parts[1] if len(parts) > 1 else b""
                self._send_packet(_scramble(password, seed[:20], plugin))
                pkt = self._recv_packet()
            elif pkt[:1] == b"\x01":               # AuthMoreData
                pkt = self._recv_packet()
            else:
                raise DriverError(f"unexpected auth packet {pkt[:1]!r}")

    # ---- queries ------------------------------------------------------

    def query(self, sql: str) -> Result:
        if self.sock is None:
            raise DriverError("connection is closed")
        self._seq = 0
        self._send_packet(b"\x03" + sql.encode())
        return self._read_result()

    exec = query

    def _read_result(self) -> Result:
        pkt = self._recv_packet()
        if pkt[:1] == b"\xff":
            raise _err_packet(pkt)
        if pkt[:1] == b"\x00":                     # OK
            affected, off = _lenenc_int(pkt, 1)
            last_id, _ = _lenenc_int(pkt, off)
            return Result(affected_rows=affected, last_insert_id=last_id)
        ncols, _ = _lenenc_int(pkt, 0)
        cols = []
        for _ in range(ncols):
            cols.append(_column_name(self._recv_packet()))
        eof = self._recv_packet()
        if eof[:1] != b"\xfe":
            raise DriverError("expected EOF after column definitions")
        rows = []
        while True:
            pkt = self._recv_packet()
            if pkt[:1] == b"\xfe" and len(pkt) < 9:  # EOF
                return Result(columns=cols, rows=rows)
            if pkt[:1] == b"\xff":
                raise _err_packet(pkt)
            rows.append(_text_row(pkt, ncols))

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._seq = 0
                self._send_packet(b"\x01")         # COM_QUIT
            except DriverError:
                pass
            self._abandon()


def _scramble(password: str, seed: bytes, plugin: str) -> bytes:
    if not password:
        return b""
    if plugin in ("mysql_native_password", ""):
        # SHA1(pass) XOR SHA1(seed + SHA1(SHA1(pass)))
        h1 = hashlib.sha1(password.encode()).digest()
        h2 = hashlib.sha1(h1).digest()
        h3 = hashlib.sha1(seed + h2).digest()
        return bytes(a ^ b for a, b in zip(h1, h3))
    if plugin == "mysql_clear_password":
        return password.encode() + b"\0"
    if plugin == "caching_sha2_password":
        # fast path: XOR(SHA256(p), SHA256(SHA256(SHA256(p)) + seed))
        h1 = hashlib.sha256(password.encode()).digest()
        h2 = hashlib.sha256(hashlib.sha256(h1).digest() + seed).digest()
        return bytes(a ^ b for a, b in zip(h1, h2))
    raise DriverError(f"unsupported auth plugin {plugin!r}")


def _lenenc_int(data: bytes, off: int) -> tuple[int, int]:
    first = data[off]
    if first < 0xFB:
        return first, off + 1
    if first == 0xFC:
        return struct.unpack_from("<H", data, off + 1)[0], off + 3
    if first == 0xFD:
        b = data[off + 1:off + 4]
        return b[0] | (b[1] << 8) | (b[2] << 16), off + 4
    if first == 0xFE:
        return struct.unpack_from("<Q", data, off + 1)[0], off + 9
    raise DriverError(f"bad length-encoded int 0x{first:x}")


def _lenenc_str(data: bytes, off: int) -> tuple[bytes, int]:
    n, off = _lenenc_int(data, off)
    return data[off:off + n], off + n


def _column_name(pkt: bytes) -> str:
    off = 0
    for _ in range(4):                 # catalog, schema, table, org_table
        _, off = _lenenc_str(pkt, off)
    name, _ = _lenenc_str(pkt, off)
    return name.decode()


def _text_row(pkt: bytes, ncols: int) -> list:
    row, off = [], 0
    for _ in range(ncols):
        if pkt[off] == 0xFB:                       # NULL
            row.append(None)
            off += 1
        else:
            val, off = _lenenc_str(pkt, off)
            row.append(val.decode())
    return row


def _err_packet(pkt: bytes) -> DBError:
    (code,) = struct.unpack_from("<H", pkt, 1)
    off = 3
    if pkt[off:off + 1] == b"#":                   # SQLSTATE marker
        off += 6
    return DBError(code, pkt[off:].decode(errors="replace"))


def connect(host: str, port: int = 3306, user: str = "root",
            database: str = "", password: str = "",
            timeout: float = 10.0) -> MyConn:
    return MyConn(host, port, user, database, password, timeout)
