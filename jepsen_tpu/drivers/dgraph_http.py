"""Dgraph HTTP API client (no external deps).

The reference's dgraph suite drives Dgraph over its grpc client
(dgraph/src/jepsen/dgraph/client.clj); Dgraph exposes the same
transaction API over plain HTTP on the alpha's 8080 port, which is what
this client uses: /alter for schema, /query for DQL reads, /mutate for
writes, with optional multi-request transactions via start_ts + commit.

Transactions: `begin()` returns a Txn; queries/mutations within it carry
`start_ts` (and accumulate preds/keys), `commit()` posts them to
/commit. Single-shot mutations pass commitNow=true.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from . import DBError, DriverError


class DgraphConn:
    def __init__(self, host: str, port: int = 8080, timeout: float = 10.0):
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def _post(self, path: str, body: bytes, content_type: str) -> dict:
        req = urllib.request.Request(
            self.base + path, data=body, method="POST",
            headers={"Content-Type": content_type})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                out = json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("errors")
            except Exception:
                detail = None
            raise DBError(str(e.code), f"{detail or e.reason}") from e
        except (OSError, json.JSONDecodeError) as e:
            raise DriverError(f"dgraph request failed: {e}") from e
        errs = out.get("errors")
        if errs:
            msg = "; ".join(e.get("message", "") for e in errs)
            code = (errs[0].get("extensions") or {}).get("code", "Unknown")
            # Aborted transactions are definite failures (the server
            # rejected the commit) — map to a retriable code.
            raise DBError(code, msg)
        return out

    def alter(self, schema: str) -> dict:
        return self._post("/alter", schema.encode(), "application/dql")

    def query(self, dql: str, start_ts: int | None = None) -> dict:
        path = "/query" + (f"?startTs={start_ts}" if start_ts else "")
        return self._post(path, dql.encode(), "application/dql")

    def mutate(self, set_obj=None, delete_obj=None, commit_now=True,
               start_ts: int | None = None, cond: str | None = None,
               query: str | None = None,
               mutations: list[dict] | None = None) -> dict:
        """`mutations` is the multi-block upsert form: a list of
        {"set": [...], "cond": "@if(...)"} applied atomically against
        one `query`'s vars (dgraph's conditional upsert)."""
        mu: dict = {}
        if mutations is not None:
            mu["mutations"] = mutations
        if set_obj is not None:
            mu["set"] = set_obj
        if delete_obj is not None:
            mu["delete"] = delete_obj
        if cond:
            mu["cond"] = cond
        if query:  # upsert block: vars from `query` usable in set/cond
            mu["query"] = query
        body = mu
        params = []
        if commit_now:
            params.append("commitNow=true")
        if start_ts:
            params.append(f"startTs={start_ts}")
        path = "/mutate" + ("?" + "&".join(params) if params else "")
        return self._post(path, json.dumps(body).encode(),
                          "application/json")

    def begin(self) -> "Txn":
        return Txn(self)

    def close(self) -> None:
        pass


class Txn:
    """Multi-request transaction: first op pins start_ts, ops accumulate
    the txn context (keys/preds), commit posts it to /commit."""

    def __init__(self, conn: DgraphConn):
        self.conn = conn
        self.start_ts: int | None = None
        self.keys: list = []
        self.preds: list = []

    def _merge(self, out: dict) -> dict:
        ext = out.get("extensions", {}).get("txn", {})
        if self.start_ts is None:
            self.start_ts = ext.get("start_ts")
        self.keys += ext.get("keys", [])
        self.preds += ext.get("preds", [])
        return out

    def query(self, dql: str) -> dict:
        out = self.conn._post(
            "/query" + (f"?startTs={self.start_ts}" if self.start_ts
                        else ""),
            dql.encode(), "application/dql")
        return self._merge(out)

    def mutate(self, set_obj=None, delete_obj=None,
               cond: str | None = None, query: str | None = None,
               mutations: list[dict] | None = None) -> dict:
        out = self.conn.mutate(set_obj, delete_obj, commit_now=False,
                               start_ts=self.start_ts, cond=cond,
                               query=query, mutations=mutations)
        return self._merge(out)

    def commit(self) -> dict:
        if self.start_ts is None:
            return {}
        ctx = {"start_ts": self.start_ts, "keys": self.keys,
               "preds": self.preds}
        return self.conn._post(
            f"/commit?startTs={self.start_ts}",
            json.dumps(ctx).encode(), "application/json")

    def discard(self) -> None:
        if self.start_ts is not None:
            try:
                self.conn._post(
                    f"/commit?startTs={self.start_ts}&abort=true",
                    b"{}", "application/json")
            except (DBError, DriverError):
                pass


def connect(host: str, port: int = 8080, timeout: float = 10.0,
            **_kw) -> DgraphConn:
    return DgraphConn(host, port, timeout)
