"""CQL binary protocol v4 client (no external deps).

Speaks the Cassandra native protocol for YugabyteDB's YCQL API — the
reference's yugabyte suite drives YCQL through the java cassandra
driver (yugabyte/src/yugabyte/ycql/*). One socket, synchronous,
unprepared QUERY messages only: a jepsen worker needs nothing more, and
text-literal statements keep the client honest about exactly what hits
the server.

Frame: version:1 flags:1 stream:2 opcode:1 length:4, big-endian
(protocol spec §2). Results decode by column type id; only the types
YCQL workloads touch are mapped (varchar/int/bigint/boolean/list).
"""

from __future__ import annotations

import socket
import struct
from dataclasses import dataclass, field

from . import DBError, DriverError

REQUEST = 0x04
RESPONSE = 0x84

OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

CONSISTENCY_QUORUM = 0x0004

KIND_VOID = 0x0001
KIND_ROWS = 0x0002
KIND_SET_KEYSPACE = 0x0003
KIND_SCHEMA_CHANGE = 0x0005

TYPE_BIGINT = 0x0002
TYPE_BOOLEAN = 0x0004
TYPE_INT = 0x0009
TYPE_VARCHAR = 0x000D
TYPE_LIST = 0x0020


@dataclass
class Result:
    columns: list = field(default_factory=list)
    rows: list = field(default_factory=list)
    kind: int = KIND_VOID


class CQLConn:
    def __init__(self, host: str, port: int = 9042,
                 user: str | None = None, password: str | None = None,
                 keyspace: str | None = None, timeout: float = 10.0):
        self.host, self.port = host, port
        self._buf = b""
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.sock.settimeout(timeout)
            self._startup(user, password)
            if keyspace:
                self.query(f"USE {keyspace}")
        except (OSError, DriverError, DBError):
            self._abandon()
            raise

    # -- framing --------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _send_frame(self, opcode: int, body: bytes) -> None:
        try:
            self.sock.sendall(struct.pack("!BBhBI", REQUEST, 0, 0,
                                          opcode, len(body)) + body)
        except OSError as e:
            self._abandon()
            raise DriverError(f"send failed: {e}") from e

    def _recv_frame(self) -> tuple[int, bytes]:
        head = self._recv_exact(9)
        _ver, _flags, _stream, opcode, length = struct.unpack("!BBhBI",
                                                              head)
        return opcode, self._recv_exact(length)

    def _abandon(self) -> None:
        try:
            if getattr(self, "sock", None) is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None

    # -- startup --------------------------------------------------------

    def _startup(self, user, password) -> None:
        opts = {"CQL_VERSION": "3.0.0"}
        body = struct.pack("!H", len(opts))
        for k, v in opts.items():
            body += _string(k) + _string(v)
        self._send_frame(OP_STARTUP, body)
        opcode, data = self._recv_frame()
        if opcode == OP_READY:
            return
        if opcode == OP_AUTHENTICATE:
            token = b"\0" + (user or "").encode() + b"\0" + \
                (password or "").encode()
            self._send_frame(OP_AUTH_RESPONSE,
                             struct.pack("!i", len(token)) + token)
            opcode, data = self._recv_frame()
            if opcode == OP_AUTH_SUCCESS:
                return
        if opcode == OP_ERROR:
            raise _error(data)
        raise DriverError(f"unexpected startup opcode 0x{opcode:02x}")

    # -- queries --------------------------------------------------------

    def query(self, cql: str,
              consistency: int = CONSISTENCY_QUORUM) -> Result:
        if self.sock is None:
            raise DriverError("connection is closed")
        body = _long_string(cql) + struct.pack("!HB", consistency, 0)
        self._send_frame(OP_QUERY, body)
        opcode, data = self._recv_frame()
        if opcode == OP_ERROR:
            raise _error(data)
        if opcode != OP_RESULT:
            self._abandon()
            raise DriverError(f"unexpected opcode 0x{opcode:02x}")
        return _result(data)

    exec = query

    def close(self) -> None:
        self._abandon()


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!I", len(b)) + b


def _read_string(data: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("!H", data, off)
    off += 2
    return data[off:off + n].decode(), off + n


def _error(data: bytes) -> DBError:
    (code,) = struct.unpack_from("!i", data, 0)
    msg, _ = _read_string(data, 4)
    return DBError(f"cql-{code:#06x}", msg)


def _read_type(data: bytes, off: int) -> tuple[tuple, int]:
    (tid,) = struct.unpack_from("!H", data, off)
    off += 2
    if tid == TYPE_LIST:
        inner, off = _read_type(data, off)
        return (tid, inner), off
    if tid == 0x0000:  # custom: class name string follows
        _, off = _read_string(data, off)
    return (tid, None), off


def _decode(value: bytes | None, typ: tuple):
    if value is None:
        return None
    tid, inner = typ
    if tid == TYPE_BIGINT:
        return struct.unpack("!q", value)[0]
    if tid == TYPE_INT:
        return struct.unpack("!i", value)[0]
    if tid == TYPE_BOOLEAN:
        return bool(value[0])
    if tid == TYPE_LIST:
        (n,) = struct.unpack_from("!i", value, 0)
        off, out = 4, []
        for _ in range(n):
            (ln,) = struct.unpack_from("!i", value, off)
            off += 4
            if ln < 0:
                out.append(None)
            else:
                out.append(_decode(value[off:off + ln], inner))
                off += ln
        return out
    return value.decode()  # varchar & fallback


def _result(data: bytes) -> Result:
    (kind,) = struct.unpack_from("!i", data, 0)
    if kind != KIND_ROWS:
        return Result(kind=kind)
    off = 4
    flags, ncols = struct.unpack_from("!iI", data, off)
    off += 8
    if flags & 0x0002:  # has_more_pages: paging state bytes
        (n,) = struct.unpack_from("!i", data, off)
        off += 4 + max(0, n)
    global_spec = bool(flags & 0x0001)
    if global_spec:
        _, off = _read_string(data, off)
        _, off = _read_string(data, off)
    cols, types = [], []
    for _ in range(ncols):
        if not global_spec:
            _, off = _read_string(data, off)
            _, off = _read_string(data, off)
        name, off = _read_string(data, off)
        typ, off = _read_type(data, off)
        cols.append(name)
        types.append(typ)
    (nrows,) = struct.unpack_from("!i", data, off)
    off += 4
    rows = []
    for _ in range(nrows):
        row = []
        for c in range(ncols):
            (ln,) = struct.unpack_from("!i", data, off)
            off += 4
            if ln < 0:
                row.append(None)
            else:
                row.append(_decode(data[off:off + ln], types[c]))
                off += ln
        rows.append(row)
    return Result(columns=cols, rows=rows, kind=kind)


def connect(host: str, port: int = 9042, user: str | None = None,
            password: str | None = None, keyspace: str | None = None,
            timeout: float = 10.0) -> CQLConn:
    return CQLConn(host, port, user, password, keyspace, timeout)
