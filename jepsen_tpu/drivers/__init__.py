"""DB wire-protocol drivers, dependency-free.

The reference's suites lean on JVM client libraries (jdbc for
cockroach/tidb/yugabyte — cockroachdb/src/jepsen/cockroach/client.clj:1-60,
dgraph's grpc client). This image ships no psycopg2/pymysql, so the
framework carries its own minimal clients:

    pgwire       PostgreSQL wire protocol v3 (cockroach, yugabyte YSQL)
    mysql_wire   MySQL client/server protocol (tidb)
    dgraph_http  Dgraph HTTP API (mutate/query/alter)

All are synchronous, one-socket, simple-query-protocol clients — exactly
what a jepsen client worker needs: each worker owns one connection, and
the latency of interest is the DB's, not the driver's.

Error taxonomy (client.clj semantics): a `DBError` is a *definite*
failure — the op did not happen (safe to map to type "fail"); a
`DriverError` (connection loss, timeout, protocol violation) is
*indeterminate* — map to type "info".
"""

from __future__ import annotations


class DriverError(Exception):
    """Indeterminate failure: connection dropped, timeout, protocol
    desync. The op may or may not have taken effect -> op type "info"."""


class DBError(Exception):
    """Definite failure reported by the database: the statement was
    rejected, nothing happened -> op type "fail".

    `code` is the backend's error code (SQLSTATE for pg, errno for
    mysql, HTTP-ish for dgraph)."""

    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.message = message


#: SQLSTATEs / error codes that signal a retriable conflict: the txn was
#: definitely aborted (serialization failure, deadlock, write conflict).
RETRIABLE_PG = {"40001", "40P01", "23505"}
RETRIABLE_MYSQL = {1062, 1213, 1205, 8022, 8028, 9007}  # duplicate key,
# deadlock, lock wait; tidb: txn retryable / schema changed / write conflict


def is_retriable(exc: Exception) -> bool:
    """True when the error is a definite abort the workload may retry
    (cockroach/client.clj's retry-loop discriminates exactly these)."""
    if not isinstance(exc, DBError):
        return False
    code = exc.code
    if isinstance(code, str):
        return code in RETRIABLE_PG
    return code in RETRIABLE_MYSQL
