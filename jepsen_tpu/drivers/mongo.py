"""MongoDB wire-protocol client: minimal BSON + OP_MSG (no deps).

The reference's mongodb suites use the java driver
(mongodb-rocks/src/jepsen/mongodb/, mongodb-smartos); this client
implements the modern wire protocol's single message type (OP_MSG,
opcode 2013) with hand-rolled BSON for the types a jepsen workload
touches: documents, arrays, strings, ints, bools, null, doubles.

Commands are plain documents (insert/find/update/findAndModify/
delete); read/write concerns ride along as subdocuments, which is how
the suites express majority acknowledgement.
"""

from __future__ import annotations

import socket
import struct
import threading

from . import DBError, DriverError

OP_MSG = 2013


# ---------------------------------------------------------------------
# BSON


def _enc_element(key: str, v) -> bytes:
    kb = key.encode() + b"\0"
    if isinstance(v, bool):                 # before int (bool is int)
        return b"\x08" + kb + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + kb + struct.pack("<i", v)
        return b"\x12" + kb + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + kb + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode() + b"\0"
        return b"\x02" + kb + struct.pack("<i", len(b)) + b
    if v is None:
        return b"\x0a" + kb
    if isinstance(v, dict):
        return b"\x03" + kb + encode_doc(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + kb + encode_doc(
            {str(i): x for i, x in enumerate(v)})
    if isinstance(v, bytes):
        return b"\x05" + kb + struct.pack("<i", len(v)) + b"\x00" + v
    raise TypeError(f"can't BSON-encode {type(v)}")


def encode_doc(doc: dict) -> bytes:
    body = b"".join(_enc_element(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\0"


def _dec_element(data: bytes, off: int) -> tuple[str, object, int]:
    t = data[off]
    off += 1
    end = data.index(b"\0", off)
    key = data[off:end].decode()
    off = end + 1
    if t == 0x01:
        return key, struct.unpack_from("<d", data, off)[0], off + 8
    if t == 0x02:
        (n,) = struct.unpack_from("<i", data, off)
        return key, data[off + 4:off + 4 + n - 1].decode(), off + 4 + n
    if t in (0x03, 0x04):
        doc, off2 = decode_doc(data, off)
        if t == 0x04:
            return key, [doc[str(i)] for i in range(len(doc))], off2
        return key, doc, off2
    if t == 0x05:
        (n,) = struct.unpack_from("<i", data, off)
        return key, data[off + 5:off + 5 + n], off + 5 + n
    if t == 0x07:
        return key, data[off:off + 12], off + 12
    if t == 0x08:
        return key, bool(data[off]), off + 1
    if t == 0x09 or t == 0x11 or t == 0x12:
        return key, struct.unpack_from("<q", data, off)[0], off + 8
    if t == 0x0A:
        return key, None, off
    if t == 0x10:
        return key, struct.unpack_from("<i", data, off)[0], off + 4
    raise DriverError(f"unsupported BSON type 0x{t:02x}")


def decode_doc(data: bytes, off: int = 0) -> tuple[dict, int]:
    (length,) = struct.unpack_from("<i", data, off)
    end = off + length - 1
    off += 4
    doc: dict = {}
    while off < end:
        key, v, off = _dec_element(data, off)
        doc[key] = v
    return doc, end + 1


# ---------------------------------------------------------------------
# OP_MSG transport


class MongoConn:
    def __init__(self, host: str, port: int = 27017,
                 database: str = "test", timeout: float = 10.0):
        self.database = database
        self._buf = b""
        self._req_id = 0
        self._lock = threading.Lock()
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.sock.settimeout(timeout)
        except OSError:
            raise

    def _recvn(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _abandon(self) -> None:
        try:
            if getattr(self, "sock", None) is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None

    def command(self, doc: dict) -> dict:
        """Run one command document against self.database; returns the
        reply doc. Raises DBError when the server says ok: 0 or returns
        writeErrors."""
        with self._lock:
            if self.sock is None:
                raise DriverError("connection is closed")
            self._req_id += 1
            body = encode_doc({**doc, "$db": self.database})
            payload = struct.pack("<I", 0) + b"\x00" + body  # flags, kind 0
            header = struct.pack("<iiii", 16 + len(payload),
                                 self._req_id, 0, OP_MSG)
            try:
                self.sock.sendall(header + payload)
            except OSError as e:
                self._abandon()
                raise DriverError(f"send failed: {e}") from e
            length, _rid, _rto, opcode = struct.unpack("<iiii",
                                                       self._recvn(16))
            data = self._recvn(length - 16)
            if opcode != OP_MSG:
                self._abandon()
                raise DriverError(f"unexpected opcode {opcode}")
            # flags:4 kind:1 doc
            reply, _ = decode_doc(data, 5)
        if not reply.get("ok"):
            raise DBError(str(reply.get("code", "unknown")),
                          reply.get("errmsg", "command failed"))
        errs = reply.get("writeErrors")
        if errs:
            raise DBError(str(errs[0].get("code", "write")),
                          errs[0].get("errmsg", "write error"))
        return reply

    # convenience wrappers ------------------------------------------------

    def insert(self, coll: str, docs: list[dict],
               write_concern: dict | None = None) -> dict:
        cmd: dict = {"insert": coll, "documents": docs}
        if write_concern:
            cmd["writeConcern"] = write_concern
        return self.command(cmd)

    def find(self, coll: str, filter_: dict | None = None,
             read_concern: dict | None = None) -> list[dict]:
        cmd: dict = {"find": coll, "filter": filter_ or {}}
        if read_concern:
            cmd["readConcern"] = read_concern
        out = self.command(cmd)
        return out.get("cursor", {}).get("firstBatch", [])

    def find_and_modify(self, coll: str, query: dict, update: dict,
                        upsert: bool = False,
                        write_concern: dict | None = None) -> dict:
        cmd: dict = {"findAndModify": coll, "query": query,
                     "update": update, "upsert": upsert, "new": True}
        if write_concern:
            cmd["writeConcern"] = write_concern
        return self.command(cmd)

    def update(self, coll: str, query: dict, update: dict,
               upsert: bool = False,
               write_concern: dict | None = None) -> dict:
        cmd: dict = {"update": coll,
                     "updates": [{"q": query, "u": update,
                                  "upsert": upsert}]}
        if write_concern:
            cmd["writeConcern"] = write_concern
        return self.command(cmd)

    def close(self) -> None:
        self._abandon()


def connect(host: str, port: int = 27017, database: str = "test",
            timeout: float = 10.0) -> MongoConn:
    return MongoConn(host, port, database, timeout)
