"""PostgreSQL wire protocol v3 client (no external deps).

Speaks the simple-query protocol against anything pg-compatible:
PostgreSQL itself, CockroachDB (--insecure => trust auth), and
YugabyteDB's YSQL port. Replaces the jdbc client layer of the
reference's SQL suites (cockroachdb/src/jepsen/cockroach/client.clj:1-60).

Supported auth: trust, cleartext password, md5, SCRAM-SHA-256.
Unsupported: TLS, COPY, extended query protocol — a jepsen client only
ever needs `BEGIN; ...; COMMIT` round-trips, and the simple protocol
pipelines a whole transaction in one message anyway.

Wire format (https://www.postgresql.org/docs/current/protocol.html):
every backend message is `type:1 len:4 payload`, where len includes
itself; the startup message has no type byte.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
from dataclasses import dataclass, field

from . import DBError, DriverError

PROTOCOL_V3 = 196608  # 3 << 16


@dataclass
class Result:
    """One statement's result: column names, text-decoded rows, and the
    CommandComplete tag ("SELECT 3", "INSERT 0 1", ...)."""
    columns: list = field(default_factory=list)
    rows: list = field(default_factory=list)
    tag: str = ""


class PGConn:
    def __init__(self, host: str, port: int = 5432, user: str = "root",
                 database: str = "postgres", password: str | None = None,
                 timeout: float = 10.0, options: dict | None = None):
        self.host, self.port, self.user = host, port, user
        self.database = database
        self._buf = b""
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.sock.settimeout(timeout)
            self._startup(password, options or {})
        except (OSError, DriverError, DBError):
            self._abandon()
            raise

    # ---- low-level framing -------------------------------------------

    def _send(self, type_byte: bytes, payload: bytes) -> None:
        try:
            self.sock.sendall(type_byte +
                              struct.pack("!I", len(payload) + 4) + payload)
        except OSError as e:
            self._abandon()
            raise DriverError(f"send failed: {e}") from e

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_msg(self) -> tuple[bytes, bytes]:
        head = self._recv_exact(5)
        mtype = head[:1]
        (length,) = struct.unpack("!I", head[1:5])
        return mtype, self._recv_exact(length - 4)

    def _abandon(self) -> None:
        try:
            if getattr(self, "sock", None) is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None

    # ---- startup / auth ----------------------------------------------

    def _startup(self, password: str | None, options: dict) -> None:
        params = {"user": self.user, "database": self.database, **options}
        body = b"".join(k.encode() + b"\0" + v.encode() + b"\0"
                        for k, v in params.items()) + b"\0"
        payload = struct.pack("!II", len(body) + 8, PROTOCOL_V3) + body
        try:
            self.sock.sendall(payload)
        except OSError as e:
            raise DriverError(f"startup send failed: {e}") from e
        scram = None
        while True:
            mtype, data = self._recv_msg()
            if mtype == b"R":
                (code,) = struct.unpack("!I", data[:4])
                if code == 0:                     # AuthenticationOk
                    continue
                if code == 3:                     # CleartextPassword
                    self._send(b"p", (password or "").encode() + b"\0")
                elif code == 5:                   # MD5Password
                    salt = data[4:8]
                    inner = hashlib.md5(
                        (password or "").encode() +
                        self.user.encode()).hexdigest()
                    outer = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._send(b"p", b"md5" + outer.encode() + b"\0")
                elif code == 10:                  # SASL
                    mechs = data[4:].split(b"\0")
                    if b"SCRAM-SHA-256" not in mechs:
                        raise DriverError(
                            f"no supported SASL mechanism in {mechs}")
                    scram = _ScramClient(self.user, password or "")
                    first = scram.client_first().encode()
                    self._send(b"p", b"SCRAM-SHA-256\0" +
                               struct.pack("!I", len(first)) + first)
                elif code == 11:                  # SASLContinue
                    assert scram is not None
                    self._send(b"p",
                               scram.client_final(data[4:].decode()).encode())
                elif code == 12:                  # SASLFinal
                    assert scram is not None
                    scram.verify_server(data[4:].decode())
                else:
                    raise DriverError(f"unsupported auth method {code}")
            elif mtype in (b"S", b"K", b"N"):     # ParameterStatus/KeyData
                continue
            elif mtype == b"Z":                   # ReadyForQuery
                return
            elif mtype == b"E":
                raise _error(data)
            else:
                raise DriverError(f"unexpected startup msg {mtype!r}")

    # ---- queries ------------------------------------------------------

    def query(self, sql: str) -> list[Result]:
        """Run one simple-query round trip. `sql` may contain several
        statements separated by ';' — each yields a Result. Raises
        DBError on backend errors, DriverError on transport failure."""
        if self.sock is None:
            raise DriverError("connection is closed")
        self._send(b"Q", sql.encode() + b"\0")
        results: list[Result] = []
        current: Result | None = None
        error: DBError | None = None
        while True:
            mtype, data = self._recv_msg()
            if mtype == b"T":                     # RowDescription
                current = Result(columns=_row_description(data))
            elif mtype == b"D":                   # DataRow
                if current is None:
                    current = Result()
                current.rows.append(_data_row(data))
            elif mtype == b"C":                   # CommandComplete
                if current is None:
                    current = Result()
                current.tag = data.rstrip(b"\0").decode()
                results.append(current)
                current = None
            elif mtype == b"I":                   # EmptyQueryResponse
                results.append(Result())
            elif mtype == b"E":
                error = _error(data)
            elif mtype == b"N":                   # NoticeResponse
                continue
            elif mtype == b"Z":                   # ReadyForQuery
                if error is not None:
                    raise error
                return results
            else:
                self._abandon()
                raise DriverError(f"unexpected msg {mtype!r}")

    def exec(self, sql: str) -> Result:
        """One statement; returns its single Result."""
        res = self.query(sql)
        return res[0] if res else Result()

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._send(b"X", b"")
            except DriverError:
                pass
            self._abandon()


def _row_description(data: bytes) -> list[str]:
    (n,) = struct.unpack("!H", data[:2])
    cols, off = [], 2
    for _ in range(n):
        end = data.index(b"\0", off)
        cols.append(data[off:end].decode())
        off = end + 1 + 18  # tableoid:4 attnum:2 typoid:4 len:2 mod:4 fmt:2
    return cols


def _data_row(data: bytes) -> list:
    (n,) = struct.unpack("!H", data[:2])
    row, off = [], 2
    for _ in range(n):
        (length,) = struct.unpack("!i", data[off:off + 4])
        off += 4
        if length == -1:
            row.append(None)
        else:
            row.append(data[off:off + length].decode())
            off += length
    return row


def _error(data: bytes) -> DBError:
    fields = {}
    for part in data.split(b"\0"):
        if part:
            fields[chr(part[0])] = part[1:].decode(errors="replace")
    return DBError(fields.get("C", "XX000"), fields.get("M", "unknown"))


class _ScramClient:
    """SCRAM-SHA-256 (RFC 5802/7677), channel-binding 'n' (no TLS)."""

    def __init__(self, user: str, password: str):
        self.password = password
        self.nonce = base64.b64encode(os.urandom(18)).decode()
        # pg ignores the SCRAM username (uses the startup user)
        self.first_bare = f"n=,r={self.nonce}"
        self.server_signature: bytes | None = None

    def client_first(self) -> str:
        return "n,," + self.first_bare

    def client_final(self, server_first: str) -> str:
        attrs = dict(p.split("=", 1) for p in server_first.split(","))
        r, s, i = attrs["r"], attrs["s"], int(attrs["i"])
        if not r.startswith(self.nonce):
            raise DriverError("SCRAM server nonce mismatch")
        salted = hashlib.pbkdf2_hmac(
            "sha256", self.password.encode(), base64.b64decode(s), i)
        client_key = hmac.digest(salted, b"Client Key", "sha256")
        stored_key = hashlib.sha256(client_key).digest()
        final_bare = f"c=biws,r={r}"
        auth_msg = ",".join(
            (self.first_bare, server_first, final_bare)).encode()
        client_sig = hmac.digest(stored_key, auth_msg, "sha256")
        proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
        server_key = hmac.digest(salted, b"Server Key", "sha256")
        self.server_signature = hmac.digest(server_key, auth_msg, "sha256")
        return f"{final_bare},p={base64.b64encode(proof).decode()}"

    def verify_server(self, server_final: str) -> None:
        attrs = dict(p.split("=", 1) for p in server_final.split(","))
        if "e" in attrs:
            raise DBError("28P01", f"SCRAM error: {attrs['e']}")
        if base64.b64decode(attrs["v"]) != self.server_signature:
            raise DriverError("SCRAM server signature mismatch")


def connect(host: str, port: int = 5432, user: str = "root",
            database: str = "postgres", password: str | None = None,
            timeout: float = 10.0, **kw) -> PGConn:
    return PGConn(host, port, user, database, password, timeout, **kw)
