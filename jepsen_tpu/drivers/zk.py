"""ZooKeeper wire protocol client (no external deps).

Speaks ZooKeeper's jute-serialized protocol directly — the reference's
zookeeper suite goes through the avout JVM client
(zookeeper/src/jepsen/zookeeper.clj:1-17); here the session handshake
and the four request types a CAS-register workload needs (create,
getData, setData, exists) are hand-framed. setData's version argument
is the CAS primitive: ZooKeeper rejects it with BADVERSION when the
node changed since the read.

Framing: every packet is `len:4` + payload, big-endian. Requests carry
`xid:4 type:4`; replies `xid:4 zxid:8 err:4`. Strings/buffers are
`len:4 bytes` (-1 = null).
"""

from __future__ import annotations

import socket
import struct
import threading

from . import DBError, DriverError

CREATE, DELETE, EXISTS, GETDATA, SETDATA = 1, 2, 3, 4, 5
PING, CLOSE = 11, -11

#: error codes (zookeeper KeeperException)
OK = 0
NONODE = -101
BADVERSION = -103
NODEEXISTS = -110

ERR_NAMES = {NONODE: "no-node", BADVERSION: "bad-version",
             NODEEXISTS: "node-exists"}

def _buf(b: bytes | None) -> bytes:
    if b is None:
        return struct.pack("!i", -1)
    return struct.pack("!i", len(b)) + b


def _string(s: str) -> bytes:
    return _buf(s.encode())


#: world-anyone ACL with all perms (31): one jute ACL entry
OPEN_ACL = (struct.pack("!i", 1) + struct.pack("!i", 31) +
            _string("world") + _string("anyone"))


class Stat:
    """The subset of the jute Stat a CAS register needs."""

    __slots__ = ("version",)

    def __init__(self, version: int):
        self.version = version


class ZKConn:
    def __init__(self, host: str, port: int = 2181,
                 timeout: float = 10.0, session_timeout_ms: int = 10000):
        self._buf = b""
        self._xid = 0
        self._lock = threading.Lock()
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.sock.settimeout(timeout)
            self._connect(session_timeout_ms)
        except (OSError, DriverError, DBError):
            self._abandon()
            raise

    # -- framing --------------------------------------------------------

    def _recvn(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_packet(self) -> bytes:
        (n,) = struct.unpack("!i", self._recvn(4))
        return self._recvn(n)

    def _send_packet(self, payload: bytes) -> None:
        try:
            self.sock.sendall(struct.pack("!i", len(payload)) + payload)
        except OSError as e:
            self._abandon()
            raise DriverError(f"send failed: {e}") from e

    def _abandon(self) -> None:
        try:
            if getattr(self, "sock", None) is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None

    # -- session --------------------------------------------------------

    def _connect(self, session_timeout_ms: int) -> None:
        req = struct.pack("!iqi", 0, 0, session_timeout_ms) + \
            struct.pack("!q", 0) + _buf(b"\0" * 16)
        self._send_packet(req)
        resp = self._recv_packet()
        _ver, timeout_ms, session_id = struct.unpack_from("!iiq", resp, 0)
        if session_id == 0:
            raise DriverError("zookeeper session rejected")
        self.session_id = session_id

    def _call(self, op_type: int, body: bytes) -> bytes:
        """One request/reply; raises DBError on server error codes."""
        with self._lock:
            if self.sock is None:
                raise DriverError("connection is closed")
            self._xid += 1
            xid = self._xid
            self._send_packet(struct.pack("!ii", xid, op_type) + body)
            while True:
                resp = self._recv_packet()
                rxid, _zxid, err = struct.unpack_from("!iqi", resp, 0)
                if rxid == -1:      # watch event notification: skip
                    continue
                if rxid != xid:
                    self._abandon()
                    raise DriverError(
                        f"xid mismatch: sent {xid}, got {rxid}")
                if err != OK:
                    raise DBError(ERR_NAMES.get(err, str(err)),
                                  f"zookeeper error {err}")
                return resp[16:]

    # -- ops ------------------------------------------------------------

    def create(self, path: str, data: bytes,
               ephemeral: bool = False) -> str:
        flags = 1 if ephemeral else 0
        body = _string(path) + _buf(data) + OPEN_ACL + \
            struct.pack("!i", flags)
        out = self._call(CREATE, body)
        (n,) = struct.unpack_from("!i", out, 0)
        return out[4:4 + n].decode()

    def get_data(self, path: str) -> tuple[bytes, Stat]:
        out = self._call(GETDATA, _string(path) + b"\0")  # watch=false
        (n,) = struct.unpack_from("!i", out, 0)
        if n < 0:
            data, off = b"", 4
        else:
            data, off = out[4:4 + n], 4 + n
        # jute Stat: czxid mzxid ctime mtime version ... (version at +32)
        (version,) = struct.unpack_from("!i", out, off + 32)
        return data, Stat(version)

    def set_data(self, path: str, data: bytes,
                 version: int = -1) -> Stat:
        out = self._call(SETDATA, _string(path) + _buf(data) +
                         struct.pack("!i", version))
        (version_,) = struct.unpack_from("!i", out, 32)
        return Stat(version_)

    def exists(self, path: str) -> bool:
        try:
            self._call(EXISTS, _string(path) + b"\0")
            return True
        except DBError as e:
            if e.code == "no-node":
                return False
            raise

    def delete(self, path: str, version: int = -1) -> None:
        self._call(DELETE, _string(path) + struct.pack("!i", version))

    def ping(self) -> None:
        with self._lock:
            if self.sock is None:
                raise DriverError("connection is closed")
            self._send_packet(struct.pack("!ii", -2, PING))
            while True:
                resp = self._recv_packet()
                (rxid,) = struct.unpack_from("!i", resp, 0)
                if rxid == -2:
                    return

    def close(self) -> None:
        if self.sock is not None:
            try:
                with self._lock:
                    self._xid += 1
                    self._send_packet(struct.pack("!ii", self._xid, CLOSE))
            except DriverError:
                pass
            self._abandon()


def connect(host: str, port: int = 2181, timeout: float = 10.0,
            **kw) -> ZKConn:
    return ZKConn(host, port, timeout, **kw)
