"""Minimal AMQP 0-9-1 client for queue workloads (no external deps).

The reference's rabbitmq suite drives RabbitMQ through langohr
(rabbitmq/src/jepsen/rabbitmq.clj:104-175); this client implements just
the slice a jepsen queue workload needs: PLAIN auth, one channel,
queue.declare, basic.publish (with persistent delivery), basic.get,
basic.ack, and queue.purge. Everything is synchronous on one socket.

Frame: type:1 channel:2 size:4 payload 0xCE. Methods are
class-id:2 method-id:2 + packed args; content goes as a header frame
(class:2 weight:2 body-size:8 flags:2 [properties]) + body frames.
"""

from __future__ import annotations

import socket
import struct

from . import DBError, DriverError

FRAME_METHOD, FRAME_HEADER, FRAME_BODY, FRAME_HEARTBEAT = 1, 2, 3, 8
FRAME_END = 0xCE


def _shortstr(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def _longstr(b: bytes) -> bytes:
    return struct.pack("!I", len(b)) + b


def _read_shortstr(data: bytes, off: int) -> tuple[str, int]:
    n = data[off]
    return data[off + 1:off + 1 + n].decode(), off + 1 + n


def _read_longstr(data: bytes, off: int) -> tuple[bytes, int]:
    (n,) = struct.unpack_from("!I", data, off)
    return data[off + 4:off + 4 + n], off + 4 + n


def _skip_table(data: bytes, off: int) -> int:
    (n,) = struct.unpack_from("!I", data, off)
    return off + 4 + n


class AMQPConn:
    def __init__(self, host: str, port: int = 5672,
                 user: str = "guest", password: str = "guest",
                 vhost: str = "/", timeout: float = 10.0):
        self._buf = b""
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
            self.sock.settimeout(timeout)
            self._handshake(user, password, vhost)
        except (OSError, DriverError, DBError):
            self._abandon()
            raise

    # -- framing --------------------------------------------------------

    def _recvn(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_frame(self) -> tuple[int, int, bytes]:
        head = self._recvn(7)
        ftype, channel, size = struct.unpack("!BHI", head)
        payload = self._recvn(size)
        end = self._recvn(1)
        if end[0] != FRAME_END:
            self._abandon()
            raise DriverError("bad frame end octet")
        if ftype == FRAME_HEARTBEAT:
            return self._recv_frame()
        return ftype, channel, payload

    def _recv_method(self) -> tuple[int, int, bytes]:
        """-> (class_id, method_id, args); raises on connection.close /
        channel.close."""
        ftype, _ch, payload = self._recv_frame()
        if ftype != FRAME_METHOD:
            self._abandon()
            raise DriverError(f"expected method frame, got {ftype}")
        cls, mth = struct.unpack_from("!HH", payload, 0)
        args = payload[4:]
        if (cls, mth) in ((10, 50), (20, 40)):   # connection/channel close
            code, off = struct.unpack_from("!H", args, 0)[0], 2
            text, off = _read_shortstr(args, off)
            self._abandon()
            raise DBError(code, text)
        return cls, mth, args

    def _send_frame(self, ftype: int, channel: int,
                    payload: bytes) -> None:
        try:
            self.sock.sendall(struct.pack("!BHI", ftype, channel,
                                          len(payload)) +
                              payload + bytes([FRAME_END]))
        except OSError as e:
            self._abandon()
            raise DriverError(f"send failed: {e}") from e

    def _send_method(self, channel: int, cls: int, mth: int,
                     args: bytes = b"") -> None:
        self._send_frame(FRAME_METHOD, channel,
                         struct.pack("!HH", cls, mth) + args)

    def _abandon(self) -> None:
        try:
            if getattr(self, "sock", None) is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None

    # -- connection negotiation ----------------------------------------

    def _expect(self, cls: int, mth: int) -> bytes:
        rcls, rmth, args = self._recv_method()
        if (rcls, rmth) != (cls, mth):
            self._abandon()
            raise DriverError(
                f"expected method ({cls},{mth}), got ({rcls},{rmth})")
        return args

    def _handshake(self, user: str, password: str, vhost: str) -> None:
        self.sock.sendall(b"AMQP\x00\x00\x09\x01")
        self._expect(10, 10)                       # connection.start
        response = b"\0" + user.encode() + b"\0" + password.encode()
        args = (struct.pack("!I", 0) +             # client-properties {}
                _shortstr("PLAIN") + _longstr(response) +
                _shortstr("en_US"))
        self._send_method(0, 10, 11, args)         # start-ok
        tune = self._expect(10, 30)                # tune
        channel_max, frame_max, heartbeat = struct.unpack_from(
            "!HIH", tune, 0)
        self.frame_max = frame_max or 131072
        self._send_method(0, 10, 31, struct.pack(  # tune-ok (no heartbeat)
            "!HIH", channel_max, self.frame_max, 0))
        self._send_method(0, 10, 40,               # open
                          _shortstr(vhost) + _shortstr("") + b"\0")
        self._expect(10, 41)                       # open-ok
        self._send_method(1, 20, 10, _shortstr(""))  # channel.open
        self._expect(20, 11)                       # channel.open-ok
        self._confirms = False
        self._publish_seq = 0

    def confirm_select(self) -> None:
        """Enter publisher-confirm mode: every publish then blocks until
        the broker acks it — without this, basic.publish is
        fire-and-forget and a lost message would be recorded as an
        acknowledged enqueue."""
        self._send_method(1, 85, 10, b"\0")        # confirm.select
        self._expect(85, 11)                       # select-ok
        self._confirms = True

    # -- queue ops ------------------------------------------------------

    def queue_declare(self, queue: str, durable: bool = True) -> None:
        flags = 0b00010 if durable else 0          # bit1 = durable
        args = (struct.pack("!H", 0) + _shortstr(queue) +
                bytes([flags]) + struct.pack("!I", 0))  # empty args table
        self._send_method(1, 50, 10, args)
        self._expect(50, 11)                       # declare-ok

    def queue_purge(self, queue: str) -> int:
        args = struct.pack("!H", 0) + _shortstr(queue) + b"\0"
        self._send_method(1, 50, 30, args)
        out = self._expect(50, 31)
        return struct.unpack_from("!I", out, 0)[0]

    def publish(self, queue: str, body: bytes,
                persistent: bool = True) -> None:
        args = (struct.pack("!H", 0) + _shortstr("") +  # default exchange
                _shortstr(queue) + b"\0")
        self._send_method(1, 60, 40, args)
        # content header: class 60, weight 0, size, flags: delivery-mode
        props_flags = 0x1000 if persistent else 0  # delivery-mode bit 12
        header = struct.pack("!HHQH", 60, 0, len(body), props_flags)
        if persistent:
            header += bytes([2])                   # delivery-mode = 2
        self._send_frame(FRAME_HEADER, 1, header)
        max_body = self.frame_max - 8
        for i in range(0, len(body), max_body):
            self._send_frame(FRAME_BODY, 1, body[i:i + max_body])
        if self._confirms:
            self._publish_seq += 1
            cls, mth, margs = self._recv_method()
            if (cls, mth) == (60, 120):            # basic.nack
                raise DBError("nack", "broker refused the publish")
            if (cls, mth) != (60, 80):             # basic.ack
                self._abandon()
                raise DriverError(
                    f"expected publish confirm, got ({cls},{mth})")
            (tag,) = struct.unpack_from("!Q", margs, 0)
            if tag != self._publish_seq:
                self._abandon()
                raise DriverError(
                    f"confirm tag {tag} != seq {self._publish_seq}")

    def get(self, queue: str, no_ack: bool = False
            ) -> tuple[int, bytes] | None:
        """basic.get -> (delivery_tag, body) or None when empty."""
        args = (struct.pack("!H", 0) + _shortstr(queue) +
                (b"\1" if no_ack else b"\0"))
        self._send_method(1, 60, 70, args)
        cls, mth, margs = self._recv_method()
        if (cls, mth) == (60, 72):                 # get-empty
            return None
        if (cls, mth) != (60, 71):                 # get-ok
            self._abandon()
            raise DriverError(f"unexpected method ({cls},{mth})")
        (tag,) = struct.unpack_from("!Q", margs, 0)
        ftype, _ch, header = self._recv_frame()
        if ftype != FRAME_HEADER:
            self._abandon()
            raise DriverError("expected content header")
        (size,) = struct.unpack_from("!Q", header, 4)
        body = b""
        while len(body) < size:
            ftype, _ch, chunk = self._recv_frame()
            if ftype != FRAME_BODY:
                self._abandon()
                raise DriverError("expected content body")
            body += chunk
        return tag, body

    def ack(self, delivery_tag: int) -> None:
        self._send_method(1, 60, 80,
                          struct.pack("!Q", delivery_tag) + b"\0")

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._send_method(0, 10, 50,       # connection.close
                                  struct.pack("!H", 200) +
                                  _shortstr("bye") +
                                  struct.pack("!HH", 0, 0))
            except (DriverError, DBError):
                pass
            self._abandon()


def connect(host: str, port: int = 5672, user: str = "guest",
            password: str = "guest", vhost: str = "/",
            timeout: float = 10.0) -> AMQPConn:
    return AMQPConn(host, port, user, password, vhost, timeout)
