"""RESP (REdis Serialization Protocol) client — Disque speaks RESP
(the reference's disque suite uses the jedisque JVM client,
disque/src/jepsen/disque.clj). Commands go as arrays of bulk strings;
replies are simple strings, errors, integers, bulk strings, or arrays.
"""

from __future__ import annotations

import socket

from . import DBError, DriverError


class RespConn:
    def __init__(self, host: str, port: int = 7711,
                 timeout: float = 10.0):
        self._buf = b""
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self.sock.settimeout(timeout)

    def _recvn(self, n: int) -> bytes:
        while len(self._buf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                self._abandon()
                raise DriverError(f"recv failed: {e}") from e
            if not chunk:
                self._abandon()
                raise DriverError("connection closed by server")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _abandon(self) -> None:
        try:
            if getattr(self, "sock", None) is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None

    def _read_reply(self):
        line = self._recv_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            msg = rest.decode()
            code = msg.split(None, 1)[0] if msg else "ERR"
            raise DBError(code, msg)
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n < 0:
                return None
            out = self._recvn(n)
            self._recvn(2)  # trailing \r\n
            return out.decode()
        if t == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        self._abandon()
        raise DriverError(f"bad RESP type byte {t!r}")

    def command(self, *args):
        """Send one command; return the decoded reply."""
        if self.sock is None:
            raise DriverError("connection is closed")
        parts = [f"*{len(args)}\r\n".encode()]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            parts.append(f"${len(b)}\r\n".encode() + b + b"\r\n")
        try:
            self.sock.sendall(b"".join(parts))
        except OSError as e:
            self._abandon()
            raise DriverError(f"send failed: {e}") from e
        return self._read_reply()

    def close(self) -> None:
        self._abandon()


def connect(host: str, port: int = 7711, timeout: float = 10.0
            ) -> RespConn:
    return RespConn(host, port, timeout)
