"""L8: the command-line interface.

Counterpart of jepsen.cli (jepsen/src/jepsen/cli.clj): per-suite mains
call `run_cli(test_fn=...)` to get `test`, `analyze`, and `serve`
subcommands with the standard option set (cli.clj:55-99) and exit codes
(cli.clj:117-127):

    0    test ran and was valid
    1    test ran and was invalid
    2    validity unknown
    254  usage error
    255  crash
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from pathlib import Path
from typing import Callable

from . import core
from .store import Store

log = logging.getLogger(__name__)


def validity_exit_code(results: dict | None) -> int:
    v = (results or {}).get("valid?")
    if v is True:
        return 0
    if v == "unknown" or v is None:
        return 2
    return 1


def add_test_opts(p: argparse.ArgumentParser) -> None:
    p.add_argument("--node", "-n", action="append", dest="nodes",
                   metavar="HOST", help="node to test (repeatable)")
    p.add_argument("--nodes-file", help="file with one node per line")
    p.add_argument("--username", default="root")
    p.add_argument("--password")
    p.add_argument("--port", type=int, default=22)
    p.add_argument("--private-key-path")
    p.add_argument("--dummy", action="store_true",
                   help="use the no-op dummy remote")
    p.add_argument("--concurrency", default="1n",
                   help="worker count; 'Nn' means N per node")
    p.add_argument("--time-limit", type=float, default=60.0,
                   help="seconds of main workload")
    p.add_argument("--test-count", type=int, default=1)
    p.add_argument("--leave-db-running", action="store_true")
    p.add_argument("--store", default="store", help="store directory")


def test_map_from_args(args: argparse.Namespace) -> dict:
    nodes = list(args.nodes or [])
    if args.nodes_file:
        nodes += [ln.strip() for ln in
                  Path(args.nodes_file).read_text().splitlines()
                  if ln.strip()]
    t: dict = {
        "concurrency": args.concurrency,
        "time_limit": args.time_limit,
        "leave_db_running": args.leave_db_running,
        "store": Store(args.store),
        "ssh": {"username": args.username, "password": args.password,
                "port": args.port, "private_key_path": args.private_key_path,
                "dummy": args.dummy},
    }
    if nodes:
        t["nodes"] = nodes
    return t


def run_cli(test_fn: Callable[[dict, argparse.Namespace], dict],
            name: str = "jepsen-tpu", opt_fn=None,
            argv: list[str] | None = None) -> int:
    """Build and dispatch the CLI. `test_fn(base_test, args)` returns the
    full test map; `opt_fn(parser)` may add suite-specific options."""
    parser = argparse.ArgumentParser(prog=name)
    sub = parser.add_subparsers(dest="command", required=True)

    p_test = sub.add_parser("test", help="run a test")
    add_test_opts(p_test)
    if opt_fn:
        opt_fn(p_test)

    p_an = sub.add_parser("analyze",
                          help="re-run the checker on a stored history")
    p_an.add_argument("run_dir", nargs="?",
                      help="store run dir (default: latest)")
    # The same option set as `test` (including --store), so test_fn sees
    # a complete args namespace when rebuilding checkers (cli.clj:381-411).
    add_test_opts(p_an)
    if opt_fn:
        opt_fn(p_an)

    p_serve = sub.add_parser("serve", help="serve the store over HTTP")
    p_serve.add_argument("--port", type=int, default=8080)
    p_serve.add_argument("--host", default="0.0.0.0")
    p_serve.add_argument("--store", default="store")

    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 254 if e.code not in (0, None) else 0

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s")

    try:
        if args.command == "test":
            code = 0
            for i in range(args.test_count):
                test = test_fn(test_map_from_args(args), args)
                test = core.run(test)
                print(json.dumps(
                    {"valid?": test["results"].get("valid?"),
                     "dir": str(test["store"].test_dir(test))}))
                code = max(code, validity_exit_code(test.get("results")))
                if code:
                    break
            return code
        if args.command == "analyze":
            store = Store(args.store)
            run_dir = args.run_dir or store.latest()
            if run_dir is None:
                print("no stored runs", file=sys.stderr)
                return 254
            stored = store.load_test(run_dir)
            test = test_fn(stored, args)
            test.setdefault("name", stored.get("name", "analyze"))
            test["history"] = stored["history"]
            test["store"] = store
            test = core.analyze(test)
            print(json.dumps({"valid?": test["results"].get("valid?")}))
            return validity_exit_code(test["results"])
        if args.command == "serve":
            from . import web
            web.serve(Store(args.store), host=args.host, port=args.port)
            return 0
        return 254
    except KeyboardInterrupt:
        return 255
    except Exception:
        log.exception("fatal error")
        return 255
