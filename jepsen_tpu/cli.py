"""L8: the command-line interface.

Counterpart of jepsen.cli (jepsen/src/jepsen/cli.clj): per-suite mains
call `run_cli(test_fn=...)` to get `test`, `analyze`, and `serve`
subcommands with the standard option set (cli.clj:55-99) and exit codes
(cli.clj:117-127):

    0    test ran and was valid
    1    test ran and was invalid
    2    validity unknown
    254  usage error
    255  crash
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from pathlib import Path
from typing import Callable

from . import core, gates, trace
from .store import Store

log = logging.getLogger(__name__)


def validity_exit_code(results: dict | None) -> int:
    v = (results or {}).get("valid?")
    if v is True:
        return 0
    if v == "unknown" or v is None:
        return 2
    return 1


def add_test_opts(p: argparse.ArgumentParser) -> None:
    p.add_argument("--node", "-n", action="append", dest="nodes",
                   metavar="HOST", help="node to test (repeatable)")
    p.add_argument("--nodes-file", help="file with one node per line")
    p.add_argument("--username", default="root")
    p.add_argument("--password")
    p.add_argument("--port", type=int, default=22)
    p.add_argument("--private-key-path")
    p.add_argument("--dummy", action="store_true",
                   help="use the no-op dummy remote")
    p.add_argument("--concurrency", default="1n",
                   help="worker count; 'Nn' means N per node")
    p.add_argument("--time-limit", type=float, default=60.0,
                   help="seconds of main workload")
    p.add_argument("--test-count", type=int, default=1)
    p.add_argument("--leave-db-running", action="store_true")
    p.add_argument("--store", default="store", help="store directory")
    p.add_argument("--faults", default=None,
                   help="comma list for the combined nemesis bundle "
                        "(partition,kill,pause,clock) — swaps the "
                        "suite's default nemesis for the composed "
                        "package (combined.clj:318-364)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "tpu", "cpu", "race"],
                   help="analysis backend: device kernels (tpu), host "
                        "oracles (cpu), or pick by hardware (auto — "
                        "the default; the north star's :backend :tpu "
                        "is the production path when a chip is up)")
    add_trace_opts(p)


def add_trace_opts(p: argparse.ArgumentParser) -> None:
    p.add_argument("--trace", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="write trace.json (Chrome trace-event / "
                        "Perfetto) + metrics.json into the run dir "
                        "(default on; --no-trace or JEPSEN_TPU_TRACE=0 "
                        "disables)")
    p.add_argument("--jax-profile",
                   action=argparse.BooleanOptionalAction, default=None,
                   help="additionally capture a jax.profiler session "
                        "of the run (sets JEPSEN_TPU_JAX_PROFILE; "
                        "lands in <run-dir>/jax-profile; "
                        "--no-jax-profile overrides an inherited env)")


def apply_trace_opts(args: argparse.Namespace) -> None:
    """Export --trace/--no-trace/--jax-profile to the env gates every
    layer reads (JEPSEN_TPU_TRACE / JEPSEN_TPU_JAX_PROFILE), so
    embedded callers and subprocesses see the same choice."""
    if getattr(args, "trace", None) is not None:
        gates.export("JEPSEN_TPU_TRACE", args.trace)
        trace.reset()
    if getattr(args, "jax_profile", None) is not None:
        gates.export("JEPSEN_TPU_JAX_PROFILE", args.jax_profile)


def _trace_path_of(test: dict) -> str | None:
    """The run's written trace.json path (None when tracing is off)."""
    try:
        p = test["store"].test_dir(test) / "trace.json"
        return str(p) if p.exists() else None
    except Exception:
        return None


def _print_result_line(test: dict, line: dict) -> None:
    """The one-line JSON result every run-style subcommand prints,
    with the run's written trace.json path attached when one exists."""
    tp = _trace_path_of(test)
    if tp:
        line["trace"] = tp
    print(json.dumps(line))


def test_map_from_args(args: argparse.Namespace) -> dict:
    nodes = list(args.nodes or [])
    if args.nodes_file:
        nodes += [ln.strip() for ln in
                  Path(args.nodes_file).read_text().splitlines()
                  if ln.strip()]
    t: dict = {
        "backend": getattr(args, "backend", "auto"),
        "concurrency": args.concurrency,
        **({"faults": [f.strip() for f in args.faults.split(",")
                       if f.strip()]}
           if getattr(args, "faults", None) else {}),
        "time_limit": args.time_limit,
        "leave_db_running": args.leave_db_running,
        "store": Store(args.store),
        "ssh": {"username": args.username, "password": args.password,
                "port": args.port, "private_key_path": args.private_key_path,
                "dummy": args.dummy},
    }
    if nodes:
        t["nodes"] = nodes
    return t


def run_cli(test_fn: Callable[[dict, argparse.Namespace], dict],
            name: str = "jepsen-tpu", opt_fn=None,
            argv: list[str] | None = None,
            tests_fn: Callable[[dict, argparse.Namespace], list] | None
            = None) -> int:
    """Build and dispatch the CLI. `test_fn(base_test, args)` returns the
    full test map; `opt_fn(parser)` may add suite-specific options;
    `tests_fn(base_test, args)` returns the list of test maps run by the
    `test-all` subcommand (defaults to the single test_fn test)."""
    parser = argparse.ArgumentParser(prog=name)
    sub = parser.add_subparsers(dest="command", required=True)

    p_test = sub.add_parser("test", help="run a test")
    add_test_opts(p_test)
    if opt_fn:
        opt_fn(p_test)

    p_an = sub.add_parser("analyze",
                          help="re-run the checker on a stored history")
    p_an.add_argument("run_dir", nargs="?",
                      help="store run dir (default: latest)")
    # The same option set as `test` (including --store), so test_fn sees
    # a complete args namespace when rebuilding checkers (cli.clj:381-411).
    add_test_opts(p_an)
    if opt_fn:
        opt_fn(p_an)

    p_all = sub.add_parser(
        "test-all",
        help="run a whole suite of tests (cli.clj:413-491's test-all)")
    add_test_opts(p_all)
    if opt_fn:
        opt_fn(p_all)

    p_batch = sub.add_parser(
        "analyze-store",
        help="batch re-check every stored run on the device mesh "
             "(the north-star batch path)")
    p_batch.add_argument("--store", default="store")
    p_batch.add_argument("--checker", default="append",
                         choices=["append", "wr", "register", "stored"],
                         help="append/wr: encode histories and batch-"
                              "check on the mesh; register: per-key "
                              "CAS linearizability, every key of every "
                              "run in one dense-kernel sweep; stored: "
                              "re-run each run's own checker")
    p_batch.add_argument("--name", default=None,
                         help="only runs of this test name")
    p_batch.add_argument("--backend", default="auto",
                         choices=["auto", "tpu", "cpu", "race"])
    p_batch.add_argument("--resume", action="store_true",
                         help="continue an interrupted sweep: skip "
                              "runs this checker already verdicted "
                              "(results.json naming the checker, or "
                              "the fallback's .sweep-* sidecar)")
    p_batch.add_argument("--report", action="store_true",
                         help="write the critical-path attribution "
                              "report (<store>/report.json + "
                              "report.md) from the merged sweep "
                              "timeline at exit (JEPSEN_TPU_REPORT=1 "
                              "is the env equivalent; needs tracing "
                              "on)")
    p_batch.add_argument("--mesh", action="store_true",
                         help="run as ONE SHARD of a multi-host mesh "
                              "sweep (JEPSEN_TPU_MESH=1 is the env "
                              "equivalent): deterministic shard of "
                              "the run dirs, per-shard "
                              "verdicts-<shard>.jsonl journal + "
                              "trace-shard<k>.json artifacts, "
                              "coordinator merge on shard 0; shard "
                              "identity from JEPSEN_TPU_MESH_SHARD/"
                              "_SHARDS or the jax.distributed job")
    add_trace_opts(p_batch)

    p_serve = sub.add_parser(
        "serve",
        help="run the multi-tenant verdict daemon: tenants stream "
             "histories over a local socket and get verdicts back "
             "while their tests run (continuous batching, per-tenant "
             "fairness, journaled verdicts; analyze-store remains the "
             "batch path). --web serves the legacy HTTP store browser "
             "instead.")
    p_serve.add_argument("--port", type=int, default=None,
                         help="TCP port for the daemon (default: unix "
                              "socket <store>/serve.sock); with --web, "
                              "the HTTP port (default 8080)")
    p_serve.add_argument("--host", default=None,
                         help="bind address (default 127.0.0.1 for "
                              "the daemon, the historical 0.0.0.0 "
                              "for --web)")
    p_serve.add_argument("--store", default="store")
    p_serve.add_argument("--socket", default=None,
                         help="unix-socket path the daemon listens on "
                              "(default <store>/serve.sock; "
                              "JEPSEN_TPU_SERVE_SOCKET is the env "
                              "equivalent)")
    p_serve.add_argument("--drain-timeout", type=float, default=None,
                         help="seconds to drain admitted work on "
                              "SIGTERM (default "
                              "JEPSEN_TPU_SERVE_DRAIN_S)")
    p_serve.add_argument("--web", action="store_true",
                         help="serve the legacy HTTP store browser "
                              "instead of the verdict daemon")
    p_serve.add_argument("--fleet-instance", type=int, default=None,
                         help="run as member <k> of a serve fleet "
                              "(the `fleet` subcommand spawns these): "
                              "bind fleet-d<k>.sock, heartbeat the "
                              "fleet-d<k>.json beacon, honor the "
                              "epoch fence")
    p_serve.add_argument("--fleet-epoch", type=int, default=None,
                         help="the membership epoch this member was "
                              "started under (the fleet router sets "
                              "it)")
    add_trace_opts(p_serve)

    p_fleet = sub.add_parser(
        "fleet",
        help="run N verdict daemons behind a fault-tolerant router: "
             "tenants connect to one fleet socket; the router "
             "hash-affines them to daemons, spills on backpressure, "
             "and on a daemon death replays its tenants' journals on "
             "a successor (zero lost or duplicated verdicts)")
    p_fleet.add_argument("--store", default="store")
    p_fleet.add_argument("--daemons", type=int, default=3,
                         help="fleet size (default 3)")
    p_fleet.add_argument("--socket", default=None,
                         help="router socket path (default "
                              "<store>/fleet.sock)")
    p_fleet.add_argument("--no-stonith", action="store_true",
                         help="skip the router's best-effort SIGKILL "
                              "of a daemon it declares dead (nemesis "
                              "harnesses that manage the process "
                              "themselves set this)")
    add_trace_opts(p_fleet)

    from . import lint as _lint   # stdlib-only, import-cheap
    p_lint = sub.add_parser(
        "lint",
        help="self-hosted static analysis (gate registry, JAX "
             "hazards, concurrency, shm lifecycle, tracer discipline)")
    _lint.add_args(p_lint)

    from .obs import bench_report as _breport   # stdlib-only
    p_breport = sub.add_parser(
        "bench-report",
        help="bench-trajectory trend table + regression gate over the "
             "BENCH_*.json series (exit 1 when the latest round "
             "regresses past a declared threshold)")
    _breport.add_args(p_breport)

    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 254 if e.code not in (0, None) else 0

    if args.command == "lint":
        # no logging/backend/trace setup: lint parses source, it never
        # imports or executes the target package
        return _lint.run_from_args(args)
    if args.command == "bench-report":
        # same posture as lint: reads artifacts, never touches jax
        return _breport.run_from_args(args)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s")

    # Hung sweeps must be debuggable in production: SIGUSR1 dumps
    # every thread's stack (faulthandler) without killing the process
    # — `kill -USR1 <pid>` answers "where is it stuck" on a wedged
    # device wait or a parked pool. Best-effort: unavailable off the
    # main thread and on platforms without SIGUSR1.
    try:
        import faulthandler
        import signal as _signal
        # the REAL stderr fd: sys.stderr may be a captured/fileno-less
        # wrapper (pytest, some embedders), which faulthandler rejects
        faulthandler.register(_signal.SIGUSR1, all_threads=True,
                              chain=True, file=sys.__stderr__)
    except (AttributeError, ValueError, OSError, ImportError):
        pass

    # Every auto-backend checker constructed from here on resolves per
    # this process-wide choice (devices.resolve_backend).
    if getattr(args, "backend", None) and args.backend != "auto":
        gates.export("JEPSEN_TPU_BACKEND", args.backend)
    apply_trace_opts(args)

    try:
        if args.command == "test":
            code = 0
            for i in range(args.test_count):
                test = test_fn(test_map_from_args(args), args)
                test = core.run(test)
                _print_result_line(test, {
                    "valid?": test["results"].get("valid?"),
                    "dir": str(test["store"].test_dir(test))})
                code = max(code, validity_exit_code(test.get("results")))
                if code:
                    break
            return code
        if args.command == "analyze":
            store = Store(args.store)
            run_dir = args.run_dir or store.latest()
            if run_dir is None:
                print("no stored runs", file=sys.stderr)
                return 254
            stored = store.load_test(run_dir)
            test = test_fn(stored, args)
            test.setdefault("name", stored.get("name", "analyze"))
            from . import independent
            # json/edn round trips erase the lifted-tuple type; re-lift
            # so per-key checkers split the history again
            test["history"] = independent.relift_history(
                stored["history"])
            test["store"] = store
            trace.fresh_run(test.get("name"))
            with trace.jax_profile_session(
                    Path(run_dir) / "jax-profile"):
                test = core.analyze(test)
            _print_result_line(test,
                               {"valid?": test["results"].get("valid?")})
            return validity_exit_code(test["results"])
        if args.command == "test-all":
            tests = (tests_fn(test_map_from_args(args), args)
                     if tests_fn is not None
                     else [test_fn(test_map_from_args(args), args)])
            worst = 0
            for test in tests:
                try:
                    test = core.run(test)
                    code = validity_exit_code(test.get("results"))
                    _print_result_line(test, {
                        "name": test.get("name"),
                        "valid?": test["results"].get("valid?"),
                        "dir": str(test["store"].test_dir(test))})
                except Exception as e:
                    log.exception("test %s crashed", test.get("name"))
                    print(json.dumps({"name": test.get("name"),
                                      "error": str(e)}))
                    code = 255
                worst = max(worst, code)
            return worst
        if args.command == "analyze-store":
            if args.mesh:
                # flag→env export, like --backend: embedded callers
                # and subprocesses of this sweep see the same choice
                gates.export("JEPSEN_TPU_MESH", True)
            return analyze_store(Store(args.store), checker=args.checker,
                                 name=args.name, resume=args.resume,
                                 report=args.report or None,
                                 mesh=args.mesh or None)
        if args.command == "serve":
            if args.web:
                from . import web
                web.serve(Store(args.store),
                          host=args.host or "0.0.0.0",
                          port=args.port if args.port is not None
                          else 8080)
                return 0
            from .serve import run_daemon
            return run_daemon(Store(args.store),
                              socket_path=args.socket, port=args.port,
                              host=args.host or "127.0.0.1",
                              drain_s=args.drain_timeout,
                              fleet_instance=args.fleet_instance,
                              fleet_epoch=args.fleet_epoch)
        if args.command == "fleet":
            from .serve.fleet import run_fleet
            return run_fleet(Store(args.store), daemons=args.daemons,
                             socket_path=args.socket,
                             stonith=not args.no_stonith)
        return 254
    except KeyboardInterrupt:
        return 255
    except Exception:
        log.exception("fatal error")
        return 255


def analyze_store(store: Store, checker: str = "append",
                  name: str | None = None,
                  resume: bool = False, obs_hook=None,
                  report: bool | None = None,
                  mesh: bool | None = None) -> int:
    """`_analyze_store_impl` wrapped in a fresh sweep tracer: the whole
    sweep's spans (ingest parse, pack/h2d/dispatch/collect phases,
    device windows, per-checker fallbacks) export to
    `<store>/trace.json` + `metrics.json` at exit, printing the path —
    the sweep-level analogue of the per-run artifacts save_2 writes.
    Since the trace fabric the exported trace.json is MERGED: each
    pool worker's span spool (`trace-<pid>.jsonl`, written per task)
    folds in as its own real-pid process track, so encode time is
    visible per worker, not inferred from parent stalls. With
    `report` (the `--report` flag; None defers to JEPSEN_TPU_REPORT)
    the critical-path attribution report (`report.json` +
    `report.md`) is derived from the same merged timeline.

    Sweep start also reclaims /dev/shm segments a previous crashed
    run's dead pid left behind (`shm_stale_reclaimed` counter), and
    every verdict appends to the store's `verdicts.jsonl` journal as
    it lands — `--resume` reads it back and skips the journaled
    (run, checker) pairs, so an interrupted sweep restarts where it
    died.

    Live telemetry (jepsen_tpu.obs) wraps the whole sweep: the flight
    recorder (`<store>/events.jsonl`) always records the lifecycle;
    `JEPSEN_TPU_HEALTH_INTERVAL_S` additionally starts the health
    sampler (`<store>/health.json`, atomic, every N s) and
    `JEPSEN_TPU_METRICS_PORT` the `/metrics`+`/healthz` endpoint —
    both off by default, costing nothing when unset. `obs_hook(server,
    sampler)` is a test/smoke seam called once the obs layer is up.

    With `mesh` (the `--mesh` flag; None defers to JEPSEN_TPU_MESH)
    this process sweeps ONE SHARD of a multi-host mesh sweep
    (jepsen_tpu.mesh): a deterministic hash-split of the run dirs,
    journaled to `verdicts-<shard>.jsonl` (resume stays strictly
    per-shard), dispatched on this host's LOCAL devices via the same
    warm path, traced to `trace-shard<k>.json` with the shard id in
    every track name; the coordinator (shard 0) then merges journals,
    traces, metrics and — with `report` — the per-shard attribution
    report once the fleet's done markers land. A shard that never
    reports is LOST (exit ≥2, runs unverdicted) and re-assignable
    with `JEPSEN_TPU_MESH_SHARD=<k> --mesh --resume` — the
    supervisor's degradation contract at fleet scale."""
    from . import mesh as meshmod
    from . import obs
    from . import shm as _shm
    from . import supervisor as sv
    from .obs import device as device_obs
    from .obs import search as search_obs
    from .store import VerdictJournal, analytics_path, costdb_path
    if report is None:
        report = gates.get("JEPSEN_TPU_REPORT")
    if mesh is None:
        mesh = meshmod.mesh_enabled()
    shard = n_shards = None
    run_name = f"analyze-store:{checker}"
    if mesh:
        shard, n_shards = meshmod.resolve_shard()
        # the shard id rides the tracer's run name, so every process
        # track of this shard's trace carries it after the merge
        run_name = f"{run_name}@shard{shard}/{n_shards}"
    tr = trace.fresh_run(run_name, scope="sweep")
    # the device cost observatory is per-sweep state like the tracer:
    # a fresh sweep must not inherit a previous sweep's records or
    # half-open dispatch windows (no-op-cheap; gate read at capture)
    device_obs.reset()
    # so is the kernel search-telemetry ledger (JEPSEN_TPU_KERNEL_STATS)
    search_obs.reset()
    # the cost-aware planner is per-sweep state too: load the store's
    # persisted plan.json (warm start) or run cold — a no-op with
    # JEPSEN_TPU_PLANNER off
    from . import planner as planner_mod
    planner_mod.activate(store.base)
    if getattr(tr, "enabled", False) and store.base.is_dir():
        # point the worker trace fabric at the store: pool workers
        # spool spans to <spool_dir>/trace-<pid>.jsonl; stale spools
        # from a previous sweep are derived artifacts keyed by trace
        # id — cleared here so the dir holds exactly this sweep's set.
        # Mesh shards share the store CONCURRENTLY and two hosts'
        # workers can even share a pid, so each shard owns its own
        # spool subdirectory (trace.shard_spool_dir) — cleaning it
        # can't race a sibling, and spool names can't collide.
        sd = store.base if not mesh \
            else trace.shard_spool_dir(store.base, shard)
        sd.mkdir(exist_ok=True)
        trace.clean_spools(sd)
        tr.spool_dir = sd
    elif report:
        print("attribution report needs tracing on "
              "(JEPSEN_TPU_TRACE=0 set); skipping", file=sys.stderr)
    tr.counter("shm_stale_reclaimed").inc(_shm.reclaim_stale())
    journal = VerdictJournal(
        meshmod.shard_journal_path(store.base, shard) if mesh
        else store.base / "verdicts.jsonl", base=store.base)
    if mesh and store.base.is_dir():
        sv.mark_shard_start(store.base, shard)
    obs.install_events(store.base)
    obs.emit("sweep_start", checker=checker, resume=bool(resume),
             store=str(store.base),
             **({"shard": shard, "shards": n_shards} if mesh else {}))
    sampler = obs.maybe_start_health_sampler(store.base)
    server = obs.maybe_start_metrics_server(
        health_fn=(sampler.write_snapshot if sampler is not None
                   else None))
    rc: int | None = None
    try:
        if obs_hook is not None:
            obs_hook(server, sampler)
        with trace.jax_profile_session(store.base / "jax-profile"):
            rc = _analyze_store_impl(store, checker=checker,
                                     name=name, resume=resume,
                                     journal=journal, shard=shard,
                                     n_shards=n_shards)
    finally:
        journal.close()
        obs.emit("sweep_end",
                 exit_code=rc if rc is not None else "crashed")
        if sampler is not None:
            sampler.stop()
        if server is not None:
            server.stop()
        if store.base.is_dir():
            # the costdb lands whether or not tracing was on: the
            # observatory's windows are measured with perf_counter
            # directly, and the planner's training data must not
            # depend on the trace gate. flush() is a no-op (zero
            # files) with JEPSEN_TPU_COSTDB off. It runs BEFORE
            # reset_events so its costdb_flush mark reaches the
            # flight recorder.
            try:
                n_cost = device_obs.flush(
                    costdb_path(store.base, shard if mesh else None))
                if n_cost:
                    print(f"costdb: {n_cost} record(s) appended to "
                          f"{costdb_path(store.base, shard if mesh else None)}",
                          file=sys.stderr)
            except Exception:
                log.warning("costdb flush failed", exc_info=True)
            # the analytics ledger follows the same contract: journal
            # before reset_events so its flight-recorder mark lands;
            # zero files with the gate off
            try:
                n_stats = search_obs.flush(
                    analytics_path(store.base,
                                   shard if mesh else None))
                if n_stats:
                    print(f"analytics: {n_stats} record(s) appended "
                          f"to "
                          f"{analytics_path(store.base, shard if mesh else None)}",
                          file=sys.stderr)
            except Exception:
                log.warning("analytics flush failed", exc_info=True)
            # sweep-end planner refit from the full on-disk tables
            # (this sweep's fresh records included): plan.json is what
            # the NEXT sweep and the daemon warm-start from. Mesh
            # shards skip it — the coordinator refits once over the
            # merged fleet tables instead.
            if not mesh and planner_mod.enabled():
                try:
                    from .store import load_analytics, load_costdb
                    plan = planner_mod.refresh(
                        store.base,
                        load_costdb(costdb_path(store.base)),
                        load_analytics(analytics_path(store.base)))
                    if plan is not None:
                        print(f"planner: plan.json refit from "
                              f"{plan['trained_records']} record(s)",
                              file=sys.stderr)
                except Exception:
                    log.warning("planner refresh failed",
                                exc_info=True)
        obs.reset_events()
        if getattr(tr, "enabled", False) and store.base.is_dir():
            try:
                # the merged export: parent events + every worker
                # spool of THIS sweep (from this sweep's own spool
                # dir), one real-pid track per worker
                evs = trace.merge_traces(tr)
                if mesh:
                    # a resume that re-checked nothing records no
                    # timed events: keep the PREVIOUS shard trace —
                    # it is still the evidence for how this shard's
                    # journaled verdicts were produced, and the
                    # coordinator's per-shard attribution needs it
                    timed = any(e.get("ph") != "M" for e in evs)
                    sp = trace.shard_trace_path(store.base, shard)
                    if timed or not sp.exists():
                        p = trace.export_shard_trace(
                            tr, store.base, shard, n_shards, evs)
                        tr.export_metrics(
                            store.base / f"metrics-shard{shard}.json")
                        print(f"shard trace written to {p}",
                              file=sys.stderr)
                    else:
                        print(f"shard {shard}: no new events; "
                              f"keeping {sp}", file=sys.stderr)
                else:
                    p = trace.atomic_write_text(
                        store.base / "trace.json",
                        json.dumps({"traceEvents": evs,
                                    "displayTimeUnit": "ms"}))
                    tr.export_metrics(store.base / "metrics.json")
                    print(f"trace written to {p}", file=sys.stderr)
                    if report:
                        from .obs import attribution
                        rj, _rmd = attribution.write_report(
                            store.base, evs, tr.metrics_dict(),
                            device_records=(device_obs.records()
                                            if device_obs.enabled()
                                            else None),
                            search_records=(search_obs.records()
                                            if search_obs.enabled()
                                            else None))
                        print(f"report written to {rj}",
                              file=sys.stderr)
            except Exception:
                log.warning("sweep trace export failed", exc_info=True)
        if mesh and store.base.is_dir():
            # the done marker is the LAST artifact: a coordinator that
            # sees it may merge this shard's journal + trace right away
            sv.mark_shard_done(store.base, shard, {
                "shard": shard, "shards": n_shards, "checker": checker,
                "exit_code": rc if rc is not None else "crashed"})
    if mesh:
        return meshmod.coordinator_merge(store, checker, shard,
                                         n_shards, rc, report=report,
                                         tracer=tr, name=name)
    return rc


def _analyze_store_impl(store: Store, checker: str = "append",
                        name: str | None = None,
                        resume: bool = False,
                        journal=None, shard: int | None = None,
                        n_shards: int | None = None) -> int:
    """Batch re-check every stored run — the north-star batch path
    (SURVEY.md §3.4, §7 stage 8): encodable histories are packed,
    length-bucketed, and dispatched across the device mesh in one sweep;
    the rest (or --checker stored) re-run their own checker host-side.

    Writes `results.json`/`results.edn` into each run dir and prints one
    JSON summary line per run. Exit code: worst validity across runs.
    With `shard`/`n_shards` (a mesh sweep) only this shard's
    deterministic slice of the run dirs is walked — the store iterator
    applies the hash split during the (lazy) listing itself, so no
    host ever builds the other shards' run list."""
    from .store import VerdictJournal
    run_dirs = list(store.iter_run_dirs(
        name=name, shard=shard,
        n_shards=n_shards if n_shards is not None else 1))
    prior_worst = 0
    if resume:
        # resumable analysis (SURVEY.md §5.4): skip runs THIS sweep
        # already verdicted — journaled in verdicts.jsonl (appended
        # per history as results land, so it survives a SIGKILL of
        # the sweep) or carrying the per-run marker (which records
        # which checker wrote it, so an append sweep never masks a
        # pending wr sweep). Skipped runs still contribute their
        # recorded validity to the exit code — an invalid verdict
        # from the completed part of an interrupted sweep must not
        # read as success.
        # per-shard resume reads THIS shard's journal only (the
        # journal threaded in is already verdicts-<shard>.jsonl):
        # cross-host resume must never read — or race — another
        # shard's evidence
        journaled = VerdictJournal.load(
            journal.path if journal is not None
            else store.base / "verdicts.jsonl")
        rel = journal.rel if journal is not None else str
        pending = []
        for d in run_dirs:
            ent = journaled.get((rel(d), checker))
            if _verdicted(d, checker):
                prior_worst = max(prior_worst, _prior_code(d, checker))
            elif ent is not None:
                prior_worst = max(prior_worst,
                                  validity_exit_code(ent))
            else:
                pending.append(d)
        from . import obs
        obs.emit("sweep_resume", skipped=len(run_dirs) - len(pending),
                 pending=len(pending))
        if not pending and run_dirs:
            print(f"all {len(run_dirs)} runs already verdicted "
                  f"({checker}); nothing to resume", file=sys.stderr)
            return prior_worst
        run_dirs = pending
    if not run_dirs:
        if shard is not None \
                and next(store.iter_run_dirs(name=name), None) \
                is not None:
            # a legitimate mesh assignment, not a usage error: the
            # hash split left this shard nothing (tiny store, many
            # shards) — the shard completes empty so the coordinator
            # can still merge the fleet
            print(f"shard {shard}/{n_shards}: no runs assigned",
                  file=sys.stderr)
            return prior_worst
        print("no stored runs", file=sys.stderr)
        return 254
    # live-telemetry progress denominators: the health sampler reads
    # these from the sweep tracer (runs_verdicted ticks per verdict)
    trace.get_current().gauge("runs_total").set(len(run_dirs))

    # multi-host pods: join the job before any device work so meshes
    # span every host's chips (no-op without a coordinator env)
    if checker != "stored":
        from . import parallel as _parallel
        try:
            _parallel.init_distributed()
        except Exception:
            log.warning("jax.distributed init failed; continuing "
                        "single-process", exc_info=True)

    def stored_check(d) -> dict:
        stored = store.load_test(d)
        test = dict(stored)
        test["store"] = store
        return core.analyze(test)["results"]

    def emit(d, res):
        return _write_results(d, res, checker, journal=journal)

    worst = prior_worst
    if checker == "stored":
        for d in run_dirs:
            worst = max(worst,
                        _stored_fallback(d, stored_check, "stored",
                                         journal=journal))
        return worst

    if checker == "register":
        return max(prior_worst,
                   _analyze_store_register(store, run_dirs,
                                           stored_check,
                                           journal=journal))

    from . import parallel
    from .checker import elle
    from .checker.elle import kernels as elle_kernels
    from .checker.elle import wr as elle_wr
    import os as _os

    # An EXPLICIT --backend cpu (the dispatcher exports it) routes the
    # sweep through the host oracle. Auto stays on the batched kernels:
    # they run on whatever devices exist — that's the north-star sweep,
    # and on CPU-only hosts it doubles as the virtual-mesh dryrun.
    host_only = gates.get("JEPSEN_TPU_BACKEND") == "cpu"

    # Kernel search telemetry (JEPSEN_TPU_KERNEL_STATS): dispatches
    # additionally return per-history stats rows, recorded into the
    # per-sweep ledger keyed by the SAME store-relative dir string the
    # verdict journal uses. The host-oracle sweep runs no kernels, so
    # it records nothing for the elle checkers.
    from .obs import search as search_obs
    want_stats = search_obs.enabled() and not host_only
    _rel = journal.rel if journal is not None else str

    def record_stats(d, checker_name: str, sd, cycles=None) -> None:
        if sd is not None:
            search_obs.record(
                _rel(d), checker_name, sd,
                anomalies=(cycles if isinstance(cycles, dict)
                           else None))

    # Encodable histories get the batched device sweep; the rest fall
    # back to their own stored checker host-side. Ingest shards run
    # dirs across a process pool (ingest.py, SURVEY.md §5.7).
    from . import ingest

    def encodable(d, enc, fallback: list) -> bool:
        """Shared triage, with per-history isolation: a run whose
        encode raised (the pool returns the per-run exception) gets
        ONE more chance through its own stored checker — a wr sweep
        over an append-shaped store is unencodable yet perfectly
        checkable — and if that fails too, `_stored_fallback`
        quarantines it as a `valid? unknown` verdict instead of
        killing the sweep (JEPSEN_TPU_STRICT=1 restores fail-fast).
        A self-nemesis InjectedFault skips the detour: the injection
        simulates a poisoned history, whose terminal state IS
        quarantine. Txn-less histories are no failure at all and
        route to the stored checker as before."""
        nonlocal worst
        if isinstance(enc, Exception):
            from . import supervisor
            if isinstance(enc, supervisor.InjectedFault):
                worst = max(worst, _quarantine_run(d, enc, "encode",
                                                   checker,
                                                   journal=journal))
                return False
            log.info("run %s not encodable as %s (%r); using stored "
                     "checker", d, checker, enc)
            fallback.append(d)
            return False
        if enc.n == 0:   # no txn ops at all: not a txn workload
            fallback.append(d)
            return False
        return True

    # Pipelining decision passed DOWN to iter_encode_chunks, not via
    # process-global env (a later sweep or embedded caller must not
    # inherit a stale accelerator probe). None = let ingest decide.
    sweep_procs = None
    if not host_only:
        from . import devices as devmod
        if devmod.accelerator_available():   # probe-bounded, jax-free
            # overlap pays even on a single-core host when a real
            # device runs the checks: the worker parses while the
            # parent blocks on the accelerator (append AND wr sweeps)
            sweep_procs = max(1, _os.cpu_count() or 1)

    if checker == "append":
        # Mesh built lazily on the FIRST dense dispatch: an
        # all-fallback store (non-txn workloads) must never pay — or
        # hang in — device init it doesn't need.
        mesh_box: list = []

        def get_mesh():
            if not mesh_box:
                try:
                    # a mesh-sweep shard dispatches on ITS OWN host's
                    # chips only: the cross-host axis is the shard
                    # split of run dirs, never a global dispatch mesh
                    mesh_box.append(parallel.host_local_mesh()
                                    if shard is not None
                                    else parallel.make_mesh())
                except Exception:
                    mesh_box.append(None)
            return mesh_box[0]

        # The checker class's own defaults, so batch verdicts match
        # single-run verdicts for the same history.
        prohibited = elle.AppendChecker().prohibited

        def emit_append(d, enc, cycles):
            from . import supervisor
            if isinstance(cycles, supervisor.Quarantined):
                # the dispatcher abandoned this history (OOM backdown
                # exhausted / watchdog) — already counted + span'd at
                # the quarantine site; persist the unknown verdict
                return emit(d, cycles.verdict("append"))
            res = elle.render_verdict(enc, cycles, prohibited)
            res["checker"] = "append"   # --resume marker
            return emit(d, res)

        fallback, huge, huge_map = [], [], []
        # Streaming ingest/check pipeline: each chunk's device sweep
        # overlaps the pool workers' parsing of the NEXT chunk, so
        # device time hides under ingest on stores big enough to
        # matter (SURVEY.md §5.7; the bench's north-star block uses
        # the same loop). Verdicts persist PER CHUNK: an interrupted
        # sweep --resumes from the last chunk, not from zero (huge
        # runs defer to their own host-condensation pass below).
        # Each main-thread stall on the ingest iterator lands as a
        # "parse" phase span in the sweep tracer (bench semantics).
        for chunk in _parse_timed(ingest.iter_encode_chunks(
                run_dirs, checker=checker, processes=sweep_procs)):
            dense, dense_map = [], []
            for d, enc in chunk:
                if not encodable(d, enc, fallback):
                    continue
                if enc.n > parallel.DENSE_TXN_LIMIT:
                    # too long for the dense [T,T] closure: SCC
                    # condensation (the 100k-op path), after the sweep
                    huge.append(enc)
                    huge_map.append(d)
                elif host_only:
                    worst = max(worst, emit_append(
                        d, enc, elle.cycle_anomalies_cpu(enc)))
                else:
                    dense.append(enc)
                    dense_map.append(d)
            if dense:
                souts: list | None = [] if want_stats else None
                cycles_per = parallel.check_bucketed(
                    dense, get_mesh(), stats_out=souts)
                for i, (d, enc, cycles) in enumerate(
                        zip(dense_map, dense, cycles_per)):
                    worst = max(worst, emit_append(d, enc, cycles))
                    if souts is not None:
                        record_stats(d, "append", souts[i], cycles)
        for d, enc in zip(huge_map, huge):
            shuge: list | None = [] if want_stats else None
            try:
                if host_only:
                    cycles = elle.cycle_anomalies_cpu(enc)
                else:
                    # mesh=None: these are all past the dense limit, so
                    # check_long_history goes host-condensation; None
                    # just lets the per-SCC classify stage use
                    # default_devices() (the dp batch mesh would be
                    # wrong for B=1 anyway)
                    cycles = parallel.check_long_history(
                        enc, None, dense_limit=parallel.DENSE_TXN_LIMIT,
                        stats_out=shuge)
            except Exception as e:
                # one monster history must fail alone, not take the
                # whole sweep's remaining verdicts with it
                worst = max(worst, _quarantine_run(
                    d, e, "check", checker, journal=journal))
                continue
            worst = max(worst, emit_append(d, enc, cycles))
            if shuge:
                record_stats(d, "append", shuge[0], cycles)
        for d in fallback:
            worst = max(worst, _stored_fallback(d, stored_check,
                                                checker,
                                                journal=journal))
        return worst

    # wr: edge lists host-built; bucketed device dispatches — the same
    # streaming pipeline as the append sweep (chunked device work
    # overlaps pool parsing of the next chunk).
    prohibited = elle_wr.WrChecker().prohibited
    fallback = []
    for chunk in _parse_timed(ingest.iter_encode_chunks(
            run_dirs, checker=checker, processes=sweep_procs)):
        good = [(d, enc) for d, enc in chunk
                if encodable(d, enc, fallback)]
        if not good:
            continue
        wr_stats: list | None = [] if want_stats else None
        if host_only:
            cycles_per = [elle_wr.cycle_anomalies_cpu(e)
                          for _d, e in good]
        else:
            cycles_per = _wr_chunk_with_backdown(
                good, elle_kernels, elle_wr, stats_out=wr_stats)
        # emit per chunk: verdicts persist incrementally (an
        # interrupted sweep --resumes from the last chunk, not from
        # zero) and encodings free as we go
        for i, ((d, enc), cycles) in enumerate(zip(good, cycles_per)):
            if hasattr(cycles, "verdict"):   # supervisor.Quarantined
                worst = max(worst, emit(d, cycles.verdict("wr")))
                continue
            res = elle_wr.render_wr_verdict(enc, cycles, prohibited)
            res["checker"] = "wr"       # --resume marker
            worst = max(worst, emit(d, res))
            if wr_stats is not None and i < len(wr_stats):
                record_stats(d, "wr", wr_stats[i], cycles)

    for d in fallback:
        worst = max(worst, _stored_fallback(d, stored_check, checker,
                                            journal=journal))
    return worst


def _wr_chunk_with_backdown(good, elle_kernels, elle_wr,
                            stats_out: list | None = None):
    """One wr chunk's device dispatch with the supervisor's OOM and
    watchdog degradation: the bucketed batch first; on
    RESOURCE_EXHAUSTED (or a watchdog timeout) the chunk re-checks one
    history at a time (the wr dispatcher has no incremental split, so
    singletons ARE the backdown floor), and a history that still fails
    alone quarantines. Two CONSECUTIVE singleton watchdog timeouts mean
    the device is wedged, not the data: the chunk's remainder
    quarantines without re-probing. Other errors (and strict mode)
    re-raise — fail-fast exactly as before.

    `stats_out` (a list) is extended with one kernel-stats dict per
    history in chunk order (None for quarantined histories) — only
    on completion, so a re-raised failure leaves it untouched."""
    from . import supervisor

    def recoverable(e) -> bool:
        return not supervisor.strict_enabled() and (
            supervisor.is_oom_error(e)
            or isinstance(e, supervisor.WatchdogTimeout))

    edges = [elle_wr.to_edge_dict(e) for _d, e in good]
    tr = trace.get_current()
    # the stats kwarg is passed ONLY when requested: the supervisor
    # tests drive this ladder through duck-typed fake kernels whose
    # stats-free signature must keep working
    try:
        if stats_out is not None:
            batch_stats: list = []
            res = elle_kernels.check_edge_batch_bucketed(
                edges, stats_out=batch_stats)
            stats_out.extend(batch_stats)
        else:
            res = elle_kernels.check_edge_batch_bucketed(edges)
        return res
    except Exception as e:
        if not recoverable(e):
            raise
        if supervisor.is_oom_error(e):
            # watchdog batch failures are already counted inside the
            # bounded wait; oom_retries must mean real OOMs so the
            # bench's robustness block can tell the two causes apart
            tr.counter("oom_retries").inc()
    out = []
    souts: list | None = [] if stats_out is not None else None
    wedged = 0
    for ed in edges:
        if wedged >= 2:
            # two consecutive singleton watchdog timeouts: the device
            # is wedged, not the data — quarantine the remainder
            # instead of burning 2x the timeout (and two abandoned
            # waiter threads) per history on a dead runtime
            with tr.span("quarantine", stage="watchdog", histories=1):
                tr.counter("quarantined").inc()
            from . import obs
            obs.emit("quarantine", stage="watchdog", histories=1,
                     cause="device wedged")
            out.append(supervisor.Quarantined(
                "watchdog", "device wedged: consecutive singleton "
                "watchdog timeouts"))
            if souts is not None:
                souts.append(None)
            continue
        try:
            if souts is not None:
                s1: list = []
                out.append(elle_kernels.check_edge_batch_bucketed(
                    [ed], stats_out=s1)[0])
                souts.append(s1[0] if s1 else None)
            else:
                out.append(
                    elle_kernels.check_edge_batch_bucketed([ed])[0])
            wedged = 0
        except Exception as e:
            if not recoverable(e):
                raise
            if isinstance(e, supervisor.WatchdogTimeout):
                stage = "watchdog"
                wedged += 1
            else:
                stage = "oom"
            with tr.span("quarantine", stage=stage, histories=1):
                tr.counter("quarantined").inc()
            from . import obs
            obs.emit("quarantine", stage=stage, histories=1,
                     cause=repr(e)[:300])
            out.append(supervisor.Quarantined(stage, repr(e)))
            if souts is not None:
                souts.append(None)
    if stats_out is not None:
        stats_out.extend(souts)
    return out


def _parse_timed(it):
    """Re-yield an iterator, recording each main-thread stall on it as
    a "parse" phase span in the current tracer — analyze-store sweeps
    get the same parse/pack/h2d/dispatch/collect attribution as the
    bench's north-star loop."""
    import time

    it = iter(it)
    while True:
        t0 = time.perf_counter()
        chunk = next(it, None)
        trace.get_current().phase("parse", t0)
        if chunk is None:
            return
        yield chunk


def _verdicted(d, checker: str) -> bool:
    """Did a prior sweep of THIS checker fully verdict this run? Every
    completed verdict leaves an additive `.sweep-<checker>` sidecar
    (so alternating sweeps never erase each other's progress); a
    parseable results.json naming the checker counts too."""
    if (d / f".sweep-{checker}").exists():
        return True
    p = d / "results.json"
    if not p.exists() or checker == "stored":
        return False  # stored sweeps mark ONLY via the sidecar: the
        #               run's own results.json predates the sweep
    try:
        return json.loads(p.read_text()).get("checker") == checker
    except (OSError, json.JSONDecodeError):
        return False  # truncated marker: redo the run


def _prior_code(d, checker: str | None = None) -> int:
    """Exit-code contribution of an already-verdicted (skipped) run.
    THIS sweep's sidecar is consulted first: results.json is whichever
    checker wrote it last, so a later sweep by a different checker
    would mask this checker's recorded validity (and stored-fallback
    runs never write results.json at all) — an invalid verdict from
    the completed part of an interrupted sweep must not read as
    success. Legacy empty sidecars fall through to results.json."""
    if checker is not None:
        try:
            return validity_exit_code(
                json.loads((d / f".sweep-{checker}").read_text()))
        except (OSError, json.JSONDecodeError, ValueError):
            pass
    try:
        return validity_exit_code(
            json.loads((d / "results.json").read_text()))
    except (OSError, json.JSONDecodeError):
        return 0  # legacy empty sidecar: validity was reported when run


def _write_results(d, res: dict, checker: str | None = None,
                   journal=None, persist: bool = True) -> int:
    """Persist results.json/.edn into a run dir and print the one-line
    summary; returns the validity exit code. results.json lands via
    per-process temp-file + atomic rename (multi-host sweeps over a
    shared store race benignly — identical content, last writer wins),
    then the additive `.sweep-<checker>` sidecar marks the run done
    for --resume, and the sweep's verdicts.jsonl journal (when one is
    threaded through) gets its per-history append. persist=False skips
    the results.json/.edn write (sidecar/journal/summary only) so the
    stored-fallback's failure path can't clobber a run's original
    test-time results — its success path never writes them either."""
    import os as _os
    from . import edn as edn_mod
    from .store import _results_to_edn
    if persist:
        (d / "results.edn").write_text(
            edn_mod.dumps(_results_to_edn(_json_safe(res))) + "\n")
        tmp = d / f"results.json.tmp.{_os.getpid()}"
        tmp.write_text(json.dumps(_json_safe(res), indent=2))
        _os.replace(tmp, d / "results.json")
    if checker is not None:
        (d / f".sweep-{checker}").write_text(
            json.dumps({"valid?": res.get("valid?")}))
    if journal is not None and checker is not None:
        journal.record(d, checker, res)
    trace.get_current().counter("runs_verdicted").inc()
    line = {"dir": str(d), "valid?": res.get("valid?")}
    if "anomaly-types" in res:
        line["anomalies"] = res.get("anomaly-types", [])
    if "failures" in res:
        line["failures"] = res["failures"]
    if "quarantined" in res:
        line["quarantined"] = res["quarantined"]
        line["error"] = res.get("error")
    print(json.dumps(line))
    return validity_exit_code(res)


def _quarantine_run(d, err, stage: str, checker: str | None = None,
                    journal=None, persist: bool = True) -> int:
    """Record a run the sweep abandoned as a `valid? unknown` verdict —
    never a false verdict, never a dead sweep (Elle's degradation
    contract) — persisting the cause for triage and journaling it so
    --resume doesn't grind over the same broken run forever.
    JEPSEN_TPU_STRICT=1 re-raises instead (the old fail-fast)."""
    from . import supervisor
    if supervisor.strict_enabled():
        if isinstance(err, BaseException):
            raise err
        raise RuntimeError(str(err))
    tr = trace.get_current()
    with tr.span("quarantine", stage=stage):
        tr.counter("quarantined").inc()
    from . import obs
    obs.emit("quarantine", stage=stage, run=str(d),
             cause=str(err)[:300])
    log.warning("quarantining %s (%s): %s", d, stage, err)
    return _write_results(
        d, supervisor.quarantine_verdict(err, stage, checker), checker,
        journal=journal, persist=persist)


def _stored_fallback(d, stored_check, checker: str | None = None,
                     journal=None) -> int:
    """Run a dir through its own stored checker, quarantining (an
    `unknown` verdict, never an exception, never a dead sweep) on
    failure. With `checker`, a success leaves the `.sweep-<checker>`
    sidecar so --resume counts the run done for that sweep."""
    try:
        res = stored_check(d)
    except Exception as e:
        # never clobber an existing test-time results.json — the
        # stored path's success leaves it untouched too, and a
        # transient failure must not replace a recorded verdict with
        # an unknown. A run dir without one records the quarantine so
        # triage has something to read.
        return _quarantine_run(
            d, e, "stored", checker, journal=journal,
            persist=not (d / "results.json").exists())
    print(json.dumps({"dir": str(d), "valid?": res.get("valid?")}))
    trace.get_current().counter("runs_verdicted").inc()
    if checker is not None:
        # record the validity: the fallback may not write a
        # results.json, and --resume must reproduce this run's
        # exit-code contribution from the sidecar alone
        (d / f".sweep-{checker}").write_text(
            json.dumps({"valid?": res.get("valid?")}))
    if journal is not None and checker is not None:
        journal.record(d, checker, res)
    return validity_exit_code(res)


def _analyze_store_register(store: Store, run_dirs: list,
                            stored_check, journal=None) -> int:
    """Per-key CAS-register linearizability over a whole store: every
    key's subhistory from EVERY run goes down in one tiered device
    sweep (dense grid -> bounded frontier -> CPU re-run), then verdicts
    regroup per run — the etcd-shaped batch sweep of BASELINE config
    #1. Runs whose client ops aren't register-shaped fall back to
    their own stored checker."""
    from . import independent, ingest
    from .checker import linearizable, merge_valid, models

    # auto resolves to the device kernels when an accelerator is
    # reachable and honors the --backend env export either way
    c = linearizable(models.cas_register(), backend="auto")

    subs: list[list] = []          # flattened subhistories
    owners: list[tuple[int, object]] = []   # (run index, key)
    fallback: list[int] = []
    for i, (d, hist) in enumerate(
            zip(run_dirs, ingest.parallel_load(run_dirs))):
        if isinstance(hist, Exception):
            fallback.append(i)
            continue
        hist = independent.relift_history(hist)
        client_fs = {o.get("f") for o in hist
                     if o.get("process") != "nemesis"
                     and o.get("f") is not None}
        if not client_fs or not client_fs <= {"read", "write", "cas"}:
            fallback.append(i)
            continue
        by_key = independent.subhistories(hist)   # one pass, all keys
        ks = list(by_key)
        # a plain cas value is [old new] (scalars); a LIFTED cas value
        # is [key [old new]] — second element a list marks it lifted
        if not ks and any(
                isinstance(o.get("value"), (list, tuple))
                and len(o["value"]) == 2
                and (o.get("f") != "cas"
                     or isinstance(o["value"][1], (list, tuple)))
                for o in hist if o.get("process") != "nemesis"):
            # looks lifted ([k v] values) but relift declined (e.g. no
            # ok read survived the faults): checking it as ONE register
            # would feed the oracle [key value] pairs — let the run's
            # own stored checker handle it instead
            fallback.append(i)
            continue
        for k in (ks or [None]):
            subs.append(by_key[k] if ks else hist)
            owners.append((i, k))

    from .obs import search as search_obs
    ksouts: list | None = [] if search_obs.enabled() else None
    try:
        results = c.check_batch({}, subs, {}, stats_out=ksouts) \
            if subs else []
    except Exception:
        # one malformed run must not sink the sweep: re-dispatch each
        # subhistory in isolation, degrading only the broken ones
        log.warning("batched register sweep failed; isolating per key",
                    exc_info=True)
        results = []
        ksouts = None   # isolation retries run telemetry-free
        for s in subs:
            try:
                results.append(c.check_batch({}, [s], {})[0])
            except Exception as e:
                results.append({"valid?": "unknown",
                                "error": repr(e)[:200]})
    per_run: dict[int, dict] = {}
    per_run_stats: dict[int, list] = {}
    for j, ((i, k), res) in enumerate(zip(owners, results)):
        per_run.setdefault(i, {})[k] = res
        if ksouts is not None:
            per_run_stats.setdefault(i, []).append(
                (k, len(subs[j]), ksouts[j]))

    worst = 0
    for i, d in enumerate(run_dirs):
        if i in fallback:
            worst = max(worst,
                        _stored_fallback(d, stored_check, "register",
                                         journal=journal))
            continue
        keyed = per_run.get(i, {})
        valid = merge_valid([r.get("valid?", True)
                             for r in keyed.values()] or [True])
        res = {"valid?": valid,
               "checker": "register",       # --resume marker
               "key-count": len(keyed),
               "results": {str(k): r for k, r in keyed.items()},
               "failures": sorted(str(k) for k, r in keyed.items()
                                  if r.get("valid?") is False)}
        worst = max(worst, _write_results(d, res, "register",
                                          journal=journal))
        if ksouts is not None and i in per_run_stats:
            rel = journal.rel if journal is not None else str
            search_obs.record(
                rel(d), "register",
                _register_run_stats(per_run_stats[i]),
                anomalies=res["failures"] or None)
    return worst


def _register_run_stats(keyed: list) -> dict | None:
    """One run's register-sweep search record: the per-key subhistory
    sizes the native split produced (the WGL cost driver) plus the
    engines' own counters aggregated across keys — summed where the
    quantity is additive (configs, backtracks, rounds), maxed where it
    is a peak (frontier width, depth)."""
    sizes = [n for _k, n, _s in keyed]
    stats = [s for _k, _n, s in keyed if isinstance(s, dict)]
    if not sizes:
        return None
    out: dict = {
        "keys": len(sizes),
        "subhistory_ops": {"min": min(sizes), "max": max(sizes),
                           "mean": round(sum(sizes) / len(sizes), 2)},
        "engines": sorted({s.get("engine") for s in stats
                           if s.get("engine")}),
    }
    for f in ("configs", "backtracks", "rounds"):
        vals = [s[f] for s in stats if isinstance(s.get(f), int)]
        if vals:
            out[f] = sum(vals)
    for f in ("frontier_peak", "max_depth"):
        vals = [s[f] for s in stats if isinstance(s.get(f), int)]
        if vals:
            out[f] = max(vals)
    return out


def _json_safe(v):
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


if __name__ == "__main__":
    # `python -m jepsen_tpu.cli analyze-store ...` — the suite-agnostic
    # entry (test/analyze with no suite run a noop test map).
    sys.exit(run_cli(lambda tmap, args: tmap))
