"""Composable nemesis packages: nemesis + generators in one bundle.

Counterpart of jepsen.nemesis.combined
(jepsen/src/jepsen/nemesis/combined.clj): a *package* is a dict

    {"nemesis":          the fault injector
     "generator":        op generator for the main phase
     "final_generator":  ops to run at the end (heal/restart everything)
     "perf":             {"name","start","stop"} fs for plot shading}

`nemesis_package(db=..., faults={"partition","kill","pause","clock"},
interval=10)` builds the standard kitchen-sink package
(combined.clj:318-364, default interval combined.clj:26-28).
"""

from __future__ import annotations

import random
from typing import Iterable

from .. import control, db as jdb, generator as gen
from ..control import util as cutil
from ..util import majority
from . import Nemesis, Partitioner, bisect, complete_grudge, compose, \
    majorities_ring, split_one
from .clock import ClockNemesis, clock_gen

DEFAULT_INTERVAL = 10  # seconds between fault ops (combined.clj:26-28)


def db_nodes(test: dict, db, spec) -> list[str]:
    """Interpret a node spec: "one" | "minority" | "majority" | "all" |
    "primaries" | a list of nodes (combined.clj:30-50)."""
    nodes = list(test.get("nodes", []))
    if spec == "one":
        return [random.choice(nodes)]
    if spec == "minority":
        k = max(1, majority(len(nodes)) - 1)
        return random.sample(nodes, k)
    if spec == "majority":
        return random.sample(nodes, majority(len(nodes)))
    if spec == "all":
        return nodes
    if spec == "primaries":
        if isinstance(db, jdb.Primary):
            return list(db.primaries(test)) or [nodes[0]]
        return [nodes[0]]
    return list(spec)


class DBNemesis(Nemesis):
    """Kills/restarts and pauses/resumes DB processes via the DB's
    Process/Pause protocols (combined.clj:59-87)."""

    fs = frozenset({"start-kill", "stop-kill", "start-pause", "stop-pause"})

    def __init__(self, db, fs: frozenset | None = None):
        self.db = db
        if fs is not None:
            self.fs = fs  # restrict routing (e.g. kill-only package)

    def invoke(self, test, op):
        f = op.get("f")
        spec = op.get("value", "one")
        if f == "start-kill":
            targets = db_nodes(test, self.db, spec)
            res = control.on_nodes(
                test, lambda t, n: self.db.kill(t, n) or "killed", targets)
        elif f == "stop-kill":
            res = control.on_nodes(
                test, lambda t, n: self.db.start(t, n) or "started")
        elif f == "start-pause":
            targets = db_nodes(test, self.db, spec)
            res = control.on_nodes(
                test, lambda t, n: self.db.pause(t, n) or "paused", targets)
        elif f == "stop-pause":
            res = control.on_nodes(
                test, lambda t, n: self.db.resume(t, n) or "resumed")
        else:
            raise ValueError(f"unknown db nemesis op {op!r}")
        return {**op, "type": "info", "value": dict(res)}


def _cycle_gen(start_f, start_value_fn, stop_f, interval):
    """start, wait, stop, wait, ... — built from pure combinators
    (a stateful closure here would misfire: generators are asked for ops
    speculatively, so impure state must live in generator structure)."""

    def start(test, ctx):
        return {"type": "info", "f": start_f, "value": start_value_fn(test)}

    stop = {"type": "info", "f": stop_f, "value": None}
    return gen.stagger(interval, gen.flip_flop(
        gen.repeat_gen(start), gen.repeat_gen(stop)))


def partition_package(db=None, interval: float = DEFAULT_INTERVAL,
                      targets: Iterable[str] = ("one", "majority",
                                                "majorities-ring")) -> dict:
    """Partitions package (combined.clj:217-241)."""
    targets = list(targets)

    def grudge(test):
        nodes = list(test.get("nodes", []))
        t = random.choice(targets)
        if t == "one":
            return complete_grudge(split_one(nodes))
        if t == "majority":
            shuffled = random.sample(nodes, len(nodes))
            return complete_grudge(bisect(shuffled))
        if t == "majorities-ring":
            return majorities_ring(nodes)
        if t == "primaries" and db is not None and \
                isinstance(db, jdb.Primary):
            prim = db.primaries(test) or nodes[:1]
            return complete_grudge(split_one(nodes, prim[0]))
        return complete_grudge(bisect(nodes))

    # Route the package's outer fs to the partitioner's start/stop, so
    # the nemesis is usable standalone as well as via compose_packages.
    nemesis = compose({_freeze_router({"start-partition": "start",
                                       "stop-partition": "stop"}):
                       Partitioner(None)})
    return {
        "nemesis": nemesis,
        "generator": _cycle_gen("start-partition", grudge, "stop-partition",
                                interval),
        "final_generator": gen.once({"type": "info", "f": "stop-partition",
                                     "value": None}),
        "perf": {"name": "partition", "start": {"start-partition"},
                 "stop": {"stop-partition"}},
    }


def kill_package(db, interval: float = DEFAULT_INTERVAL,
                 targets=("one", "majority", "all")) -> dict:
    def value(test):
        return random.choice(list(targets))

    return {
        "nemesis": DBNemesis(db, fs=frozenset({"start-kill", "stop-kill"})),
        "generator": _cycle_gen("start-kill", value, "stop-kill", interval),
        "final_generator": gen.once({"type": "info", "f": "stop-kill",
                                     "value": None}),
        "perf": {"name": "kill", "start": {"start-kill"},
                 "stop": {"stop-kill"}},
    }


def pause_package(db, interval: float = DEFAULT_INTERVAL,
                  targets=("one", "majority", "all")) -> dict:
    def value(test):
        return random.choice(list(targets))

    return {
        "nemesis": DBNemesis(db, fs=frozenset({"start-pause", "stop-pause"})),
        "generator": _cycle_gen("start-pause", value, "stop-pause", interval),
        "final_generator": gen.once({"type": "info", "f": "stop-pause",
                                     "value": None}),
        "perf": {"name": "pause", "start": {"start-pause"},
                 "stop": {"stop-pause"}},
    }


def clock_package(db=None, interval: float = DEFAULT_INTERVAL) -> dict:
    """Clock faults package (combined.clj:243-292)."""
    return {
        "nemesis": ClockNemesis(),
        "generator": gen.stagger(interval, clock_gen()),
        "final_generator": gen.once({"type": "info", "f": "reset",
                                     "value": None}),
        "perf": {"name": "clock", "start": {"bump", "strobe"},
                 "stop": {"reset"}},
    }


def compose_packages(packages: list[dict]) -> dict:
    """Merge packages: nemeses composed by f-routing, generators merged
    with `any`, final generators run in sequence (combined.clj:294-316)."""
    routes = {}
    claimed: dict = {}
    for p in packages:
        nem = p["nemesis"]
        fs = frozenset(nem.fs)
        for f in fs:
            if f in claimed:
                raise ValueError(
                    f"nemesis op {f!r} routed to two packages "
                    f"({claimed[f]!r} and {nem!r}); packages must have "
                    f"disjoint :f sets")
            claimed[f] = nem
        routes[fs] = nem
    return {
        "nemesis": compose(routes),
        "generator": gen.any_gen(*[p["generator"] for p in packages]),
        "final_generator": [p["final_generator"] for p in packages
                            if p.get("final_generator") is not None],
        "perf": [p["perf"] for p in packages],
    }


class _FrozenDictRouter(dict):
    def __hash__(self):
        return hash(frozenset(self.items()))


def _freeze_router(router):
    if isinstance(router, dict):
        return _FrozenDictRouter(router)
    return frozenset(router)


def nemesis_package(db=None, interval: float = DEFAULT_INTERVAL,
                    faults: Iterable[str] = ("partition", "kill", "pause",
                                             "clock"),
                    partition_targets=("one", "majority",
                                       "majorities-ring")) -> dict:
    """The standard fault bundle (combined.clj:318-364). Only faults the
    DB supports are included."""
    faults = set(faults)
    packages = []
    if "partition" in faults:
        packages.append(partition_package(db, interval, partition_targets))
    if "kill" in faults and isinstance(db, jdb.Process):
        packages.append(kill_package(db, interval))
    if "pause" in faults and isinstance(db, jdb.Pause):
        packages.append(pause_package(db, interval))
    if "clock" in faults:
        packages.append(clock_package(db, interval))
    if not packages:
        from . import noop
        return {"nemesis": noop(), "generator": None,
                "final_generator": None, "perf": []}
    return compose_packages(packages)
